"""Fleet-fused device dispatch — F clusters' windows, ONE launch (ISSUE 20).

PR 19's facade runs F independent per-cluster stacks, but every cluster
still pays its own h2d + dispatch + d2h per window: at F=4 under a 40 ms
device tunnel the fleet fires 4 round-trips where the silicon could
absorb one. PR 18 proved the fix offline — `arm_stacked_fifo_pack` vmaps
M same-shaped windows into one `[M, N, 3]` dispatch with byte-identical
per-arm results, staged through the solver's deferred-dispatch lane
(`solver._dispatch_lane`). This module promotes that machinery into a
first-class serving path:

  * Each cluster's worker thread, on a pipelined XLA window dispatch,
    DEFERS its staged window here instead of launching it (the same
    `WindowHandle.blob_future` / deferred-blob contract the sweep rides).
  * The deferring thread then waits a short GATHER window
    (`fleet.stack-window-ms`) for the other live clusters' windows to
    arrive. The fleet has no lockstep barrier, so the gather is the
    synchronization point: whoever completes the set (or times out
    first) claims everything pending and flushes.
  * A flush groups windows by SHAPE BUCKET — `(bucket_n, emax, zones,
    mask signature)`. Clusters differ in node count and queue depth, so
    unlike the sweep's exact-digest match, members only need compatible
    padded shapes: the node axis is already power-of-two bucketed per
    cluster (`models/cluster.pad_bucket`), and app rows re-pad up to the
    group max (`ops/batched.pad_app_batch` — pad-invariant by the PR 18
    pinning). Each group launches as ONE
    `ops/batched.bucket_stacked_fifo_pack` dispatch + ONE fetch, and
    per-member blobs/avail scatter back to each cluster's handle.
  * Singleton groups and timeout-expired stragglers fall back to the
    normal per-cluster `_window_blob_donated` solve — counted, never
    blocking. A killed cluster's in-flight deferred window is expelled
    the same way (`forced_resolves`), so survivors' stacks flush clean.

Byte-identity per cluster is preserved BY CONSTRUCTION (vmap lanes are
independent; each sees only its own cluster's availability, statics, and
masks) and re-asserted end-to-end by `verify_cluster_equivalence`, whose
standalone replay runs unstacked.

Row-bucket policy: deferred windows bucket app rows at quantum 8 (the
sweep's policy — under vmap padding rows EXECUTE, so tight buckets are
pure win); windows that do NOT defer (stacking off, <2 live clusters,
pruned/pooled/Pallas paths) keep the serving quantum 32 untouched —
pinned by tests/test_fleet_dispatch.py.
"""

from __future__ import annotations

import threading
import time

import numpy as np

# How long a claimed-but-unresolved waiter sleeps between wake-up checks
# (its group is being solved by another cluster's thread; the solve ends
# with a notify_all, so this is only a lost-wakeup backstop).
_CLAIMED_POLL_S = 0.05


class _FleetBlobFuture:
    """Future protocol (`result`/`done`/`cancel`) for a deferred fleet
    window blob. Unlike the sweep's future — resolved by the lockstep
    driver's explicit flush — `result()` IS the gather: the owning
    cluster thread parks here until its group flushes (by count, by its
    deadline, or by drain/expel), and flushes it itself if it is the one
    that completes the set or times out first."""

    __slots__ = ("_coord", "payload", "_value", "_exc", "_done")

    def __init__(self, coord):
        self._coord = coord
        self.payload = None
        self._value = None
        self._exc = None
        self._done = False

    def _set(self, value) -> None:
        self._value = value
        self._done = True

    def _set_exception(self, exc) -> None:
        self._exc = exc
        self._done = True

    def result(self, timeout=None):
        if not self._done:
            self._coord._gather_and_flush(self.payload)
        if self._exc is not None:
            raise self._exc
        # Patch the owner's pipeline carry HERE, on the owning cluster
        # thread. A flusher-side patch would race the dispatch epilogue:
        # the solver parks the deferral marker in its pipe AFTER
        # defer_window returns, so a flush completing in that gap (on
        # another cluster's thread) would patch a not-yet-marked pipe,
        # get skipped by the identity guard, and strand the marker.
        # result() always runs after the marker is parked — fetch follows
        # dispatch on the same worker thread.
        self._coord._patch(self.payload)
        return self._value

    def done(self) -> bool:
        return self._done

    def cancel(self) -> bool:
        return False


class _DeferredBlob:
    """Dispatch-time stand-in for the decision blob; the solver wires
    `sweep_future` as the handle's blob_future (the lane contract shared
    with replay/sweep.py). Nothing ever treats it as an array."""

    __slots__ = ("sweep_future",)

    def __init__(self, future):
        self.sweep_future = future


class _DeferredAvail:
    """Stand-in for `available_after`, parked in the solver's pipeline
    carry until the flush patches the real per-member slice in. Its
    identity doubles as the patch guard."""

    __slots__ = ()


class _Payload:
    """One cluster's deferred window: everything a flush needs to solve
    it (stacked or singly) and patch that cluster's pipeline."""

    __slots__ = (
        "solver", "apps", "avail", "statics", "fill", "emax",
        "num_zones", "future", "marker", "deferred_at", "deadline",
        "order", "claimed", "avail_after",
    )

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)
        self.claimed = False
        self.avail_after = None

    def bucket_key(self):
        """Windows stack iff their PADDED shapes are compatible: same
        bucketed node axis, same executor-slot padding, same zone bound,
        and the same optional-mask signature (serving windows always
        carry all masks; the signature guards hypothetical callers).
        App-row counts may differ — the flush re-pads to the group max."""
        return (
            int(self.avail.shape[0]),
            self.emax,
            self.num_zones,
            tuple(f is not None for f in self.apps),
        )


class FleetDispatchCoordinator:
    """The fleet's deferred-dispatch lane (`solver._dispatch_lane` on
    every cluster stack when `fleet.stack-window-ms` > 0).

    Threading model: each cluster's single worker thread defers at most
    one window at a time (serving is dispatch-then-fetch per predicate),
    then blocks in `result()` until its window resolves. All bookkeeping
    runs under one condition variable; device work (the stacked solve or
    a fallback single) runs OUTSIDE the lock on whichever cluster thread
    claimed the batch, while the other owners wait — exactly one solve
    in flight per claimed batch, and an owner's pipeline is only patched
    while that owner is parked, so no pipeline is ever raced."""

    # Lane protocol: deferred windows bucket app rows like sweep lanes
    # (see module docstring); non-deferred serving windows keep 32.
    row_bucket_quantum = 8

    def __init__(
        self,
        window_ms: float,
        expected: int,
        *,
        telemetry=None,
        clock=time.monotonic,
    ):
        self.window_s = max(0.0, float(window_ms)) / 1e3
        self.telemetry = telemetry
        self._clock = clock
        self._cond = threading.Condition()
        self._pending: list[_Payload] = []
        self._expected = max(1, int(expected))
        self._draining = False
        self._seq = 0
        self.stats = {
            "stacked_dispatches": 0,
            "stack_arms": 0,
            "fallbacks": 0,  # singleton groups + straggler timeouts
            "forced_resolves": 0,  # expel / early fetch / stale overwrite
            "flushes": 0,
            "deferred": 0,
            "gather_wait_ms": 0.0,
        }

    # -- lane protocol (called from PlacementSolver.pack_window_dispatch) ----

    def accepts(self, solver) -> bool:
        """Defer only when a stacking partner can exist: at least two
        clusters live and not draining. Declined windows take the normal
        serving path untouched (quantum 32, immediate launch)."""
        with self._cond:
            return self._expected >= 2 and not self._draining

    def defer_window(
        self, solver, apps, *, avail, statics, host, fill, emax, num_zones
    ):
        fut = _FleetBlobFuture(self)
        now = self._clock()
        payload = _Payload(
            solver=solver, apps=apps, avail=avail, statics=statics,
            fill=fill, emax=emax, num_zones=num_zones,
            future=fut, marker=_DeferredAvail(),
            deferred_at=now, deadline=now + self.window_s,
        )
        fut.payload = payload
        stale = None
        with self._cond:
            # Defensive: serving is synchronous dispatch-then-fetch, so a
            # solver can never have two windows parked — but if a future
            # async path ever dispatches ahead, resolve the old window
            # singly rather than stacking two windows of one pipeline.
            for pl in self._pending:
                if pl.solver is solver:
                    stale = pl
                    break
            if stale is not None:
                self._pending.remove(stale)
                stale.claimed = True
            self._seq += 1
            payload.order = self._seq
            self._pending.append(payload)
            self.stats["deferred"] += 1
            self._cond.notify_all()
        if stale is not None:
            self._resolve_forced(stale)
        return _DeferredBlob(fut), payload.marker

    # -- gather --------------------------------------------------------------

    def _gather_and_flush(self, payload: _Payload) -> None:
        """Park the owning cluster thread until `payload` resolves; claim
        and flush the pending set when this thread completes it, hits its
        own deadline, or the coordinator is draining."""
        fut = payload.future
        batch = None
        timed_out = False
        with self._cond:
            while True:
                if fut._done:
                    return
                if payload.claimed:
                    # Another cluster's thread is solving our group right
                    # now; its notify_all wakes us.
                    self._cond.wait(timeout=_CLAIMED_POLL_S)
                    continue
                now = self._clock()
                full = len(self._pending) >= self._expected
                timed_out = now >= payload.deadline
                if full or timed_out or self._draining:
                    batch = [pl for pl in self._pending if not pl.claimed]
                    for pl in batch:
                        pl.claimed = True
                    self._pending = [
                        pl for pl in self._pending if pl not in batch
                    ]
                    break
                self._cond.wait(
                    timeout=max(1e-4, payload.deadline - now)
                )
        self._flush(batch, timed_out=timed_out and not full)

    # -- flush ---------------------------------------------------------------

    def _flush(self, batch: list[_Payload], *, timed_out: bool) -> None:
        now = self._clock()
        for pl in batch:
            wait_ms = max(0.0, now - pl.deferred_at) * 1e3
            self.stats["gather_wait_ms"] += wait_ms
            if self.telemetry is not None:
                self.telemetry.on_gather_wait(wait_ms)
        groups: dict = {}
        for pl in batch:
            groups.setdefault(pl.bucket_key(), []).append(pl)
        with self._cond:
            self.stats["flushes"] += 1
        for members in groups.values():
            if len(members) == 1:
                reason = "straggler-timeout" if timed_out else "singleton"
                with self._cond:
                    self.stats["fallbacks"] += 1
                if self.telemetry is not None:
                    self.telemetry.on_stack_fallback(reason)
                self._solve_guarded(self._solve_single, members)
            else:
                with self._cond:
                    self.stats["stacked_dispatches"] += 1
                    self.stats["stack_arms"] += len(members)
                if self.telemetry is not None:
                    self.telemetry.on_stacked_dispatch(len(members))
                self._solve_guarded(self._solve_stacked, members)

    def _solve_guarded(self, solve, members: list[_Payload]) -> None:
        """Run a solve, convert failures into per-member future
        exceptions (the fetch path's device-failure handling — pipeline
        drop + degraded policy — takes over in each owner), and ALWAYS
        wake the parked owners."""
        try:
            solve(members)
        except BaseException as exc:  # noqa: BLE001 - fanned out to owners
            for pl in members:
                if not pl.future._done:
                    pl.future._set_exception(exc)
        finally:
            with self._cond:
                self._cond.notify_all()

    def _patch(self, payload: _Payload) -> None:
        """Swap the solved `available_after` for the payload's marker in
        the owner's pipeline carry. Runs on the OWNER's thread (see
        _FleetBlobFuture.result); the identity guard keeps it idempotent
        and a no-op when the pipeline was dropped or rebuilt."""
        p = payload.solver._pipe
        if (
            payload.avail_after is not None
            and p is not None
            and p.get("avail") is payload.marker
        ):
            p["avail"] = payload.avail_after

    def _solve_single(self, members: list[_Payload]) -> None:
        import jax

        from spark_scheduler_tpu.core.solver import (
            _shim,
            _window_blob_donated,
        )

        (payload,) = members
        # The round-trip this window would have paid on the normal path.
        _shim("h2d")
        blob, avail_after = _window_blob_donated(
            payload.avail, payload.statics, payload.apps,
            fill=payload.fill, emax=payload.emax,
            num_zones=payload.num_zones,
        )
        payload.avail_after = avail_after
        _shim("d2h")
        payload.future._set(np.asarray(jax.device_get(blob)))

    def _solve_stacked(self, members: list[_Payload]) -> None:
        import jax
        import jax.numpy as jnp

        from spark_scheduler_tpu.core.solver import _shim
        from spark_scheduler_tpu.ops.batched import (
            bucket_stacked_fifo_pack,
            pad_app_batch,
            stack_app_batches,
        )

        # Equal fills adjacent (the kernel vmaps per same-fill
        # sub-stack); defer order breaks ties deterministically.
        members.sort(key=lambda pl: (pl.fill, pl.order))
        fills = tuple(pl.fill for pl in members)
        rows = max(pl.apps.driver_req.shape[0] for pl in members)
        apps = stack_app_batches(
            [pad_app_batch(pl.apps, rows) for pl in members]
        )
        statics = tuple(
            jnp.stack([pl.statics[i] for pl in members])
            for i in range(len(members[0].statics))
        )
        avail_stack = jnp.stack([pl.avail for pl in members])
        lead = members[0]
        # ONE simulated round-trip for the whole group — the fused
        # launch this module exists for.
        _shim("h2d")
        blob, avail_after = bucket_stacked_fifo_pack(
            avail_stack, statics, apps,
            fills=fills, emax=lead.emax, num_zones=lead.num_zones,
        )
        _shim("d2h")
        np_blob = np.asarray(jax.device_get(blob))
        for i, pl in enumerate(members):
            pl.avail_after = avail_after[i]
            # Slice back to the member's own row bucket so downstream
            # fetch decoding sees exactly the unstacked blob shape.
            pl.future._set(np_blob[i, : pl.apps.driver_req.shape[0]])

    def _resolve_forced(self, payload: _Payload) -> None:
        with self._cond:
            self.stats["forced_resolves"] += 1
        if self.telemetry is not None:
            self.telemetry.on_stack_fallback("forced")
        self._solve_guarded(self._solve_single, [payload])

    # -- membership / lifecycle ---------------------------------------------

    def set_expected(self, live: int) -> None:
        """Track live-cluster count (kill/rejoin): gathers complete at
        the live count, and below 2 live the lane stops accepting."""
        with self._cond:
            self._expected = max(1, int(live))
            self._cond.notify_all()

    def expel(self, solver) -> None:
        """A cluster was killed: resolve its parked window NOW via the
        single-window fallback so its worker unblocks and the survivors'
        gather no longer waits on a dead peer."""
        with self._cond:
            victim = None
            for pl in self._pending:
                if pl.solver is solver:
                    victim = pl
                    break
            if victim is not None:
                self._pending.remove(victim)
                victim.claimed = True
        if victim is not None:
            self._resolve_forced(victim)

    def drain(self) -> None:
        """Shutdown: stop accepting, release every parked owner (each
        claims and flushes immediately on wake)."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    # -- introspection --------------------------------------------------------

    def describe(self) -> dict:
        with self._cond:
            out = dict(self.stats)
            out.update(
                enabled=True,
                window_ms=self.window_s * 1e3,
                expected=self._expected,
                pending=len(self._pending),
                draining=self._draining,
            )
            out["gather_wait_ms"] = round(out["gather_wait_ms"], 3)
        return out
