"""Full CRD definitions with openAPI v3 schemas.

The reference ships complete CustomResourceDefinition manifests for both
CRD groups — served/storage version sets, structural openAPI validation,
status subresource, and the webhook conversion strategy
(vendor/.../apis/sparkscheduler/v1beta2/crd_resource_reservation.go:23-115,
vendor/.../apis/scaler/v1alpha2/crd_demand.go:15-195). These builders
produce the equivalent manifests as plain dicts; they are what
`ensure_resource_reservations_crd` registers, what the deployment
manifests in examples/ embed, and what the fake apiserver can validate
against.
"""

from __future__ import annotations

from typing import Any, Optional

RESERVATION_GROUP = "sparkscheduler.palantir.com"
DEMAND_GROUP = "scaler.palantir.com"
RESERVATION_CRD_NAME = f"resourcereservations.{RESERVATION_GROUP}"
DEMAND_CRD_NAME = f"demands.{DEMAND_GROUP}"

_QUANTITY = {
    # k8s resource.Quantity serializes as a string or (small ints) a number;
    # the reference schema uses x-kubernetes-int-or-string semantics.
    "x-kubernetes-int-or-string": True,
}

_RESOURCES_SCHEMA = {
    "type": "object",
    "properties": {
        "cpu": _QUANTITY,
        "memory": _QUANTITY,
        "nvidia.com/gpu": _QUANTITY,
    },
}


def _objectmeta_passthrough() -> dict:
    return {"type": "object"}


def resource_reservation_crd(webhook_url: Optional[str] = None,
                             ca_bundle: Optional[str] = None) -> dict:
    """The ResourceReservation CRD: v1beta2 is the storage version, v1beta1
    stays served for old clients, and a conversion webhook bridges them
    (crd_resource_reservation.go:83-115). `webhook_url` wires the conversion
    client config the way InitializeCRDConversionWebhook does in-process
    (internal/conversionwebhook/resource_reservation.go:46-84)."""
    v1beta2_schema = {
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "metadata": _objectmeta_passthrough(),
                "spec": {
                    "type": "object",
                    "required": ["reservations"],
                    "properties": {
                        "reservations": {
                            "type": "object",
                            "additionalProperties": {
                                "type": "object",
                                "required": ["node", "resources"],
                                "properties": {
                                    "node": {"type": "string"},
                                    "resources": _RESOURCES_SCHEMA,
                                },
                            },
                        },
                        # Gang priority class (policy subsystem); optional so
                        # pre-policy objects — and all objects written with
                        # the engine off — validate unchanged.
                        "priorityClass": {"type": "string"},
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "pods": {
                            "type": "object",
                            "additionalProperties": {"type": "string"},
                        }
                    },
                },
            },
        }
    }
    v1beta1_schema = {
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "metadata": _objectmeta_passthrough(),
                "spec": {
                    "type": "object",
                    "required": ["reservations"],
                    "properties": {
                        "reservations": {
                            "type": "object",
                            "additionalProperties": {
                                "type": "object",
                                "required": ["node", "cpu", "memory"],
                                "properties": {
                                    "node": {"type": "string"},
                                    "cpu": _QUANTITY,
                                    "memory": _QUANTITY,
                                },
                            },
                        }
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "pods": {
                            "type": "object",
                            "additionalProperties": {"type": "string"},
                        }
                    },
                },
            },
        }
    }
    conversion: dict[str, Any] = {"strategy": "None"}
    if webhook_url:
        conversion = {
            "strategy": "Webhook",
            "webhook": {
                "conversionReviewVersions": ["v1"],
                "clientConfig": {
                    "url": webhook_url,
                    **({"caBundle": ca_bundle} if ca_bundle else {}),
                },
            },
        }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": RESERVATION_CRD_NAME},
        "spec": {
            "group": RESERVATION_GROUP,
            "scope": "Namespaced",
            "names": {
                "kind": "ResourceReservation",
                "listKind": "ResourceReservationList",
                "plural": "resourcereservations",
                "singular": "resourcereservation",
                "shortNames": ["rr"],
            },
            "preserveUnknownFields": False,
            "conversion": conversion,
            "versions": [
                {
                    "name": "v1beta1",
                    "served": True,
                    "storage": False,
                    "schema": v1beta1_schema,
                },
                {
                    "name": "v1beta2",
                    "served": True,
                    "storage": True,
                    "schema": v1beta2_schema,
                },
            ],
        },
    }


def demand_crd() -> dict:
    """The Demand CRD (owned by the external autoscaler; the scheduler only
    consumes it): v1alpha2 storage with the status subresource and phase
    enum validation (crd_demand.go:15-195)."""
    unit_v1alpha2 = {
        "type": "object",
        "required": ["resources", "count"],
        "properties": {
            "resources": _RESOURCES_SCHEMA,
            "count": {"type": "integer", "minimum": 0},
            "pod-names-by-namespace": {
                "type": "object",
                "additionalProperties": {
                    "type": "array",
                    "items": {"type": "string"},
                },
            },
        },
    }
    v1alpha2_schema = {
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "metadata": _objectmeta_passthrough(),
                "spec": {
                    "type": "object",
                    "required": ["units", "instance-group"],
                    "properties": {
                        "units": {"type": "array", "items": unit_v1alpha2},
                        "instance-group": {"type": "string"},
                        "is-long-lived": {"type": "boolean"},
                        "enforce-single-zone-scheduling": {"type": "boolean"},
                        "zone": {"type": "string"},
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "phase": {
                            "type": "string",
                            # types_demand.go phases: empty/pending/fulfilled/
                            # cannot-fulfill
                            "enum": [
                                "",
                                "empty",
                                "pending",
                                "fulfilled",
                                "cannot-fulfill",
                            ],
                        },
                        "last-transition-time": {"type": "string"},
                        "fulfilled-zone": {"type": "string"},
                    },
                },
            },
        }
    }
    unit_v1alpha1 = {
        "type": "object",
        "required": ["count"],
        "properties": {
            "cpu": _QUANTITY,
            "memory": _QUANTITY,
            "gpu": _QUANTITY,
            "count": {"type": "integer", "minimum": 0},
        },
    }
    v1alpha1_schema = {
        "openAPIV3Schema": {
            "type": "object",
            "properties": {
                "metadata": _objectmeta_passthrough(),
                "spec": {
                    "type": "object",
                    "required": ["units", "instance-group"],
                    "properties": {
                        "units": {"type": "array", "items": unit_v1alpha1},
                        "instance-group": {"type": "string"},
                        "is-long-lived": {"type": "boolean"},
                    },
                },
                "status": {
                    "type": "object",
                    "properties": {
                        "phase": {"type": "string"},
                        "last-transition-time": {"type": "string"},
                    },
                },
            },
        }
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": DEMAND_CRD_NAME},
        "spec": {
            "group": DEMAND_GROUP,
            "scope": "Namespaced",
            "names": {
                "kind": "Demand",
                "listKind": "DemandList",
                "plural": "demands",
                "singular": "demand",
                "shortNames": ["dem"],
            },
            "preserveUnknownFields": False,
            "conversion": {"strategy": "None"},
            "versions": [
                {
                    "name": "v1alpha1",
                    "served": True,
                    "storage": False,
                    "schema": v1alpha1_schema,
                },
                {
                    "name": "v1alpha2",
                    "served": True,
                    "storage": True,
                    "schema": v1alpha2_schema,
                    "subresources": {"status": {}},
                },
            ],
        },
    }


def validate_against_schema(obj: dict, schema: dict, path: str = "$") -> list[str]:
    """Minimal structural openAPI v3 validator (type / required /
    properties / additionalProperties / items / enum / minimum /
    int-or-string) — enough to enforce the CRD schemas above the way the
    apiserver's structural validation would. Returns a list of violation
    strings (empty = valid)."""
    errors: list[str] = []
    if schema.get("x-kubernetes-int-or-string"):
        if not isinstance(obj, (int, float, str)):
            errors.append(f"{path}: expected int-or-string, got {type(obj).__name__}")
        return errors
    stype = schema.get("type")
    if stype == "object":
        if not isinstance(obj, dict):
            return [f"{path}: expected object, got {type(obj).__name__}"]
        for req in schema.get("required", []):
            if req not in obj:
                errors.append(f"{path}: missing required field '{req}'")
        props = schema.get("properties", {})
        extra = schema.get("additionalProperties")
        for key, val in obj.items():
            if key in props:
                errors.extend(validate_against_schema(val, props[key], f"{path}.{key}"))
            elif isinstance(extra, dict):
                errors.extend(validate_against_schema(val, extra, f"{path}.{key}"))
    elif stype == "array":
        if not isinstance(obj, list):
            return [f"{path}: expected array, got {type(obj).__name__}"]
        item_schema = schema.get("items")
        if isinstance(item_schema, dict):
            for i, item in enumerate(obj):
                errors.extend(validate_against_schema(item, item_schema, f"{path}[{i}]"))
    elif stype == "string":
        if not isinstance(obj, str):
            errors.append(f"{path}: expected string, got {type(obj).__name__}")
    elif stype == "integer":
        if not isinstance(obj, int) or isinstance(obj, bool):
            errors.append(f"{path}: expected integer, got {type(obj).__name__}")
        elif "minimum" in schema and obj < schema["minimum"]:
            errors.append(f"{path}: {obj} < minimum {schema['minimum']}")
    elif stype == "boolean":
        if not isinstance(obj, bool):
            errors.append(f"{path}: expected boolean, got {type(obj).__name__}")
    if "enum" in schema and obj not in schema["enum"]:
        errors.append(f"{path}: {obj!r} not in enum {schema['enum']}")
    return errors


def validate_custom_resource(crd: dict, obj: dict) -> list[str]:
    """Validate a custom resource against its CRD's schema for the
    apiVersion the object declares."""
    api_version = obj.get("apiVersion", "")
    version = api_version.split("/")[-1] if api_version else ""
    for v in crd["spec"]["versions"]:
        if v["name"] == version:
            schema = (v.get("schema") or {}).get("openAPIV3Schema")
            if schema is None:
                return []
            return validate_against_schema(obj, schema)
    return [f"$: version {version!r} not served by {crd['metadata']['name']}"]
