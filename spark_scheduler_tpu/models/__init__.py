"""Domain state models: resource algebra, cluster tensors, apps, reservations, demands."""

from spark_scheduler_tpu.models.resources import (  # noqa: F401
    Resources,
    parse_quantity,
    CPU_DIM,
    MEM_DIM,
    GPU_DIM,
    NUM_DIMS,
)
