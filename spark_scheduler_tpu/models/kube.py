"""Minimal cluster object model (Node / Pod) — the framework's view of k8s.

The reference consumes `corev1.Node` / `corev1.Pod` through informer caches;
this framework is backend-agnostic: any system that can produce these two
records (a real apiserver watch, a test harness, a synthetic generator) can
drive the scheduler. Only the fields the reference actually reads are modeled:

  Node:  name, labels, allocatable, unschedulable, ready, creationTimestamp
         (resources.go:61-100, sort/nodesorting.go:41-64)
  Pod:   metadata (name/namespace/labels/annotations/creationTimestamp/uid),
         spec (nodeName, schedulerName, nodeSelector, node affinity,
         container + initContainer resource requests), status (phase,
         conditions, container termination) — the subset read by
         internal/extender/sparkpods.go, overhead.go and common/utils/pods.go.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from spark_scheduler_tpu.models.resources import Resources

# corev1.LabelZoneFailureDomain, used for AZ awareness (resources.go:96-99).
ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
DEFAULT_ZONE = "default"  # zoneLabelPlaceholder, resources.go:27-29

_uid_counter = itertools.count(1)


@dataclasses.dataclass
class Node:
    name: str
    allocatable: Resources = dataclasses.field(default_factory=Resources.zero)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    unschedulable: bool = False
    ready: bool = True
    creation_timestamp: float = 0.0

    @property
    def zone(self) -> str:
        return self.labels.get(ZONE_LABEL, DEFAULT_ZONE)


@dataclasses.dataclass
class PodCondition:
    type: str
    status: bool
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclasses.dataclass
class Container:
    """A container's resource *requests* (the only part scheduling reads)."""

    name: str = ""
    requests: Resources = dataclasses.field(default_factory=Resources.zero)
    terminated: bool = False  # status: all-containers-terminated => pod dead


@dataclasses.dataclass
class Pod:
    name: str
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    creation_timestamp: float = 0.0
    uid: str = ""
    deletion_timestamp: Optional[float] = None

    # spec
    scheduler_name: str = ""
    node_name: str = ""  # empty until bound
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    # Required node affinity expressed as {label: [allowed values]}; the
    # reference reads requiredDuringSchedulingIgnoredDuringExecution match
    # expressions only to extract the instance group (internal/podspec.go:29-53).
    node_affinity: dict[str, list[str]] = dataclasses.field(default_factory=dict)
    containers: list[Container] = dataclasses.field(default_factory=list)
    init_containers: list[Container] = dataclasses.field(default_factory=list)

    # status
    phase: str = "Pending"
    conditions: list[PodCondition] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if not self.uid:
            self.uid = f"uid-{next(_uid_counter)}"

    @property
    def key(self) -> tuple[str, str]:
        return (self.namespace, self.name)

    def is_terminated(self) -> bool:
        """All containers terminated (common/utils/pods.go IsPodTerminated)."""
        return bool(self.containers) and all(c.terminated for c in self.containers)

    def is_scheduled(self) -> bool:
        return bool(self.node_name)

    def request(self) -> Resources:
        """max(sum of containers, max of init containers) per dim — the
        effective pod request (internal/extender/overhead.go:195-208)."""
        total = Resources.zero()
        for c in self.containers:
            total.add(c.requests)
        for c in self.init_containers:
            total.set_max(c.requests)
        return total

    def get_condition(self, cond_type: str) -> Optional[PodCondition]:
        for c in self.conditions:
            if c.type == cond_type:
                return c
        return None

    def set_condition(self, cond: PodCondition) -> bool:
        """Upsert a condition; returns True if it changed (mirrors k8s
        podutil behavior used by unschedulablepods.go / demand.go)."""
        existing = self.get_condition(cond.type)
        if existing is None:
            self.conditions.append(cond)
            return True
        if (existing.status, existing.reason, existing.message) != (
            cond.status,
            cond.reason,
            cond.message,
        ):
            existing.status = cond.status
            existing.reason = cond.reason
            existing.message = cond.message
            existing.last_transition_time = cond.last_transition_time
            return True
        return False
