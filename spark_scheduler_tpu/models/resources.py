"""Exact fixed-point resource algebra.

TPU-native rebuild of the reference's resource layer
(vendor/.../k8s-spark-scheduler-lib/pkg/resources/resources.go:31-279). The
reference carries `k8s.io/apimachinery` `resource.Quantity` (infinite-precision
decimals) through every comparison; admission decisions only ever need exact
ordering and exact floor-division, so we normalize every quantity ONCE at the
boundary into integer fixed-point units and do all math in int64 host-side /
int32 device-side:

  dim 0: CPU    in millicores  (1 core  == 1000)
  dim 1: Memory in KiB         (1 Mi    == 1024)
  dim 2: GPU    in milli-GPUs  (1 GPU   == 1000)

These units are exact for every quantity k8s users actually write (integer
millicores; Ki/Mi/Gi/Ti memory; whole GPUs). Sub-KiB memory quantities round
UP for requests and DOWN for allocatable — conservative in the admission
direction, never optimistic (SURVEY.md §7 "Quantity fidelity").

Device-side the three dims form the last axis of an `[N, 3]` int32 tensor;
int32 bounds each dim at ~2.1e9 (2.1M cores / 2 TiB / 2.1M GPUs per node) —
`parse_quantity` saturates beyond that rather than overflowing.
"""

from __future__ import annotations

import dataclasses
import re
from fractions import Fraction
from functools import lru_cache

import numpy as np

CPU_DIM = 0
MEM_DIM = 1
GPU_DIM = 2
NUM_DIMS = 3

# Saturation bound for a single int32 device cell, leaving headroom so that a
# node's (allocatable - usage) stays representable even when overcommitted.
# Also used as the +inf sentinel across cluster tensors and kernels — the two
# uses must stay equal so clipped values never collide with sentinels.
INT32_SAT = 2**31 - 2
INT32_INF = INT32_SAT

_DECIMAL_SUFFIX = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}
_BINARY_SUFFIX = {
    "Ki": Fraction(2**10),
    "Mi": Fraction(2**20),
    "Gi": Fraction(2**30),
    "Ti": Fraction(2**40),
    "Pi": Fraction(2**50),
    "Ei": Fraction(2**60),
}

# Exponent alternative ([eE]...) must precede the bare "E" (exa) suffix so
# "1E3" parses as 1000 (k8s decimalExponent grammar), while "1E" is exa.
_QUANTITY_RE = re.compile(
    r"^\s*([+-]?\d+(?:\.\d*)?|\.\d+)(Ki|Mi|Gi|Ti|Pi|Ei|[eE][+-]?\d+|n|u|m|k|M|G|T|P|E)?\s*$"
)


def _parse_to_fraction(s: str | int | float) -> Fraction:
    """Parse a k8s quantity string (e.g. '500m', '8Gi', '1.5', '2e3') exactly."""
    if isinstance(s, int):
        return Fraction(s)
    if isinstance(s, float):
        return Fraction(s).limit_denominator(10**9)
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    num, suffix = m.group(1), m.group(2) or ""
    base = Fraction(num)
    if suffix[:1] in ("e", "E") and len(suffix) > 1:  # decimal exponent
        return base * Fraction(10) ** int(suffix[1:])
    if suffix in _BINARY_SUFFIX:
        return base * _BINARY_SUFFIX[suffix]
    return base * _DECIMAL_SUFFIX[suffix]


def parse_quantity(s: str | int | float, dim: int, *, round_up: bool = True) -> int:
    """Parse a quantity into this framework's integer unit for `dim`.

    round_up=True (requests) rounds toward +inf; round_up=False (allocatable)
    rounds toward -inf, so rounding is always conservative for admission.

    Memoized: quantity strings repeat massively at serving time (every pod
    of a fleet carries the same handful of "8"/"8Gi"-style values), and the
    exact-Fraction parse is the expensive part. Pure function of hashable
    inputs — safe to cache."""
    return _parse_quantity_cached(s, dim, round_up)


@lru_cache(maxsize=8192)
def _parse_quantity_cached(s, dim: int, round_up: bool) -> int:
    frac = _parse_to_fraction(s)
    scale = 1024 if dim == MEM_DIM else 1000
    # Memory unit is KiB; CPU/GPU units are milli.
    if dim == MEM_DIM:
        scaled = frac / scale
    else:
        scaled = frac * scale
    n, d = scaled.numerator, scaled.denominator
    val = -((-n) // d) if round_up else n // d
    return max(-INT32_SAT, min(INT32_SAT, val))


@dataclasses.dataclass
class Resources:
    """A (cpu, memory, gpu) triple in fixed-point units.

    Mirrors `resources.Resources` (resources.go:150-166) with the same
    operation set: Add/Sub/Copy/SetMax/GreaterThan/Eq — but over plain ints.
    Mutating ops modify the receiver in place, matching the reference.
    """

    cpu_milli: int = 0
    mem_kib: int = 0
    gpu_milli: int = 0

    @classmethod
    def zero(cls) -> "Resources":
        return cls(0, 0, 0)

    @classmethod
    def from_quantities(
        cls, cpu="0", memory="0", gpu="0", *, round_up: bool = True
    ) -> "Resources":
        return cls(
            parse_quantity(cpu, CPU_DIM, round_up=round_up),
            parse_quantity(memory, MEM_DIM, round_up=round_up),
            parse_quantity(gpu, GPU_DIM, round_up=round_up),
        )

    def copy(self) -> "Resources":
        return Resources(self.cpu_milli, self.mem_kib, self.gpu_milli)

    def add(self, other: "Resources") -> "Resources":
        self.cpu_milli += other.cpu_milli
        self.mem_kib += other.mem_kib
        self.gpu_milli += other.gpu_milli
        return self

    def sub(self, other: "Resources") -> "Resources":
        self.cpu_milli -= other.cpu_milli
        self.mem_kib -= other.mem_kib
        self.gpu_milli -= other.gpu_milli
        return self

    def mul(self, k: int) -> "Resources":
        """Scale by an integer count (used for demand units / gang totals)."""
        return Resources(self.cpu_milli * k, self.mem_kib * k, self.gpu_milli * k)

    def set_max(self, other: "Resources") -> "Resources":
        """Per-dim max, the reference's SetMaxResource (resources.go:225-238)."""
        self.cpu_milli = max(self.cpu_milli, other.cpu_milli)
        self.mem_kib = max(self.mem_kib, other.mem_kib)
        self.gpu_milli = max(self.gpu_milli, other.gpu_milli)
        return self

    def greater_than(self, other: "Resources") -> bool:
        """True if ANY dim exceeds other's (resources.go:242-245): the fit
        check is `not request.greater_than(available)`."""
        return (
            self.cpu_milli > other.cpu_milli
            or self.mem_kib > other.mem_kib
            or self.gpu_milli > other.gpu_milli
        )

    def eq(self, other: "Resources") -> bool:
        return self.as_tuple() == other.as_tuple()

    def is_zero(self) -> bool:
        return self.as_tuple() == (0, 0, 0)

    def as_tuple(self) -> tuple[int, int, int]:
        return (self.cpu_milli, self.mem_kib, self.gpu_milli)

    def as_array(self) -> np.ndarray:
        return np.array(self.as_tuple(), dtype=np.int32)

    @classmethod
    def from_array(cls, arr) -> "Resources":
        a = np.asarray(arr)
        return cls(int(a[CPU_DIM]), int(a[MEM_DIM]), int(a[GPU_DIM]))

    def __repr__(self) -> str:  # human units for logs
        return (
            f"Resources(cpu={self.cpu_milli}m, mem={self.mem_kib}Ki, "
            f"gpu={self.gpu_milli}m)"
        )


class FrozenResources(Resources):
    """Read-only Resources view.

    Shared-aggregate queries (OverheadComputer.get_overhead) used to
    deep-copy every value under their lock so callers could not corrupt the
    aggregate; profiling showed the copies, not the lock, were the cost.
    A frozen view is handed out instead: mutators raise, `copy()` stays the
    escape hatch for a caller that genuinely needs a mutable value.

    Equality is by value against ANY Resources (the generated dataclass
    `__eq__` is class-exact and would make `Resources(...) ==
    FrozenResources(...)` silently False for equal triples)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        object.__setattr__(self, "_frozen", True)

    def __setattr__(self, name, value):
        # Direct field writes must fail too, not just the mutator methods
        # — a shared memoized view silently corrupted by `view.cpu_milli
        # -= x` would poison every later reader.
        if getattr(self, "_frozen", False):
            raise TypeError(
                "frozen Resources view — call .copy() before mutating"
            )
        object.__setattr__(self, name, value)

    def _reject(self, *_args, **_kwargs):
        raise TypeError(
            "frozen Resources view — call .copy() before mutating"
        )

    add = _reject
    sub = _reject
    set_max = _reject

    def __eq__(self, other):
        if isinstance(other, Resources):
            return self.as_tuple() == other.as_tuple()
        return NotImplemented

    __hash__ = None  # mutable-by-family type, same as Resources


def format_quantity_milli(milli: int) -> str:
    """Milli-units -> k8s quantity string ("1500m", or "2" when integral)."""
    if milli % 1000 == 0:
        return str(milli // 1000)
    return f"{milli}m"


def format_quantity_kib(kib: int) -> str:
    return f"{kib}Ki"


def resources_to_quantity_map(res: Resources) -> dict:
    """Wire-shape v1beta2 ResourceList {"cpu","memory","nvidia.com/gpu"}
    (types_resource_reservation.go:24-34,77-78); GPU omitted when zero,
    matching how the reference only carries it for GPU apps."""
    out = {
        "cpu": format_quantity_milli(res.cpu_milli),
        "memory": format_quantity_kib(res.mem_kib),
    }
    if res.gpu_milli:
        out["nvidia.com/gpu"] = format_quantity_milli(res.gpu_milli)
    return out


def resources_from_quantity_map(raw: dict | None) -> Resources:
    raw = raw or {}
    return Resources.from_quantities(
        str(raw.get("cpu", "0")),
        str(raw.get("memory", "0")),
        str(raw.get("nvidia.com/gpu", "0")),
    )


def stack_resources(items: list[Resources]) -> np.ndarray:
    """[len(items), 3] int32 tensor from a list of Resources."""
    if not items:
        return np.zeros((0, NUM_DIMS), dtype=np.int32)
    return np.stack([r.as_array() for r in items])
