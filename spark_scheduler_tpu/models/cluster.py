"""Cluster state as device tensors.

The reference's scheduling view of the cluster is a pair of string-keyed maps
(`NodeGroupResources`, `NodeGroupSchedulingMetadata`, resources.go:102-106)
rebuilt per request from informer caches. The TPU-native design replaces them
with dense `[N, 3]` int32 tensors over a stable node-index space so that the
whole fit/pack computation is one XLA program:

  available[N,3]    = allocatable - reservation usage - overhead
  schedulable[N,3]  = allocatable - overhead
  zone_id[N]        int32 zone of each node (registry-interned)
  name_rank[N]      lexicographic rank of the node name (sort tie-break,
                    sort/nodesorting.go:86-95)
  label_rank_*[N]   configured label-priority rank (lower = higher priority,
                    INT32_INF when the label/value is absent;
                    sort/nodesorting.go:160-185)
  unschedulable[N] / ready[N] / valid[N] bool masks

`NodeRegistry` owns the name <-> index interning host-side. Indices are stable
across node churn (freed slots are recycled and masked out via `valid`), so
incremental scatter updates to device-resident state stay cheap.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Mapping

import numpy as np

from spark_scheduler_tpu.models.kube import Node
from spark_scheduler_tpu.models.resources import INT32_INF, NUM_DIMS, Resources


@dataclasses.dataclass
class ClusterTensors:
    """The dense scheduling view consumed by ops/ kernels.

    A plain pytree of numpy/jax arrays; every ops/ kernel takes it as the
    first argument. Replaces NodeGroupSchedulingMetadata (resources.go:61-100).
    """

    available: np.ndarray  # [N,3] i32
    schedulable: np.ndarray  # [N,3] i32
    zone_id: np.ndarray  # [N] i32
    name_rank: np.ndarray  # [N] i32
    label_rank_driver: np.ndarray  # [N] i32
    label_rank_executor: np.ndarray  # [N] i32
    unschedulable: np.ndarray  # [N] bool
    ready: np.ndarray  # [N] bool
    valid: np.ndarray  # [N] bool

    @property
    def num_nodes(self) -> int:
        return int(self.available.shape[0])

    def tree_flatten(self):
        return (
            (
                self.available,
                self.schedulable,
                self.zone_id,
                self.name_rank,
                self.label_rank_driver,
                self.label_rank_executor,
                self.unschedulable,
                self.ready,
                self.valid,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


# Register as a JAX pytree so kernels can close over / be jitted with it.
import jax.tree_util as _jtu  # noqa: E402

_jtu.register_pytree_node(
    ClusterTensors, ClusterTensors.tree_flatten, lambda aux, ch: ClusterTensors(*ch)
)


def cluster_statics(cluster: ClusterTensors) -> tuple:
    """Every ClusterTensors field EXCEPT `available`, as a flat tuple.

    The serving engine splits the cluster at this seam: the static fields
    are uploaded once per device and stay resident, while the availability
    rides its own (donatable) argument — donating a whole ClusterTensors
    would delete the resident replica's static buffers with it. Order
    matches the constructor after `available` (cluster_from_statics)."""
    return (
        cluster.schedulable,
        cluster.zone_id,
        cluster.name_rank,
        cluster.label_rank_driver,
        cluster.label_rank_executor,
        cluster.unschedulable,
        cluster.ready,
        cluster.valid,
    )


def cluster_from_statics(available, statics: tuple) -> ClusterTensors:
    """Rebuild a ClusterTensors from an availability tensor + the static
    tuple `cluster_statics` produced (works on traced values inside jit)."""
    return ClusterTensors(available, *statics)


def pad_bucket(n: int, minimum: int) -> int:
    """Power-of-two size bucketing, THE shared sizing function of the
    resident-state stack: the solver pads its tensors, the feature store
    sizes its usage/overhead masters and roster buffers, and the prune
    planner buckets K with this same function. The store/solver equality
    is load-bearing — `_dense_or_scatter`'s zero-copy fast path requires
    the store's master length to equal the solver's pad exactly."""
    out = minimum
    while out < n:
        out *= 2
    return out


class NodeRegistry:
    """Host-side interning of node names and zone labels to stable indices.

    Interning is locked: indices are long-lived (the ReservedUsageTracker
    scatters deltas into a dense array keyed by them from informer/listener
    threads while request threads intern new nodes), so two threads racing
    `intern` must never be handed the same index for different names."""

    def __init__(self):
        self._intern_lock = threading.Lock()
        self._index: dict[str, int] = {}
        self._names: list[str | None] = []
        self._free: list[int] = []
        self._zone_ids: dict[str, int] = {}
        self._zone_names: list[str] = []
        # Bumped on every name->index mapping change; lets derived artifacts
        # (candidate masks, rank tables) cache against a stable mapping.
        # Seqlock discipline: bumped BEFORE and AFTER each mutation, so an
        # odd value means a mutation is in flight — lock-free readers must
        # not cache anything keyed on an odd epoch, and must re-check the
        # epoch after reading to detect a concurrent mutation.
        self._epoch = 0
        # Mapping-change journal (ISSUE 13): post-mutation EVEN epoch ->
        # [("add"|"remove", name, row)] — lets the solver PATCH a cached
        # candidate mask across epochs (a node ADD used to force an
        # O(N) name->row rebuild of every million-name mask). Bounded;
        # a missing epoch sends the consumer to the full rebuild.
        self._journal: dict[int, list] = {}

    def _journal_put(self, entries: list) -> None:
        """Record one mutation's mapping changes (caller holds the lock;
        epoch is even again)."""
        self._journal[self._epoch] = entries
        while len(self._journal) > 128:
            self._journal.pop(next(iter(self._journal)))

    def journal_between(self, e0: int, e1: int):
        """Concatenated mapping changes over the even epochs in (e0, e1],
        oldest first — or None when any epoch is missing (evicted, or the
        consumer's base predates the journal). Lock-free reads of
        GIL-atomic dict gets; callers run under the same seqlock verify
        they use for the masks themselves."""
        if e1 < e0 or (e1 - e0) % 2 or e1 - e0 > 256:
            return None
        out: list = []
        for e in range(e0 + 2, e1 + 1, 2):
            ent = self._journal.get(e)
            if ent is None:
                return None
            out.extend(ent)
        return out

    @property
    def epoch(self) -> int:
        return self._epoch

    def _alloc_locked(self, name: str) -> int:
        """Assign a slot to a NEW name. Caller holds the intern lock and
        has already bumped the epoch odd — the single owner of the
        free-list reuse invariant, shared by intern and intern_many so
        the per-name and bulk paths cannot drift."""
        if self._free:
            idx = self._free.pop()
            self._names[idx] = name
        else:
            idx = len(self._names)
            self._names.append(name)
        self._index[name] = idx
        return idx

    def intern(self, name: str) -> int:
        with self._intern_lock:
            idx = self._index.get(name)
            if idx is None:
                self._epoch += 1  # odd: mapping unstable
                idx = self._alloc_locked(name)
                self._epoch += 1  # even: stable again
                self._journal_put([("add", name, idx)])
            return idx

    def intern_many(self, names) -> np.ndarray:
        """Bulk intern: one lock hold and one C-speed index gather for the
        whole roster, instead of a lock acquire + function call per name
        (the measured 100k-node cold-featurize hotspot — 91 ms of per-name
        `intern` calls become ~10 ms here). Returns the int32 registry row
        of each name, in input order."""
        with self._intern_lock:
            index = self._index
            missing = [n for n in names if n not in index]
            if missing:
                self._epoch += 1  # odd: mapping unstable
                added = []
                for n in missing:
                    if n not in index:  # duplicate within `missing`
                        added.append(("add", n, self._alloc_locked(n)))
                self._epoch += 1  # even: stable again
                self._journal_put(added)
            return np.fromiter(
                (index[n] for n in names), np.int32, count=len(names)
            )

    def remove(self, name: str) -> None:
        with self._intern_lock:
            if name not in self._index:
                return
            self._epoch += 1  # odd: mapping unstable
            idx = self._index.pop(name)
            self._names[idx] = None
            self._free.append(idx)
            self._epoch += 1  # even: stable again
            self._journal_put([("remove", name, idx)])

    def index_of(self, name: str) -> int | None:
        return self._index.get(name)

    def read_consistent(self, fn):
        """Run `fn()` under the intern lock: a name->index view guaranteed
        stable for the duration. The fallback for seqlock readers (see
        `epoch`) when mutations keep the epoch moving — keeps the whole
        locking protocol inside the registry."""
        with self._intern_lock:
            return fn()

    def name_of(self, idx: int) -> str | None:
        if 0 <= idx < len(self._names):
            return self._names[idx]
        return None

    def zone_id(self, zone: str) -> int:
        with self._intern_lock:
            zid = self._zone_ids.get(zone)
            if zid is None:
                zid = len(self._zone_names)
                self._zone_ids[zone] = zid
                self._zone_names.append(zone)
            return zid

    @property
    def capacity(self) -> int:
        return len(self._names)

    def names(self) -> list[str | None]:
        return list(self._names)


def resources_map_to_tensor(
    usage: Mapping[str, Resources], registry: NodeRegistry, num_nodes: int
) -> np.ndarray:
    """[N,3] tensor from a {node name: Resources} map (overhead, soft usage)."""
    out = np.zeros((num_nodes, NUM_DIMS), dtype=np.int64)
    for name, res in usage.items():
        idx = registry.index_of(name)
        if idx is not None and idx < num_nodes:
            out[idx] += res.as_array()
    return np.clip(out, -INT32_INF, INT32_INF).astype(np.int32)


def build_cluster_tensors(
    nodes: list[Node],
    usage: np.ndarray | Mapping[str, Resources],
    overhead: np.ndarray | Mapping[str, Resources],
    registry: NodeRegistry,
    *,
    driver_label_priority: tuple[str, list[str]] | None = None,
    executor_label_priority: tuple[str, list[str]] | None = None,
    pad_to: int | None = None,
) -> ClusterTensors:
    """Build the dense scheduling view for a set of live nodes.

    Mirrors `NodeSchedulingMetadataForNodes` (resources.go:61-100):
      available   = allocatable - usage - overhead
      schedulable = allocatable - overhead
    plus the priority inputs of sort/nodesorting.go. `pad_to` rounds N up
    (bucketing) so XLA compile caches stay warm across node-count jitter.
    """
    for n in nodes:
        registry.intern(n.name)
    n_slots = registry.capacity
    if pad_to is not None:
        n_slots = max(n_slots, pad_to)

    if not isinstance(usage, np.ndarray):
        usage = resources_map_to_tensor(usage, registry, n_slots)
    if not isinstance(overhead, np.ndarray):
        overhead = resources_map_to_tensor(overhead, registry, n_slots)

    alloc = np.zeros((n_slots, NUM_DIMS), dtype=np.int64)
    zone_id = np.zeros(n_slots, dtype=np.int32)
    unschedulable = np.zeros(n_slots, dtype=bool)
    ready = np.zeros(n_slots, dtype=bool)
    valid = np.zeros(n_slots, dtype=bool)
    name_rank = np.full(n_slots, INT32_INF, dtype=np.int32)
    lr_driver = np.full(n_slots, INT32_INF, dtype=np.int32)
    lr_executor = np.full(n_slots, INT32_INF, dtype=np.int32)

    live = sorted(nodes, key=lambda n: n.name)
    for rank, node in enumerate(live):
        idx = registry.intern(node.name)
        alloc[idx] = node.allocatable.as_array()
        zone_id[idx] = registry.zone_id(node.zone)
        unschedulable[idx] = node.unschedulable
        ready[idx] = node.ready
        valid[idx] = True
        name_rank[idx] = rank
        for target, prio in (
            (lr_driver, driver_label_priority),
            (lr_executor, executor_label_priority),
        ):
            if prio is not None:
                label, values = prio
                val = node.labels.get(label)
                if val is not None and val in values:
                    target[idx] = values.index(val)

    # Dense inputs may be sized to a grown tracker buffer: rows past n_slots
    # are registry-unused zeros, so pad/truncate to n_slots either way.
    if usage.shape[0] < n_slots:
        usage = np.pad(usage, ((0, n_slots - usage.shape[0]), (0, 0)))
    elif usage.shape[0] > n_slots:
        usage = usage[:n_slots]
    if overhead.shape[0] < n_slots:
        overhead = np.pad(overhead, ((0, n_slots - overhead.shape[0]), (0, 0)))
    elif overhead.shape[0] > n_slots:
        overhead = overhead[:n_slots]

    available = np.clip(
        alloc - usage.astype(np.int64) - overhead.astype(np.int64),
        -INT32_INF,
        INT32_INF,
    ).astype(np.int32)
    schedulable = np.clip(
        alloc - overhead.astype(np.int64), -INT32_INF, INT32_INF
    ).astype(np.int32)

    return ClusterTensors(
        available=available,
        schedulable=schedulable,
        zone_id=zone_id,
        name_rank=name_rank,
        label_rank_driver=lr_driver,
        label_rank_executor=lr_executor,
        unschedulable=unschedulable,
        ready=ready,
        valid=valid,
    )
