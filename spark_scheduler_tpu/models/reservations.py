"""ResourceReservation records — the durable placement state.

Rebuilds the CRD pair of the reference
(vendor/.../apis/sparkscheduler/v1beta2/types_resource_reservation.go:40-102
and v1beta1/types_resource_reservation.go:22-68 plus the conversion in
v1beta1/conversion_resource_reservation.go:29-121):

  v1beta2 (storage): Spec.Reservations: {name -> {node, resources{cpu,mem,
      gpu}}}, Status.Pods: {name -> bound pod name}.
  v1beta1 (served legacy): flat {node, cpu, memory} per reservation; the
      lossless round-trip (GPU etc.) travels in the `reservation-spec`
      annotation as JSON.

Reservation names are "driver", "executor-1".."executor-N"
(resourcereservations.go:436-466).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from spark_scheduler_tpu.models.resources import (
    Resources,
    resources_from_quantity_map,
    resources_to_quantity_map,
)

APP_ID_LABEL = "spark-app-id"
# v1beta1 round-trip carrier; fully-qualified key so reference-written objects
# (sparkscheduler common.go:23-32 GroupName + "/reservation-spec") upgrade
# losslessly through this webhook too.
RESERVATION_SPEC_ANNOTATION = "sparkscheduler.palantir.com/reservation-spec"
DRIVER_RESERVATION = "driver"
# Priority class of the gang (policy subsystem). Set on the driver pod by the
# submitter; stamped onto the ResourceReservation at creation so the running
# gang's tier survives driver-pod deletion and is visible to the preemption
# search. Absent on both when the policy engine is off — objects stay
# byte-identical to the pre-policy wire form.
PRIORITY_CLASS_ANNOTATION = "spark-priority-class"


def executor_reservation_name(i: int) -> str:
    """0-based index -> "executor-1"... (resourcereservations.go:469-471)."""
    return f"executor-{i + 1}"


@dataclasses.dataclass
class Reservation:
    node: str
    resources: Resources

    def copy(self) -> "Reservation":
        return Reservation(self.node, self.resources.copy())


@dataclasses.dataclass
class ReservationSpec:
    reservations: dict[str, Reservation] = dataclasses.field(default_factory=dict)

    def copy(self) -> "ReservationSpec":
        return ReservationSpec({k: v.copy() for k, v in self.reservations.items()})


@dataclasses.dataclass
class ReservationStatus:
    pods: dict[str, str] = dataclasses.field(default_factory=dict)

    def copy(self) -> "ReservationStatus":
        return ReservationStatus(dict(self.pods))


@dataclasses.dataclass
class ResourceReservation:
    """v1beta2 storage form. Named after the app ID, owned by the driver pod."""

    name: str
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    owner_pod_uid: str = ""
    resource_version: int = 0
    # Verbatim passthrough of metadata fields this model doesn't interpret
    # (uid, creationTimestamp, generation, ownerReferences, finalizers, ...).
    # The apiserver requires conversion to preserve immutable metadata, so
    # the webhook must round-trip these (conversion_resource_reservation.go:
    # ConvertTo/ConvertFrom DeepCopy the whole ObjectMeta).
    metadata_extra: dict = dataclasses.field(default_factory=dict)
    spec: ReservationSpec = dataclasses.field(default_factory=ReservationSpec)
    status: ReservationStatus = dataclasses.field(default_factory=ReservationStatus)

    def copy(self) -> "ResourceReservation":
        return ResourceReservation(
            name=self.name,
            namespace=self.namespace,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            owner_pod_uid=self.owner_pod_uid,
            resource_version=self.resource_version,
            metadata_extra=dict(self.metadata_extra),
            spec=self.spec.copy(),
            status=self.status.copy(),
        )


def new_resource_reservation(
    driver_node: str,
    executor_nodes: list[str],
    driver_pod,
    driver_resources: Resources,
    executor_resources: Resources,
) -> ResourceReservation:
    """Build the gang's reservation object (resourcereservations.go:436-466):
    driver slot bound to the driver pod, one slot per min-executor."""
    reservations = {
        DRIVER_RESERVATION: Reservation(driver_node, driver_resources.copy())
    }
    for idx, node in enumerate(executor_nodes):
        reservations[executor_reservation_name(idx)] = Reservation(
            node, executor_resources.copy()
        )
    app_id = driver_pod.labels.get(APP_ID_LABEL, driver_pod.name)
    annotations: dict[str, str] = {}
    priority_class = (driver_pod.annotations or {}).get(PRIORITY_CLASS_ANNOTATION)
    if priority_class is not None:
        annotations[PRIORITY_CLASS_ANNOTATION] = priority_class
    return ResourceReservation(
        name=app_id,
        namespace=driver_pod.namespace,
        labels={APP_ID_LABEL: app_id},
        annotations=annotations,
        owner_pod_uid=driver_pod.uid,
        spec=ReservationSpec(reservations),
        status=ReservationStatus(pods={DRIVER_RESERVATION: driver_pod.name}),
    )


# ---------------------------------------------------------------------------
# v1beta1 legacy form + conversion (served for pre-upgrade clients; the
# conversion webhook serves both directions, SURVEY.md L9).
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ReservationV1Beta1:
    node: str
    cpu_milli: int
    mem_kib: int


@dataclasses.dataclass
class ResourceReservationV1Beta1:
    name: str
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    resource_version: int = 0
    metadata_extra: dict = dataclasses.field(default_factory=dict)
    reservations: dict[str, ReservationV1Beta1] = dataclasses.field(default_factory=dict)
    pods: dict[str, str] = dataclasses.field(default_factory=dict)


def convert_to_v1beta1(rr: ResourceReservation) -> ResourceReservationV1Beta1:
    """Downgrade, stashing the marshaled v1beta2 spec (incl. GPU) in the
    reservation-spec annotation for lossless round-trip. The stash is the
    reference's exact format — the JSON-marshaled v1beta2
    ResourceReservationSpec with quantity strings — so objects written by
    this webhook upgrade cleanly through the reference's and vice versa
    (conversion_resource_reservation.go ConvertFrom: json.Marshal(src.Spec))."""
    spec_json = json.dumps(
        {
            "reservations": {
                name: {
                    "node": r.node,
                    "resources": resources_to_quantity_map(r.resources),
                }
                for name, r in rr.spec.reservations.items()
            }
        },
        sort_keys=True,
    )
    annotations = dict(rr.annotations)
    annotations[RESERVATION_SPEC_ANNOTATION] = spec_json
    return ResourceReservationV1Beta1(
        name=rr.name,
        namespace=rr.namespace,
        labels=dict(rr.labels),
        annotations=annotations,
        resource_version=rr.resource_version,
        metadata_extra=dict(rr.metadata_extra),
        reservations={
            name: ReservationV1Beta1(r.node, r.resources.cpu_milli, r.resources.mem_kib)
            for name, r in rr.spec.reservations.items()
        },
        pods=dict(rr.status.pods),
    )


def convert_from_v1beta1(old: ResourceReservationV1Beta1) -> ResourceReservation:
    """Upgrade with the reference's merge semantics
    (conversion_resource_reservation.go ConvertTo): node/cpu/memory come from
    the v1beta1 struct fields; the stashed annotation only contributes
    resources the flat shape cannot carry (GPU). The stash annotation is
    removed from the upgraded object."""
    annotations = dict(old.annotations)
    raw = annotations.pop(RESERVATION_SPEC_ANNOTATION, None)
    if raw is None:
        # Round-1 builds of this codebase stashed under a bare key.
        raw = annotations.pop("reservation-spec", None)
    stashed: Optional[dict] = None
    if raw is not None:
        try:
            parsed = json.loads(raw)
            if isinstance(parsed, dict):
                # Reference format: {"reservations": {name: {node, resources}}};
                # round-1 legacy format was flat {name: {node, cpu_milli, ...}}.
                stashed = parsed.get("reservations", parsed)
        except json.JSONDecodeError:
            stashed = None
    reservations: dict[str, Reservation] = {}
    for name, r in old.reservations.items():
        gpu_milli = 0
        if stashed is not None and name in stashed:
            entry = stashed[name] or {}
            if "resources" in entry:
                gpu_milli = resources_from_quantity_map(entry["resources"]).gpu_milli
            else:
                gpu_milli = int(entry.get("gpu_milli", 0))
        reservations[name] = Reservation(
            r.node, Resources(r.cpu_milli, r.mem_kib, gpu_milli)
        )
    return ResourceReservation(
        name=old.name,
        namespace=old.namespace,
        labels=dict(old.labels),
        annotations=annotations,
        resource_version=old.resource_version,
        metadata_extra=dict(old.metadata_extra),
        spec=ReservationSpec(reservations),
        status=ReservationStatus(dict(old.pods)),
    )
