"""Demand records — the autoscaler signaling surface.

Rebuilds the scaler CRD pair
(vendor/.../apis/scaler/v1alpha2/types_demand.go:23-157 and v1alpha1):
a Demand names resources an application needs but cannot get, consumed by an
external cluster autoscaler. v1alpha2 adds zone affinity + per-unit pod
attribution; v1alpha1 is the flat legacy form kept for conversion parity.

Demand name for a pod is "demand-<pod name>" (common/utils/demands.go:28-67).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_scheduler_tpu.models.resources import Resources

DEMAND_NAME_PREFIX = "demand-"

# Phases (types_demand.go:124-141)
PHASE_EMPTY = ""
PHASE_PENDING = "pending"
PHASE_FULFILLED = "fulfilled"
PHASE_CANNOT_FULFILL = "cannot-fulfill"


def demand_name_for_pod(pod) -> str:
    return DEMAND_NAME_PREFIX + pod.name


@dataclasses.dataclass
class DemandUnit:
    resources: Resources
    count: int
    # {namespace: [pod names]} — pods whose own requests already cover part
    # of the demand, so the autoscaler doesn't double-count
    # (types_demand.go:88-100).
    pod_names_by_namespace: dict[str, list[str]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DemandSpec:
    instance_group: str
    units: list[DemandUnit] = dataclasses.field(default_factory=list)
    is_long_lived: bool = False
    enforce_single_zone_scheduling: bool = False
    zone: Optional[str] = None


@dataclasses.dataclass
class DemandStatus:
    phase: str = PHASE_EMPTY
    fulfilled_zone: Optional[str] = None
    last_transition_time: float = 0.0


@dataclasses.dataclass
class Demand:
    name: str
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    owner_pod_uid: str = ""
    resource_version: int = 0
    # Uninterpreted metadata (uid, creationTimestamp, ownerReferences, ...)
    # preserved verbatim through webhook conversion.
    metadata_extra: dict = dataclasses.field(default_factory=dict)
    spec: DemandSpec = dataclasses.field(default_factory=lambda: DemandSpec(""))
    status: DemandStatus = dataclasses.field(default_factory=DemandStatus)

    def is_fulfilled(self) -> bool:
        return self.status.phase == PHASE_FULFILLED


# -- v1alpha1 legacy form + conversion (apis/scaler/v1alpha1) ---------------


@dataclasses.dataclass
class DemandUnitV1Alpha1:
    """v1alpha1 unit carries flat cpu/memory/gpu quantities
    (apis/scaler/v1alpha1/types_demand.go:57-62)."""

    cpu_milli: int
    mem_kib: int
    count: int
    gpu_milli: int = 0


@dataclasses.dataclass
class DemandV1Alpha1:
    name: str
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    resource_version: int = 0
    metadata_extra: dict = dataclasses.field(default_factory=dict)
    instance_group: str = ""
    units: list[DemandUnitV1Alpha1] = dataclasses.field(default_factory=list)
    is_long_lived: bool = False
    phase: str = PHASE_EMPTY
    last_transition_time: float = 0.0


def convert_demand_to_v1alpha1(d: Demand) -> DemandV1Alpha1:
    """Storage -> legacy (conversion_demand.go ConvertFrom): phase,
    last-transition-time and per-unit cpu/memory/gpu carry over; zone
    semantics and pod attribution have no v1alpha1 shape and drop."""
    return DemandV1Alpha1(
        name=d.name,
        namespace=d.namespace,
        labels=dict(d.labels),
        resource_version=d.resource_version,
        metadata_extra=dict(d.metadata_extra),
        instance_group=d.spec.instance_group,
        units=[
            DemandUnitV1Alpha1(
                u.resources.cpu_milli, u.resources.mem_kib, u.count,
                gpu_milli=u.resources.gpu_milli,
            )
            for u in d.spec.units
        ],
        is_long_lived=d.spec.is_long_lived,
        phase=d.status.phase,
        last_transition_time=d.status.last_transition_time,
    )


def convert_demand_from_v1alpha1(old: DemandV1Alpha1) -> Demand:
    """Legacy -> storage (conversion_demand.go ConvertTo)."""
    return Demand(
        name=old.name,
        namespace=old.namespace,
        labels=dict(old.labels),
        resource_version=old.resource_version,
        metadata_extra=dict(old.metadata_extra),
        spec=DemandSpec(
            instance_group=old.instance_group,
            units=[
                DemandUnit(Resources(u.cpu_milli, u.mem_kib, u.gpu_milli), u.count)
                for u in old.units
            ],
            is_long_lived=old.is_long_lived,
        ),
        status=DemandStatus(
            phase=old.phase, last_transition_time=old.last_transition_time
        ),
    )
