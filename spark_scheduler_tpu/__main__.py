"""CLI (cmd/root.go:22-35, cmd/server.go:44-54):

  python -m spark_scheduler_tpu server [--config install.yml] [--port N]
  python -m spark_scheduler_tpu conversion-webhook [--port N]
  python -m spark_scheduler_tpu version

`conversion-webhook` is the standalone CRD-conversion service the reference
ships as a second binary (spark-scheduler-conversion-webhook/main.go:27).
"""

from __future__ import annotations

import argparse
import sys

__version__ = "0.1.0"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="spark-scheduler-tpu")
    sub = parser.add_subparsers(dest="command")
    sub.add_parser("version", help="print version")
    srv = sub.add_parser("server", help="run the scheduler extender server")
    srv.add_argument("--config", help="install YAML (config/config.go:24-84 surface)")
    srv.add_argument("--host", default="0.0.0.0")
    srv.add_argument("--port", type=int, default=None)
    srv.add_argument(
        "--durable-store",
        default=None,
        help="JSONL write-ahead log path; state survives restarts "
        "(the etcd/CRD persistence slot, SURVEY.md §5.4)",
    )
    srv.add_argument(
        "--kube-api-url",
        default=None,
        help="apiserver base URL for list+watch ingestion (informer slot)",
    )
    srv.add_argument(
        "--transport",
        choices=("threaded", "async"),
        default=None,
        help="serving transport: 'threaded' (stdlib thread-per-connection,"
        " default) or 'async' (single-threaded event loop with pipelined"
        " keep-alive framing and explicit backpressure); overrides the"
        " install config's server.transport",
    )
    srv.add_argument(
        "--ingest",
        choices=("python", "native"),
        default=None,
        help="serving ingest lane: 'python' (json.loads per predicate "
        "body, default) or 'native' (C++ request framing + zero-copy "
        "predicate decode via native/runtime.cpp; degrades to python "
        "with a RuntimeWarning when the toolchain is missing); overrides "
        "the install config's server.ingest",
    )
    srv.add_argument(
        "--device-pool",
        type=int,
        default=None,
        help="multi-device window-solve engine: keep a resident cluster "
        "replica on N accelerator devices and round-robin concurrent "
        "window solves across them (disjoint instance-group windows "
        "solve in parallel); overrides the install config's "
        "solver.device-pool",
    )
    srv.add_argument(
        "--mesh",
        default=None,
        metavar="GROUPSxSHARDS",
        help="full mesh form of --device-pool, e.g. '4x2' = 4 pool slots "
        "of 2 node-sharding devices each (solver.mesh {groups, "
        "node-shards}); node-shards > 1 runs each window as a GSPMD "
        "node-axis-sharded solve on the slot's sub-mesh",
    )
    srv.add_argument(
        "--fuse-windows",
        type=int,
        default=None,
        help="fused multi-window device dispatch: when the predicate "
        "backlog exceeds one window, claim up to K windows and solve "
        "them in ONE device program carrying committed state on-device "
        "between windows (K windows share one device round trip); "
        "overrides the install config's solver.fuse-windows (default 1 "
        "= unfused)",
    )
    srv.add_argument(
        "--prune-top-k",
        type=int,
        default=None,
        help="sound top-K candidate pruning (the two-tier solve): serve "
        "eligible windows over a gathered top-K sub-cluster sized from "
        "the window's demand x --prune-slack, with a post-solve "
        "certificate escalating any window a pruned row could have "
        "changed (decisions stay byte-identical); overrides the install "
        "config's solver.prune-top-k (default 0 = off)",
    )
    srv.add_argument(
        "--prune-slack",
        type=float,
        default=None,
        help="candidate-pruning slack factor: kept rows per zone = "
        "max(prune-top-k, ceil(window aggregate demand x slack)); "
        "overrides solver.prune-slack (default 2.0)",
    )
    srv.add_argument(
        "--scale-tier",
        action="store_true",
        default=None,
        help="million-node scale tier: run certificate escalations and "
        "cold full-tensor re-solves as a node-sharded device solve over "
        "the local mesh instead of the host greedy walk (byte-identical "
        "decisions; wants an ICI-class interconnect); overrides "
        "solver.scale-tier (default off)",
    )
    srv.add_argument(
        "--no-delta-statics",
        action="store_true",
        default=None,
        help="disable delta STATIC uploads (solver.delta-statics): every "
        "statics change re-uploads the full blob and drains in-flight "
        "windows, the pre-ISSUE-11 behavior",
    )
    srv.add_argument(
        "--ha-replica",
        default=None,
        metavar="REPLICA_ID",
        help="run as one replica of a lease-elected HA group (enables the "
        "ha: install block with this replica id): boot as a warm standby "
        "tailing backend state, serve only after winning the leader lease "
        "and running the failover reconcile; reservation writes carry the "
        "lease's fencing epoch. With --durable-store the WAL is opened in "
        "follower mode and the lease lives in an flock-guarded "
        "<wal>.lease sidecar (the supported multi-process arbiter); "
        "combining with --kube-api-url is refused — the apiserver backend "
        "does not persist a lease kind yet, so each replica would elect "
        "itself (split-brain)",
    )
    srv.add_argument(
        "--ha-lease-ttl",
        type=float,
        default=None,
        metavar="SECONDS",
        help="leader lease TTL (default 3s; heartbeat renews at TTL/3); "
        "overrides the install config's ha.lease-ttl",
    )
    srv.add_argument(
        "--autoscaler",
        action="store_true",
        help="enable the in-process elastic autoscaler: consume pending "
        "Demand CRDs, provision simulated nodes, drain idle ones "
        "(see the install config's `autoscaler:` block for knobs)",
    )
    srv.add_argument(
        "--fleet-stack",
        type=float,
        default=None,
        metavar="MS",
        help="fused fleet dispatch gather window in milliseconds "
        "(fleet.stack-window-ms): concurrent per-cluster windows stack "
        "into one device launch (fleet/dispatch.py); 0 disables; "
        "requires fleet.enabled with >= 2 clusters to have any effect",
    )
    pc = sub.add_parser(
        "print-crds",
        help="emit the CustomResourceDefinition manifests as YAML "
        "(kubectl apply -f -)",
    )
    pc.add_argument(
        "--conversion-webhook-url",
        default=None,
        help="wire the webhook conversion strategy with this client URL",
    )
    cw = sub.add_parser(
        "conversion-webhook", help="run the standalone CRD-conversion webhook"
    )
    cw.add_argument("--host", default="0.0.0.0")
    cw.add_argument("--port", type=int, default=8485)
    cw.add_argument("--cert-file", default=None)
    cw.add_argument("--key-file", default=None)
    cw.add_argument(
        "--request-log",
        action="store_true",
        help="emit a structured request.2 access-log line per HTTP call",
    )
    args = parser.parse_args(argv)

    if args.command == "version":
        print(__version__)
        return 0
    if args.command == "print-crds":
        import yaml

        from spark_scheduler_tpu.models.crds import demand_crd, resource_reservation_crd

        print(
            yaml.safe_dump_all(
                [
                    resource_reservation_crd(
                        webhook_url=args.conversion_webhook_url
                    ),
                    demand_crd(),
                ],
                sort_keys=False,
            ),
            end="",
        )
        return 0
    if args.command == "conversion-webhook":
        from spark_scheduler_tpu.server.http import ConversionWebhookServer

        server = ConversionWebhookServer(
            host=args.host,
            port=args.port,
            cert_file=args.cert_file,
            key_file=args.key_file,
            request_log=args.request_log,
        )
        print(
            f"conversion webhook serving on {args.host}:{server.port}", file=sys.stderr
        )
        server.serve_forever()
        return 0
    if args.command != "server":
        parser.print_help()
        return 2

    from spark_scheduler_tpu.events import EventEmitter
    from spark_scheduler_tpu.metrics import (
        CacheReporter,
        MetricRegistry,
        QueueReporter,
        ReporterRunner,
        SchedulerMetrics,
        SoftReservationReporter,
        UsageReporter,
        WasteReporter,
    )
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend

    config = InstallConfig()
    if args.config:
        import yaml

        with open(args.config) as f:
            config = InstallConfig.from_dict(yaml.safe_load(f) or {})
    if args.port is not None:
        config.port = args.port
    if args.durable_store is not None:
        config.durable_store_path = args.durable_store
    if args.kube_api_url is not None:
        config.kube_api_url = args.kube_api_url
    if args.autoscaler:
        config.autoscaler_enabled = True
    if args.ha_replica is not None:
        config.ha_enabled = True
        config.ha_replica_id = args.ha_replica
    if args.ha_lease_ttl is not None:
        config.ha_lease_ttl_s = args.ha_lease_ttl
    if args.fleet_stack is not None:
        config.fleet_stack_window_ms = args.fleet_stack
    if args.transport is not None:
        config.server_transport = args.transport
    if args.ingest is not None:
        config.server_ingest = args.ingest
    if args.device_pool is not None:
        # The flag overrides the WHOLE engine config: a configured
        # solver.mesh would otherwise win inside the solver and make
        # `--device-pool 1` (disable the engine) a no-op. An explicit
        # --mesh below still takes precedence over --device-pool.
        config.solver_device_pool = args.device_pool
        config.solver_mesh_groups = None
        config.solver_mesh_node_shards = None
    if args.fuse_windows is not None:
        config.solver_fuse_windows = args.fuse_windows
    if args.prune_top_k is not None:
        config.solver_prune_top_k = args.prune_top_k
    if args.prune_slack is not None:
        config.solver_prune_slack = args.prune_slack
    if args.scale_tier:
        config.solver_scale_tier = True
    if args.no_delta_statics:
        config.solver_delta_statics = False
    if args.mesh is not None:
        try:
            groups, shards = (int(x) for x in args.mesh.lower().split("x"))
        except ValueError:
            print(
                f"--mesh expects GROUPSxSHARDS (e.g. 4x2), got {args.mesh!r}",
                file=sys.stderr,
            )
            return 2
        config.solver_mesh_groups = groups
        config.solver_mesh_node_shards = shards

    registry = MetricRegistry()
    metrics = SchedulerMetrics(registry, config.instance_group_label)
    events = EventEmitter(instance_group_label=config.instance_group_label)
    waste = WasteReporter(registry, config.instance_group_label)
    kube_backend = False
    if config.durable_store_path:
        from spark_scheduler_tpu.store.durable import DurableBackend

        # HA replicas open the shared WAL in FOLLOWER mode: read-only
        # tailing until this replica wins the lease and promotes (the
        # promotion flips it to the writer). A standalone (non-HA) server
        # is the sole writer from the start.
        backend = DurableBackend(
            config.durable_store_path, follow=config.ha_enabled
        )
    elif config.kube_api_url:
        # Reservations/demands persist as CRs in the apiserver — the
        # reference's actual deployment mode (CRDs ARE the durable store,
        # SURVEY.md §5.4). A durable-store path overrides this with a
        # local WAL instead.
        from spark_scheduler_tpu.kube.backend import KubeBackend

        if config.kube_api_url == "in-cluster":
            from spark_scheduler_tpu.kube.reflector import in_cluster_config

            base_url, ca_file, token_file = in_cluster_config()
        else:
            base_url, ca_file, token_file = config.kube_api_url, None, None
        backend = KubeBackend(
            base_url,
            qps=config.kube_api_qps,
            burst=config.kube_api_burst,
            ca_file=ca_file,
            token_file=token_file,
            insecure_skip_tls_verify=config.kube_api_insecure_skip_tls_verify,
            metrics=registry,
        )
        backend.start()  # initial CR list + watch
        kube_backend = True
    else:
        backend = InMemoryBackend()
    if not kube_backend:
        # On a real cluster the Demand CRD belongs to the external
        # autoscaler (demand_informer.go); locally we provide it so demand
        # features are exercisable.
        backend.register_crd(DEMAND_CRD)
    ha_runtime = None
    fleet_facade = None
    if config.fleet_enabled and (
        config.ha_enabled or config.durable_store_path or kube_backend
    ):
        # Fleet mode boots F private in-memory cluster stacks; composing
        # it with HA roles or a shared durable/apiserver backend (whose
        # state would reach only cluster 0) needs per-cluster state
        # ingestion — refusing beats serving a silently half-wired fleet.
        raise SystemExit(
            "fleet.enabled composes with the in-memory backend only for "
            "now (not ha.enabled / --durable-store / --kube-api-url): "
            "each cluster stack owns a private backend."
        )
    if config.ha_enabled:
        from spark_scheduler_tpu.ha import (
            BackendLeaseStore,
            FileLeaseStore,
            LeaseManager,
        )
        from spark_scheduler_tpu.ha.replica import build_replica

        # The lease arbiter must be shared across replicas: the WAL
        # deployment uses the flock-guarded sidecar (the log itself has no
        # cross-process CAS); kube/in-memory backends CAS through the
        # backend's optimistic concurrency.
        if config.durable_store_path:
            lease_store = FileLeaseStore(config.durable_store_path + ".lease")
        elif kube_backend:
            # KubeBackend round-trips only reservations/demands to the
            # apiserver; a "leases" object would land in each process's
            # PRIVATE local store — every replica would elect itself at
            # epoch 1 and no write would ever be fenced. Refusing beats
            # silent split-brain; a coordination.k8s.io Lease codec is the
            # future fix.
            raise SystemExit(
                "--ha-replica with --kube-api-url is not supported: the "
                "lease would be process-local (each replica elects itself "
                "— split-brain). Use --durable-store for multi-process HA."
            )
        else:
            lease_store = BackendLeaseStore(backend)
        lease = LeaseManager(
            lease_store, config.ha_replica_id, ttl_s=config.ha_lease_ttl_s
        )
        ha_runtime = build_replica(
            backend,
            config.ha_replica_id,
            config=config,
            lease=lease,
            metrics=metrics,
            events=events,
            waste=waste,
            registry=registry,
        )
        app = ha_runtime.app
    elif config.fleet_enabled:
        from spark_scheduler_tpu.fleet import FleetFacade

        # F independent per-cluster stacks behind this one endpoint
        # (fleet/facade.py). Cluster 0 doubles as the server's local app
        # (readiness, debug state, PUT /state ingestion); /predicates is
        # fleet-routed by the routing layer the moment `fleet` is wired.
        fleet_facade = FleetFacade(
            config.fleet_clusters,
            config,
            registry=registry,
            max_spillover_hops=config.fleet_max_spillover_hops,
            suppress_resync=False,
        )
        app = fleet_facade.stacks[0].app
    else:
        app = build_scheduler_app(
            backend, config, metrics=metrics, events=events, waste=waste
        )

    class _Cleanups:  # periodic state eviction + metric flush on the tick
        def report_once(self):
            waste.cleanup()
            metrics.report_once()
            if config.metrics_log:
                with open(config.metrics_log, "a") as f:
                    registry.emit(f)

    reporters = ReporterRunner(
        [
            UsageReporter(registry, app.reservation_manager),
            CacheReporter(
                registry,
                {"resourcereservations": app.rr_cache, "demands": app.demand_cache},
                backend=backend,
            ),
            SoftReservationReporter(registry, app.soft_store),
            QueueReporter(registry, backend, config.instance_group_label),
            _Cleanups(),
        ]
    )
    server = SchedulerHTTPServer(
        app,
        registry,
        host=args.host,
        port=config.port,
        cert_file=config.cert_file,
        key_file=config.key_file,
        client_ca_files=config.client_ca_files,
        request_timeout_s=config.request_timeout_s,
        debug_routes=config.debug_routes,
        request_log=config.request_log,
        ha=ha_runtime,
        fleet=fleet_facade,
    )
    reporters.start()
    print(f"spark-scheduler-tpu serving on {args.host}:{server.port}", file=sys.stderr)
    try:
        if config.durable_store_path or kube_backend:
            # Restored state (WAL replay or apiserver CR list) must be
            # reconciled against CURRENT cluster state BEFORE any
            # /predicates request is served: wait for watch-ingestion cache
            # sync (blocking until it succeeds — a half-populated cache
            # would make reconciliation delete reservations for pods that
            # merely haven't listed yet), then reconcile, then open the
            # server (WaitForCacheSync precedes failover recovery:
            # cmd/server.go:140-147 then failover.go:35-72 — a restart IS
            # a leader change).
            app.start_background()
            if app.ingestion is not None:
                while not app.ingestion.wait_synced(timeout=30.0):
                    print(
                        "waiting for apiserver cache sync before reconcile...",
                        file=sys.stderr,
                    )
            if kube_backend:
                while not backend.wait_synced(timeout=30.0):
                    print(
                        "waiting for reservation/demand cache sync...",
                        file=sys.stderr,
                    )
            if ha_runtime is None:
                app.reconciler.sync_resource_reservations_and_demands()
            else:
                # Election decides who reconciles: one immediate tick so a
                # sole/first replica serves without waiting a heartbeat;
                # losers stay warm standbys (readiness reports the role)
                # until the heartbeat loop promotes them.
                ha_runtime.run_election_once()
        elif ha_runtime is not None:
            ha_runtime.run_election_once()
        server.start()
        server.join()
    except KeyboardInterrupt:
        server.stop()
    finally:
        reporters.stop()
        if fleet_facade is not None:
            fleet_facade.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
