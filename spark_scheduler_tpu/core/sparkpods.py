"""Spark application shapes from pod annotations + driver FIFO listing.

Rebuilds internal/extender/sparkpods.go and internal/common/constants.go:
the driver pod carries the whole application's resource shape in
annotations; executors are matched back to their driver by the app-id label.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_scheduler_tpu.models.kube import Pod
from spark_scheduler_tpu.models.reservations import DRIVER_RESERVATION  # noqa: F401  (re-export)
from spark_scheduler_tpu.models.resources import (
    CPU_DIM,
    GPU_DIM,
    MEM_DIM,
    Resources,
    parse_quantity,
)

# Scheduler identity + labels (constants.go:17-29)
SPARK_SCHEDULER_NAME = "spark-scheduler"
SPARK_ROLE_LABEL = "spark-role"
SPARK_APP_ID_LABEL = "spark-app-id"
ROLE_DRIVER = "driver"
ROLE_EXECUTOR = "executor"

# Annotation keys (constants.go:31-51)
DRIVER_CPU = "spark-driver-cpu"
DRIVER_MEMORY = "spark-driver-mem"
DRIVER_GPUS = "spark-driver-nvidia.com/gpu"
EXECUTOR_CPU = "spark-executor-cpu"
EXECUTOR_MEMORY = "spark-executor-mem"
EXECUTOR_GPUS = "spark-executor-nvidia.com/gpu"
DYNAMIC_ALLOCATION_ENABLED = "spark-dynamic-allocation-enabled"
EXECUTOR_COUNT = "spark-executor-count"
DA_MIN_EXECUTOR_COUNT = "spark-dynamic-allocation-min-executor-count"
DA_MAX_EXECUTOR_COUNT = "spark-dynamic-allocation-max-executor-count"


class SparkPodError(ValueError):
    """Invalid/missing annotations (maps to failure-internal outcomes)."""


@dataclasses.dataclass
class SparkApplicationResources:
    driver_resources: Resources
    executor_resources: Resources
    min_executor_count: int
    max_executor_count: int


def spark_resources(pod: Pod) -> SparkApplicationResources:
    """Parse the driver's annotation set (sparkpods.go:79-138), with the same
    validation: ExecutorCount required iff static allocation; DA min/max
    required iff dynamic; GPUs optional.

    Memoized per pod OBJECT: the FIFO path re-parses every pending earlier
    driver on every request (quadratic in queue depth), and exact-decimal
    quantity parsing is the host hot spot under windowed serving. Updated
    pods arrive as fresh objects (the backend replaces, never mutates), so
    object identity is a safe cache key."""
    cached = pod.__dict__.get("_spark_resources_cache")
    if cached is not None:
        if isinstance(cached, SparkPodError):
            raise cached
        return cached
    try:
        out = _parse_spark_resources(pod)
    except SparkPodError as exc:
        pod.__dict__["_spark_resources_cache"] = exc
        raise
    pod.__dict__["_spark_resources_cache"] = out
    return out


def _parse_spark_resources(pod: Pod) -> SparkApplicationResources:
    ann = pod.annotations
    da_raw = ann.get(DYNAMIC_ALLOCATION_ENABLED)
    dynamic = False
    if da_raw is not None:
        if da_raw.lower() not in ("true", "false", "1", "0"):
            raise SparkPodError(
                "annotation DynamicAllocationEnabled could not be parsed as a boolean"
            )
        dynamic = da_raw.lower() in ("true", "1")

    def need(key: str) -> str:
        val = ann.get(key)
        if val is None:
            raise SparkPodError(f"annotation {key} is missing from driver")
        return val

    def parse_count(key: str) -> int:
        val = need(key)
        try:
            return int(parse_quantity(val, GPU_DIM) // 1000)
        except ValueError as e:
            raise SparkPodError(f"annotation {key} does not have a parseable value {val}") from e

    if dynamic:
        for key in (DA_MIN_EXECUTOR_COUNT, DA_MAX_EXECUTOR_COUNT):
            if key not in ann:
                raise SparkPodError(
                    f"annotation {key} is required when DynamicAllocationEnabled is true"
                )
        min_count = parse_count(DA_MIN_EXECUTOR_COUNT)
        max_count = parse_count(DA_MAX_EXECUTOR_COUNT)
    else:
        if EXECUTOR_COUNT not in ann:
            raise SparkPodError(
                "annotation ExecutorCount is required when DynamicAllocationEnabled is false"
            )
        min_count = max_count = parse_count(EXECUTOR_COUNT)

    def parse_res(cpu_key: str, mem_key: str, gpu_key: str) -> Resources:
        try:
            return Resources(
                parse_quantity(need(cpu_key), CPU_DIM),
                parse_quantity(need(mem_key), MEM_DIM),
                parse_quantity(ann.get(gpu_key, "0"), GPU_DIM),
            )
        except ValueError as e:
            raise SparkPodError(str(e)) from e

    return SparkApplicationResources(
        driver_resources=parse_res(DRIVER_CPU, DRIVER_MEMORY, DRIVER_GPUS),
        executor_resources=parse_res(EXECUTOR_CPU, EXECUTOR_MEMORY, EXECUTOR_GPUS),
        min_executor_count=min_count,
        max_executor_count=max_count,
    )


def find_instance_group(pod: Pod, instance_group_label: str) -> Optional[str]:
    """Instance group from nodeAffinity match expressions or nodeSelector
    (internal/podspec.go:29-53)."""
    values = pod.node_affinity.get(instance_group_label)
    if values:
        return values[0]
    sel = pod.node_selector.get(instance_group_label)
    if sel is not None:
        return sel
    return None


def pod_matches_node(pod: Pod, node) -> bool:
    """Required node affinity + nodeSelector matching (the subset of
    v1affinityhelper.GetRequiredNodeAffinity().Match the scheduler needs)."""
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    for k, allowed in pod.node_affinity.items():
        if node.labels.get(k) not in allowed:
            return False
    return True


class SparkPodLister:
    """Driver/executor pod queries over the backend (sparkpods.go:39-77)."""

    def __init__(self, backend, instance_group_label: str):
        self._backend = backend
        self.instance_group_label = instance_group_label
        # Per-app and per-role listing are on the executor/FIFO hot paths;
        # with an index-capable backend they touch one bucket instead of
        # scanning every pod (the reference's informer indexers).
        register = getattr(backend, "register_pod_index", None)
        if register is not None:
            register(SPARK_APP_ID_LABEL)
            register(SPARK_ROLE_LABEL)

    def list_pending_drivers(self) -> list[Pod]:
        """All unscheduled, undeleted driver pods, oldest first — ONE backend
        scan shared by every request of a serving window (the per-request
        filter in `earlier_of` is then O(pending))."""
        out = [
            p
            for p in self._backend.list_pods(labels={SPARK_ROLE_LABEL: ROLE_DRIVER})
            if not p.node_name and p.deletion_timestamp is None
        ]
        out.sort(key=lambda p: p.creation_timestamp)
        return out

    @staticmethod
    def is_earlier_driver(p: Pod, p_group: Optional[str], driver: Pod,
                          driver_group: Optional[str]) -> bool:
        """The FIFO predecessor predicate (same scheduler + instance group,
        strictly earlier creation, sparkpods.go:51-77) — THE single
        definition, shared by the solo path and the window assembly so the
        two cannot drift."""
        return (
            p.scheduler_name == driver.scheduler_name
            and p.creation_timestamp < driver.creation_timestamp
            and p_group == driver_group
        )

    @staticmethod
    def earlier_of(pending: list[Pod], driver: Pod, group: Optional[str],
                   instance_group_label: str) -> list[Pod]:
        """Filter a `list_pending_drivers` snapshot down to `driver`'s FIFO
        predecessors. Snapshot is already oldest-first."""
        return [
            p
            for p in pending
            if SparkPodLister.is_earlier_driver(
                p, find_instance_group(p, instance_group_label), driver, group
            )
        ]

    def list_earlier_drivers(self, driver: Pod) -> list[Pod]:
        """Unscheduled drivers of the same scheduler + instance group created
        strictly earlier, oldest first (sparkpods.go:51-77)."""
        group = find_instance_group(driver, self.instance_group_label)
        return self.earlier_of(
            self.list_pending_drivers(), driver, group, self.instance_group_label
        )

    def get_driver_pod(self, app_id: str, namespace: str) -> Optional[Pod]:
        pods = self._backend.list_pods(
            namespace=namespace,
            labels={SPARK_APP_ID_LABEL: app_id, SPARK_ROLE_LABEL: ROLE_DRIVER},
        )
        return pods[0] if len(pods) == 1 else None

    def get_driver_for_executor(self, executor: Pod) -> Optional[Pod]:
        return self.get_driver_pod(
            executor.labels.get(SPARK_APP_ID_LABEL, ""), executor.namespace
        )

    def list_app_pods(self, app_id: str, namespace: str) -> list[Pod]:
        return self._backend.list_pods(
            namespace=namespace, labels={SPARK_APP_ID_LABEL: app_id}
        )


def is_spark_scheduler_pod(pod: Pod) -> bool:
    return pod.scheduler_name == SPARK_SCHEDULER_NAME and SPARK_ROLE_LABEL in pod.labels


def is_spark_scheduler_executor_pod(pod: Pod) -> bool:
    return (
        pod.scheduler_name == SPARK_SCHEDULER_NAME
        and pod.labels.get(SPARK_ROLE_LABEL) == ROLE_EXECUTOR
    )
