"""Binpacker registry (internal/extender/binpack.go:21-54): maps the
configured algorithm name to a packing kernel and flags single-AZ packers
(which gate zone-scoped demands + same-AZ dynamic allocation)."""

from __future__ import annotations

import dataclasses

from spark_scheduler_tpu.ops.packing import SINGLE_AZ_PACKERS, BINPACK_FUNCTIONS

AZ_AWARE_TIGHTLY_PACK = "az-aware-tightly-pack"
SINGLE_AZ_TIGHTLY_PACK = "single-az-tightly-pack"
SINGLE_AZ_MINIMAL_FRAGMENTATION = "single-az-minimal-fragmentation"
TIGHTLY_PACK = "tightly-pack"
DISTRIBUTE_EVENLY = "distribute-evenly"
MINIMAL_FRAGMENTATION = "minimal-fragmentation"


@dataclasses.dataclass(frozen=True)
class Binpacker:
    name: str
    is_single_az: bool


def select_binpacker(name: str) -> Binpacker:
    """Resolve a configured algorithm name to its packer.

    The reference silently falls back to tightly-pack on an unknown name
    (binpack.go:47-54); here a typo'd config string raises an
    `UnknownStrategyError` listing the valid names — the same error shape
    the policy plug-board uses (policy/registry.py)."""
    from spark_scheduler_tpu.policy.registry import resolve

    resolve(name, BINPACK_FUNCTIONS, "binpack algorithm")
    return Binpacker(name=name, is_single_az=name in SINGLE_AZ_PACKERS)
