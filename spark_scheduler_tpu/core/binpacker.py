"""Binpacker registry (internal/extender/binpack.go:21-54): maps the
configured algorithm name to a packing kernel and flags single-AZ packers
(which gate zone-scoped demands + same-AZ dynamic allocation)."""

from __future__ import annotations

import dataclasses

from spark_scheduler_tpu.ops.packing import SINGLE_AZ_PACKERS, BINPACK_FUNCTIONS

AZ_AWARE_TIGHTLY_PACK = "az-aware-tightly-pack"
SINGLE_AZ_TIGHTLY_PACK = "single-az-tightly-pack"
SINGLE_AZ_MINIMAL_FRAGMENTATION = "single-az-minimal-fragmentation"
TIGHTLY_PACK = "tightly-pack"
DISTRIBUTE_EVENLY = "distribute-evenly"
MINIMAL_FRAGMENTATION = "minimal-fragmentation"


@dataclasses.dataclass(frozen=True)
class Binpacker:
    name: str
    is_single_az: bool


def select_binpacker(name: str) -> Binpacker:
    """Unknown names fall back to tightly-pack, matching SelectBinpacker
    (binpack.go:47-54)."""
    if name not in BINPACK_FUNCTIONS:
        name = TIGHTLY_PACK
    return Binpacker(name=name, is_single_az=name in SINGLE_AZ_PACKERS)
