"""Failover reconciliation — rebuild durable state from observed pods.

Rebuilds internal/extender/failover.go:35-432. Reservation writes are async
and fire-and-forget, so a leader change can lose writes; before serving, the
new leader walks every scheduled spark pod that has no claimed reservation
slot and (a) patches existing reservations to re-claim executors, (b)
constructs new reservations for stale drivers (greedily reserving nodes for
min-executors not yet seen), (c) rebuilds the in-memory soft-reservation
store, and (d) deletes demands of now-scheduled pods.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_scheduler_tpu.models.kube import Node, Pod
from spark_scheduler_tpu.models.reservations import (
    Reservation,
    executor_reservation_name,
    new_resource_reservation,
)
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.core.sparkpods import (
    ROLE_DRIVER,
    ROLE_EXECUTOR,
    SPARK_APP_ID_LABEL,
    SPARK_ROLE_LABEL,
    SPARK_SCHEDULER_NAME,
    SparkPodError,
    find_instance_group,
    spark_resources,
)


@dataclasses.dataclass
class _StaleAppPods:
    """sparkPods (failover.go:75-83): one app's unclaimed scheduled pods."""

    app_id: str
    inconsistent_driver: Optional[Pod] = None
    inconsistent_executors: list[Pod] = dataclasses.field(default_factory=list)


class FailoverReconciler:
    def __init__(
        self,
        backend,
        pod_lister,
        rr_cache,
        soft_store,
        demand_manager,
        overhead_computer,
        instance_group_label: str,
    ):
        self._backend = backend
        self._pod_lister = pod_lister
        self._rr_cache = rr_cache
        self._soft_store = soft_store
        self._demands = demand_manager
        self._overhead = overhead_computer
        self._instance_group_label = instance_group_label
        # Mutation counters of the pass in flight (see the sync method).
        self._summary: dict[str, int] = {}

    # ------------------------------------------------------------------ API

    def sync_resource_reservations_and_demands(self) -> dict:
        """One reconciliation pass. Returns a mutation summary
        ({stale_apps, created, patched, soft_added}) — all zeros on a
        repeat pass over unchanged state: reconciliation is IDEMPOTENT
        (re-claimed pods leave the stale set, create-or-update converges,
        soft-shell creation is if-not-exists), which is what lets two
        racing replicas both run it without duplicating reservations
        (pinned by tests/test_ha.py)."""
        self._summary = {
            "stale_apps": 0, "created": 0, "patched": 0, "soft_added": 0,
        }
        pods = self._backend.list_pods()
        rrs = self._rr_cache.list()
        stale = self._unreserved_spark_pods(rrs, pods)
        self._summary["stale_apps"] = len(stale)

        if stale:
            # The per-group availability map (an O(nodes) walk of Resources
            # copies) exists only to greedily place stale drivers' missing
            # executors — build it lazily. The common pass (HA promotion
            # over tailed-warm state, the gap-heuristic resync on a healthy
            # leader) has ZERO stale apps and stays O(pods + reservations),
            # which is what makes warm promotion fast at 100k nodes.
            nodes = self._backend.list_nodes()
            overhead = self._overhead.get_overhead(nodes)
            soft_usage = self._soft_store.used_soft_reservation_resources()
            available, ordered_nodes = self._available_per_instance_group(
                rrs, nodes, overhead, soft_usage
            )
        extra_executors_by_app: dict[str, list[Pod]] = {}
        for sp in stale.values():
            extras = self._sync_resource_reservations(sp, available, ordered_nodes)
            if extras:
                extra_executors_by_app[sp.app_id] = extras
            self._sync_demands(sp)
        self._sync_soft_reservations(extra_executors_by_app)
        return dict(self._summary)

    # ----------------------------------------------------------- inventory

    def _unreserved_spark_pods(self, rrs, pods) -> dict[str, _StaleAppPods]:
        """Scheduled spark pods claimed by no reservation, grouped by app
        (failover.go:233-270).

        Documented deviation: TERMINATED pods are skipped. The reference's
        filter (failover.go:272-274) checks only scheduler/deletion/node,
        so until a dead executor's object is deleted it would re-claim a
        slot or re-add a soft reservation for the corpse — over-committing
        the node against the live pods that replaced it (caught by the
        invariant soak). Terminated pods free their resources; reconciling
        them back is never right."""
        claimed = set()
        for rr in rrs:
            claimed.update(rr.status.pods.values())
        out: dict[str, _StaleAppPods] = {}
        for pod in pods:
            if (
                pod.scheduler_name != SPARK_SCHEDULER_NAME
                or pod.deletion_timestamp is not None
                or pod.is_terminated()
                or not pod.node_name
                or pod.name in claimed
            ):
                continue
            role = pod.labels.get(SPARK_ROLE_LABEL)
            if role == ROLE_EXECUTOR and self._soft_store.executor_has_soft_reservation(pod):
                continue
            app_id = pod.labels.get(SPARK_APP_ID_LABEL, "")
            sp = out.setdefault(app_id, _StaleAppPods(app_id=app_id))
            if role == ROLE_DRIVER:
                sp.inconsistent_driver = pod
            elif role == ROLE_EXECUTOR:
                sp.inconsistent_executors.append(pod)
        return out

    def _available_per_instance_group(
        self, rrs, nodes: list[Node], overhead, soft_usage
    ) -> tuple[dict[str, dict[str, Resources]], dict[str, list[Node]]]:
        """Schedulable+ready nodes grouped by instance group, newest first;
        available = allocatable - reservations - overhead - soft usage
        (failover.go:276-313)."""
        nodes = sorted(nodes, key=lambda n: n.creation_timestamp, reverse=True)
        grouped: dict[str, list[Node]] = {}
        for n in nodes:
            if n.unschedulable or not n.ready:
                continue
            grouped.setdefault(n.labels.get(self._instance_group_label, ""), []).append(n)

        usage: dict[str, Resources] = {}
        for rr in rrs:
            for res in rr.spec.reservations.values():
                usage.setdefault(res.node, Resources.zero()).add(res.resources)
        for source in (overhead, soft_usage):
            for node_name, res in source.items():
                usage.setdefault(node_name, Resources.zero()).add(res)

        available: dict[str, dict[str, Resources]] = {}
        for group, ns in grouped.items():
            available[group] = {
                n.name: n.allocatable.copy().sub(usage.get(n.name, Resources.zero()))
                for n in ns
            }
        return available, grouped

    # ------------------------------------------------------- reservations

    def _sync_resource_reservations(
        self, sp: _StaleAppPods, available, ordered_nodes
    ) -> list[Pod]:
        """Returns executors that still have no hard slot (soft candidates)
        (failover.go:95-155)."""
        if sp.inconsistent_driver is None and sp.inconsistent_executors:
            exec0 = sp.inconsistent_executors[0]
            rr = self._rr_cache.get(exec0.namespace, sp.app_id)
            if rr is None:
                return []
            new_rr = self._patch_resource_reservation(sp.inconsistent_executors, rr.copy())
            if new_rr is None:
                return []
            self._summary["patched"] = self._summary.get("patched", 0) + 1
            claimed = set(new_rr.status.pods.values())
            return [e for e in sp.inconsistent_executors if e.name not in claimed]

        if sp.inconsistent_driver is not None:
            driver = sp.inconsistent_driver
            try:
                app_resources = spark_resources(driver)
            except SparkPodError:
                return []
            group = find_instance_group(driver, self._instance_group_label) or ""
            end = min(len(sp.inconsistent_executors), app_resources.min_executor_count)
            up_to_min = sp.inconsistent_executors[:end]
            extras = sp.inconsistent_executors[end:]

            group_nodes = ordered_nodes.get(group)
            group_avail = available.get(group)
            if group_nodes is None or group_avail is None:
                return []

            to_assign = app_resources.min_executor_count - len(up_to_min)
            reserved_names: list[str] = []
            reserved_usage: dict[str, Resources] = {}
            if to_assign > 0:
                reserved_names, reserved_usage = _find_nodes(
                    to_assign,
                    app_resources.executor_resources,
                    group_avail,
                    group_nodes,
                )
            executor_nodes = [e.node_name for e in up_to_min] + reserved_names
            rr = new_resource_reservation(
                driver.node_name,
                executor_nodes,
                driver,
                app_resources.driver_resources,
                app_resources.executor_resources,
            )
            for i, e in enumerate(up_to_min):
                rr.status.pods[executor_reservation_name(i)] = e.name
            if not self._rr_cache.create(rr):
                # already exists -> force update (failover.go:141-150)
                existing = self._rr_cache.get(rr.namespace, rr.name)
                if existing is not None:
                    rr.resource_version = existing.resource_version
                if not self._rr_cache.update(rr):
                    return []
            self._summary["created"] = self._summary.get("created", 0) + 1
            for node_name, res in reserved_usage.items():
                if node_name in group_avail:
                    group_avail[node_name].sub(res)
            return extras
        return []

    def _patch_resource_reservation(self, execs: list[Pod], rr):
        """Re-claim reservation slots on each executor's node when the slot
        is unclaimed or its pod is gone/dead (failover.go:316-336)."""
        for e in execs:
            for name, reservation in rr.spec.reservations.items():
                if reservation.node != e.node_name:
                    continue
                current = rr.status.pods.get(name)
                if current is None:
                    rr.status.pods[name] = e.name
                    break
                pod = self._backend.get("pods", e.namespace, current)
                if pod is None or pod.is_terminated():
                    rr.status.pods[name] = e.name
                    break
        if not self._rr_cache.update(rr):
            return None
        return rr

    # ------------------------------------------------------------- demands

    def _sync_demands(self, sp: _StaleAppPods) -> None:
        if sp.inconsistent_driver is not None:
            self._demands.delete_demand_if_exists(sp.inconsistent_driver, "Reconciler")
        for e in sp.inconsistent_executors:
            self._demands.delete_demand_if_exists(e, "Reconciler")

    # ---------------------------------------------------- soft reservations

    def _sync_soft_reservations(self, extras_by_app: dict[str, list[Pod]]) -> None:
        """(failover.go:164-231): recreate app shells for all running
        dynamic-allocation drivers, then re-add extra-executor reservations
        up to max-min."""
        for d in self._backend.list_pods(labels={SPARK_ROLE_LABEL: ROLE_DRIVER}):
            if (
                d.scheduler_name != SPARK_SCHEDULER_NAME
                or not d.node_name
                or d.phase in ("Succeeded", "Failed")
            ):
                continue
            try:
                app_resources = spark_resources(d)
            except SparkPodError:
                continue
            if app_resources.max_executor_count > app_resources.min_executor_count:
                self._soft_store.create_soft_reservation_if_not_exists(
                    d.labels.get(SPARK_APP_ID_LABEL, "")
                )

        for app_id, extras in extras_by_app.items():
            driver = self._pod_lister.get_driver_for_executor(extras[0])
            if driver is None:
                continue
            try:
                app_resources = spark_resources(driver)
            except SparkPodError:
                continue
            allowed = app_resources.max_executor_count - app_resources.min_executor_count
            for i, extra in enumerate(extras):
                if i >= allowed:
                    break
                try:
                    self._soft_store.add_reservation_for_pod(
                        app_id,
                        extra.name,
                        Reservation(
                            extra.node_name, app_resources.executor_resources.copy()
                        ),
                    )
                    self._summary["soft_added"] = (
                        self._summary.get("soft_added", 0) + 1
                    )
                except KeyError:
                    pass  # app shell missing (not dynamic-allocation) — skip


def _find_nodes(
    executor_count: int,
    executor_resources: Resources,
    available: dict[str, Resources],
    ordered_nodes: list[Node],
) -> tuple[list[str], dict[str, Resources]]:
    """Greedy fallback packer for reconciliation (failover.go:402-426):
    fill newest-first schedulable nodes; may return fewer than requested."""
    names: list[str] = []
    reserved: dict[str, Resources] = {}
    for n in ordered_nodes:
        res = reserved.setdefault(n.name, Resources.zero())
        avail = available.get(n.name, Resources.zero())
        while True:
            res.add(executor_resources)
            if res.greater_than(avail):
                res.sub(executor_resources)
                break
            names.append(n.name)
            if len(names) == executor_count:
                return names, reserved
    return names, reserved
