"""GreedyFallbackSolver — degraded-mode serving on the host.

When every device slot is quarantined (or the single device died
mid-window) and `server.degraded-mode` is "greedy", the solver routes the
window through this class instead of a device program: each request packs
via the promoted greedy oracle (core/greedy.py) with the SAME segment
semantics as the batched kernel — availability rewinds to the threaded
committed base per segment, priority orders compute once from the
segment-start availability, hypothetical earlier-driver rows subtract
only within their segment, the commit row's admission persists into the
base, and a non-skippable miss blocks the rest of the segment.

O(nodes) Python per row instead of one device scan — decisions/s drops,
correctness doesn't (the oracle is slot-for-slot the kernels' semantics,
pinned by the golden parity suite and the degraded-equivalence test).
"""

from __future__ import annotations

import numpy as np

from spark_scheduler_tpu.core.greedy import (
    greedy_priority_order,
    greedy_single_az_bin_pack,
    greedy_spark_bin_pack,
)
from spark_scheduler_tpu.ops.efficiency import avg_packing_efficiency_np


class GreedyFallbackSolver:
    """Bound to one PlacementSolver for its registry and candidate-mask
    cache; stateless otherwise."""

    def __init__(self, solver):
        self._solver = solver

    # -- one gang -----------------------------------------------------------

    def _orders(self, strategy, host, avail64, cand_mask, dom_mask):
        dom = np.asarray(dom_mask, bool) & np.asarray(host.valid, bool)
        d_elig = dom & np.asarray(cand_mask, bool)
        e_elig = (
            dom
            & ~np.asarray(host.unschedulable, bool)
            & np.asarray(host.ready, bool)
        )
        zone = np.asarray(host.zone_id)
        names = np.asarray(host.name_rank)
        d_order = greedy_priority_order(
            avail64, zone, names, d_elig, domain=dom,
            label_rank=np.asarray(host.label_rank_driver),
        )
        e_order = greedy_priority_order(
            avail64, zone, names, e_elig, domain=dom,
            label_rank=np.asarray(host.label_rank_executor),
        )
        return d_order, e_order

    def _pack_once(
        self, strategy, host, avail64, d_order, e_order, drv64, exc64, count
    ):
        """One pack against the CURRENT availability with PRECOMPUTED
        orders (the kernel computes orders once per segment and reuses
        them while availability mutates)."""
        if strategy.startswith("single-az-"):
            fill = strategy[len("single-az-"):]
            return greedy_single_az_bin_pack(
                avail64, np.asarray(host.schedulable).astype(np.int64),
                np.asarray(host.zone_id), drv64, exc64, count,
                d_order, e_order, fill,
            )
        d, ex, ok, _ = greedy_spark_bin_pack(
            avail64, drv64, exc64, count, d_order, e_order, strategy
        )
        return d, list(ex) if ok else [], ok

    def pack(
        self, strategy, host, driver_resources, executor_resources,
        executor_count, driver_mask, domain_mask,
    ):
        """Solo-pack fallback: HostPacking from host-side greedy (the
        degraded twin of PlacementSolver.pack)."""
        from spark_scheduler_tpu.core.solver import HostPacking

        avail64 = np.asarray(host.available).astype(np.int64)
        drv64 = driver_resources.as_array().astype(np.int64)
        exc64 = executor_resources.as_array().astype(np.int64)
        d_order, e_order = self._orders(
            strategy, host, avail64, driver_mask, domain_mask
        )
        d, ex, ok = self._pack_once(
            strategy, host, avail64, d_order, e_order, drv64, exc64,
            executor_count,
        )
        eff = avg_packing_efficiency_np(
            np.asarray(host.schedulable),
            avail64,
            d,
            np.asarray(ex if ex else [-1], np.int64),
            drv64,
            exc64,
        )
        registry = self._solver.registry
        return HostPacking(
            driver_node=registry.name_of(d) if d >= 0 else None,
            executor_nodes=[registry.name_of(i) for i in ex],
            has_capacity=ok,
            efficiency_max=float(eff.max),
            efficiency_cpu=float(eff.cpu),
            efficiency_memory=float(eff.memory),
            efficiency_gpu=float(eff.gpu),
        )

    # -- one serving window -------------------------------------------------

    def window_decisions(self, strategy, host, base_avail, requests):
        """The degraded twin of pack_window_dispatch+fetch: decisions for
        a window of WindowRequests against `base_avail` (the committed
        base the device would have seen — host truth minus un-applied
        prior windows). Returns (decisions, placements[N,3] int64)."""
        from spark_scheduler_tpu.core.solver import (
            HostPacking,
            WindowDecision,
        )

        solver = self._solver
        registry = solver.registry
        valid = np.asarray(host.valid, bool)
        sched = np.asarray(host.schedulable)
        base = np.asarray(base_avail).astype(np.int64).copy()
        placements = np.zeros_like(base)
        decisions: list[WindowDecision] = []
        for req in requests:
            cand = solver.candidate_mask(host, req.driver_candidate_names)
            if req.domain_mask is not None:
                dom = np.asarray(req.domain_mask) & valid
            elif req.domain_node_names is not None:
                dom = (
                    solver.candidate_mask(host, req.domain_node_names) & valid
                )
            else:
                dom = valid
            seg_avail = base.copy()
            d_order, e_order = self._orders(
                strategy, host, seg_avail, cand, dom
            )
            blocked = False
            earlier_blocked = False
            last = len(req.rows) - 1
            real_admitted = False
            real_d, real_ex = -1, []
            real_packed = False
            eff = None
            drv64_real = exc64_real = None
            for j, row in enumerate(req.rows):
                drv64 = row[0].as_array().astype(np.int64)
                exc64 = row[1].as_array().astype(np.int64)
                count, skip = int(row[2]), bool(row[3])
                d, ex, packed = self._pack_once(
                    strategy, host, seg_avail, d_order, e_order,
                    drv64, exc64, count,
                )
                admitted = packed and not blocked
                if j == last:
                    real_admitted = admitted
                    real_packed = packed
                    if admitted:
                        real_d, real_ex = d, ex
                        drv64_real, exc64_real = drv64, exc64
                        eff = avg_packing_efficiency_np(
                            sched, seg_avail, d,
                            np.asarray(ex if ex else [-1], np.int64),
                            drv64, exc64,
                        )
                    break
                if admitted:
                    seg_avail[d] -= drv64
                    for n in ex:
                        seg_avail[n] -= exc64
                if not packed and not skip:
                    blocked = True
                    earlier_blocked = True
            if real_admitted:
                base[real_d] -= drv64_real
                placements[real_d] += drv64_real
                for n in real_ex:
                    base[n] -= exc64_real
                    placements[n] += exc64_real
            decisions.append(
                WindowDecision(
                    packing=HostPacking(
                        driver_node=(
                            registry.name_of(real_d) if real_d >= 0 else None
                        ),
                        executor_nodes=[
                            registry.name_of(n) for n in real_ex
                        ],
                        has_capacity=real_packed,
                        efficiency_max=float(eff.max) if eff else 0.0,
                        efficiency_cpu=float(eff.cpu) if eff else 0.0,
                        efficiency_memory=float(eff.memory) if eff else 0.0,
                        efficiency_gpu=float(eff.gpu) if eff else 0.0,
                    ),
                    admitted=real_admitted,
                    earlier_blocked=earlier_blocked,
                )
            )
        return decisions, placements
