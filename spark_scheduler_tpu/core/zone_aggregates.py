"""ZoneAggregates — resident per-zone availability totals for the prune
planner (ISSUE 12, the census/soft-mirror pattern applied to the two-tier
solve's tier-1 offsets).

The prune planner used to derive each window's `zone_base` offsets —
the excluded rows' per-zone availability sums that keep the gathered
sub-cluster's zone ranks byte-exact (ops/sorting.zone_ranks) — by
summing over the N−K excluded rows per window: a bincount over the whole
roster, the measured residual behind the 1M-tier window costing ~7x the
100k number in the same run (PERFORMANCE.md "Million-node tier").

This module keeps the per-zone totals RESIDENT and event-maintained,
exactly like the soft-usage mirror and the control-loop census of PR 11:

  cnt[z]   number of valid rows in zone z;
  mem[z] / cpu[z]
           int64 sums of available memory / cpu over the valid rows of
           zone z — EXACT integer arithmetic (the legacy per-window
           bincount accumulated in float64 and needed a slow np.add.at
           guard past 2^22 rows; the incremental int64 sums never do).

`update_rows` applies a set of changed rows in O(changed): each row's
old contribution (from the int64 snapshots kept here) is subtracted and
its new contribution added, handling validity flips and zone moves
(static row-deltas) in the same pass. The planner then derives a
window's excluded sums as `total − Σ kept` in O(K).

`diff_rows` is the resync fallback: when a serving path that does not
report its placement rows touched the availability (a dense unpruned
fetch in a mixed workload), the planner asks for the rows whose host
availability drifted from the snapshots — one vectorized compare, the
cost the explicit dirty-row plumbing normally avoids.

`rebuild` is the from-scratch oracle (attach/invalidate path) and the
consistency tests' twin.
"""

from __future__ import annotations

import numpy as np

from spark_scheduler_tpu.models.resources import CPU_DIM, GPU_DIM, MEM_DIM


class ZoneAggregates:
    __slots__ = (
        "_mem", "_cpu", "_gpu", "_valid", "_zone",
        "cnt", "mem", "cpu", "num_zones",
        "rebuilds", "updates", "rows_applied",
    )

    def __init__(self):
        self._mem: np.ndarray | None = None  # [N] int64 snapshots
        self._cpu: np.ndarray | None = None
        self._gpu: np.ndarray | None = None
        self._valid: np.ndarray | None = None  # [N] bool
        self._zone: np.ndarray | None = None  # [N] int32
        self.cnt: np.ndarray | None = None  # [Zb] int64
        self.mem: np.ndarray | None = None  # [Zb] int64
        self.cpu: np.ndarray | None = None  # [Zb] int64
        self.num_zones = 0
        self.rebuilds = 0
        self.updates = 0
        self.rows_applied = 0

    @property
    def valid(self) -> bool:
        return self._mem is not None

    def invalidate(self) -> None:
        self._mem = None

    def rebuild(
        self,
        avail: np.ndarray,  # [N,3] int32 host availability
        zone_id: np.ndarray,  # [N] int32
        valid: np.ndarray,  # [N] bool
        num_zones: int,
    ) -> None:
        self._mem = avail[:, MEM_DIM].astype(np.int64)
        self._cpu = avail[:, CPU_DIM].astype(np.int64)
        self._gpu = avail[:, GPU_DIM].astype(np.int64)
        self._valid = np.asarray(valid, bool).copy()
        self._zone = np.asarray(zone_id).astype(np.int32)
        self.num_zones = int(num_zones)
        vz = self._zone[self._valid]
        self.cnt = np.bincount(vz, minlength=num_zones).astype(np.int64)
        # int64 integer sums — exact at any roster size.
        self.mem = np.zeros(num_zones, np.int64)
        self.cpu = np.zeros(num_zones, np.int64)
        np.add.at(self.mem, vz, self._mem[self._valid])
        np.add.at(self.cpu, vz, self._cpu[self._valid])
        self.rebuilds += 1

    def update_rows(
        self,
        avail: np.ndarray,
        zone_id: np.ndarray,
        valid: np.ndarray,
        rows: np.ndarray,
    ) -> None:
        """Apply the changed rows' new (availability, validity, zone)
        state to the totals and snapshots — O(changed)."""
        rows = np.asarray(rows)
        if rows.size == 0:
            return
        rows = np.unique(rows)
        old_v = self._valid[rows]
        old_z = self._zone[rows]
        # Remove old contributions (valid rows only).
        ov = rows[old_v]
        if ov.size:
            oz = self._zone[ov]
            np.add.at(self.cnt, oz, -1)
            np.add.at(self.mem, oz, -self._mem[ov])
            np.add.at(self.cpu, oz, -self._cpu[ov])
        new_v = np.asarray(valid, bool)[rows]
        new_z = np.asarray(zone_id)[rows].astype(np.int32)
        new_mem = avail[rows, MEM_DIM].astype(np.int64)
        new_cpu = avail[rows, CPU_DIM].astype(np.int64)
        nv = new_v.nonzero()[0]
        if nv.size:
            nz = new_z[nv]
            np.add.at(self.cnt, nz, 1)
            np.add.at(self.mem, nz, new_mem[nv])
            np.add.at(self.cpu, nz, new_cpu[nv])
        self._mem[rows] = new_mem
        self._cpu[rows] = new_cpu
        self._gpu[rows] = avail[rows, GPU_DIM].astype(np.int64)
        self._valid[rows] = new_v
        self._zone[rows] = new_z
        self.updates += 1
        self.rows_applied += int(rows.size)

    def diff_rows(self, avail: np.ndarray) -> np.ndarray:
        """Rows whose host availability drifted from the snapshots (any
        dim) — the O(N) resync fallback for un-reported churn."""
        return np.flatnonzero(
            (self._mem != avail[:, MEM_DIM])
            | (self._cpu != avail[:, CPU_DIM])
            | (self._gpu != avail[:, GPU_DIM])
        )

    def zone_of(self, rows: np.ndarray) -> np.ndarray:
        """Snapshot zone of `rows` (pre-update classification)."""
        return self._zone[rows]

    def valid_of(self, rows: np.ndarray) -> np.ndarray:
        return self._valid[rows]

    def mem_of(self, rows: np.ndarray) -> np.ndarray:
        """Snapshot available-memory of `rows` — the OLD contribution a
        per-domain total must subtract before applying the new state
        (core/prune.py domain plan contexts)."""
        return self._mem[rows]

    def cpu_of(self, rows: np.ndarray) -> np.ndarray:
        return self._cpu[rows]

    def stats(self) -> dict:
        return {
            "rebuilds": self.rebuilds,
            "updates": self.updates,
            "rows_applied": self.rows_applied,
            "zones": int((self.cnt > 0).sum()) if self.cnt is not None else 0,
        }
