"""ClusterCensus — incrementally-maintained control-loop state.

The autoscaler and scale-down drainer used to re-derive their world on
every pass: `len(backend.list_nodes())` for the cluster size (three times
per autoscaler pass), a full walk of every reservation of every app plus
every pod for the drainer's never-drain/busy census — O(nodes + pods +
apps x slots) Python per pass even when nothing changed. At the
million-node tier those passes dominate the control plane.

This census keeps the same answers RESIDENT and event-maintained, the
feature-store pattern applied to the control loops:

  node mirror       {name: Node} + O(1) count, fed by backend node events;
                    optionally an `eligible` subset indexed by one label
                    (the drainer's provisioned-by filter), so a drain pass
                    scans the elastic fleet, not the whole cluster.
  busy pods         per-node refcount of bound, non-terminated pods, fed
                    by backend pod events.
  reserved nodes    per-node refcount of hard reservation slots (rr-cache
                    mutation listener, the cache-owner invariant the
                    ReservedUsageTracker rides) + soft reservations (the
                    store's delta listeners). Refcounted, not summed: a
                    zero-resource reservation still pins its node.

Every query is O(1) or O(answer); every event costs O(changed). `rebuild()`
recomputes from the sources — the attach-time oracle and the consistency
tests' from-scratch twin.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from spark_scheduler_tpu.models.kube import Node, Pod
from spark_scheduler_tpu.store.cache import BatchableListener


class ClusterCensus:
    def __init__(
        self,
        backend,
        rr_cache=None,
        soft_store=None,
        eligible_label: tuple[str, str] | None = None,
    ):
        self._backend = backend
        self._rr_cache = rr_cache
        self._soft_store = soft_store
        self._eligible_label = eligible_label
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}
        self._eligible: dict[str, Node] = {}
        self._pods_on_node: dict[str, int] = {}
        self._reserved_refs: dict[str, int] = {}
        # Instrumentation — the O(changed) claim as counters.
        self.events_applied = 0
        self.rebuilds = 0
        backend.subscribe(
            "nodes",
            on_add=self._on_node_add,
            on_update=self._on_node_update,
            on_delete=self._on_node_delete,
        )
        backend.subscribe(
            "pods",
            on_add=self._on_pod_add,
            on_update=self._on_pod_update,
            on_delete=self._on_pod_delete,
        )
        if rr_cache is not None:
            rr_cache.add_mutation_listener(
                BatchableListener(self._on_rr_mutation, self._on_rr_batch)
            )
        if soft_store is not None:
            soft_store.add_delta_listener(self._on_soft_delta)
        self.rebuild()

    # -- queries -------------------------------------------------------------

    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(name)

    def nodes_view(self) -> dict[str, Node]:
        """Snapshot of the full node mirror (O(nodes) copy — callers that
        only need the eligible subset should use eligible_view)."""
        with self._lock:
            return dict(self._nodes)

    def eligible_view(self) -> dict[str, Node]:
        """Snapshot of the label-eligible subset (O(eligible))."""
        with self._lock:
            if self._eligible_label is None:
                return dict(self._nodes)
            return dict(self._eligible)

    def is_busy(self, name: str) -> bool:
        """Node has a bound non-terminated pod OR any hard/soft
        reservation names it — the drainer's never-drain test, O(1)."""
        with self._lock:
            return (
                self._pods_on_node.get(name, 0) > 0
                or self._reserved_refs.get(name, 0) > 0
            )

    def reserved_node_names(self) -> set[str]:
        with self._lock:
            return set(self._reserved_refs)

    def stats(self) -> dict:
        with self._lock:
            return {
                "nodes": len(self._nodes),
                "eligible": len(self._eligible),
                "busy_nodes": sum(
                    1 for v in self._pods_on_node.values() if v > 0
                ),
                "reserved_nodes": len(self._reserved_refs),
                "events_applied": self.events_applied,
                "rebuilds": self.rebuilds,
            }

    # -- maintenance ---------------------------------------------------------

    def rebuild(self) -> None:
        """Recompute every aggregate from the sources (the from-scratch
        oracle; also the attach-time initializer)."""
        with self._lock:
            self._nodes = {n.name: n for n in self._backend.list_nodes()}
            self._eligible = {
                name: n
                for name, n in self._nodes.items()
                if self._is_eligible(n)
            }
            self._pods_on_node = {}
            for pod in self._backend.list("pods"):
                node = self._pod_contrib(pod)
                if node is not None:
                    self._pods_on_node[node] = (
                        self._pods_on_node.get(node, 0) + 1
                    )
            self._reserved_refs = {}
            if self._rr_cache is not None:
                for rr in self._rr_cache.list():
                    for res in rr.spec.reservations.values():
                        self._ref(res.node, +1)
            if self._soft_store is not None:
                for sr in self._soft_store.get_all_copy().values():
                    for r in sr.reservations.values():
                        self._ref(r.node, +1)
            self.rebuilds += 1

    def _is_eligible(self, node: Node) -> bool:
        if self._eligible_label is None:
            return True
        key, value = self._eligible_label
        return node.labels.get(key) == value

    @staticmethod
    def _pod_contrib(pod: Pod) -> Optional[str]:
        if pod.node_name and not pod.is_terminated():
            return pod.node_name
        return None

    def _ref(self, node: str, sign: int) -> None:
        refs = self._reserved_refs.get(node, 0) + sign
        if refs <= 0:
            self._reserved_refs.pop(node, None)
        else:
            self._reserved_refs[node] = refs

    # -- node events ---------------------------------------------------------

    def _on_node_add(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node
            if self._is_eligible(node):
                self._eligible[node.name] = node
            else:
                self._eligible.pop(node.name, None)
            self.events_applied += 1

    def _on_node_update(self, _old: Node, new: Node) -> None:
        self._on_node_add(new)

    def _on_node_delete(self, node: Node) -> None:
        with self._lock:
            self._nodes.pop(node.name, None)
            self._eligible.pop(node.name, None)
            self.events_applied += 1

    # -- pod events ----------------------------------------------------------

    def _pod_delta(self, node: Optional[str], sign: int) -> None:
        if node is None:
            return
        cnt = self._pods_on_node.get(node, 0) + sign
        if cnt <= 0:
            self._pods_on_node.pop(node, None)
        else:
            self._pods_on_node[node] = cnt

    def _on_pod_add(self, pod: Pod) -> None:
        with self._lock:
            self._pod_delta(self._pod_contrib(pod), +1)
            self.events_applied += 1

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        with self._lock:
            self._pod_delta(self._pod_contrib(old), -1)
            self._pod_delta(self._pod_contrib(new), +1)
            self.events_applied += 1

    def _on_pod_delete(self, pod: Pod) -> None:
        with self._lock:
            self._pod_delta(self._pod_contrib(pod), -1)
            self.events_applied += 1

    # -- reservation events --------------------------------------------------

    def _apply_rr(self, old: Any, new: Any) -> None:
        if (
            old is not None
            and new is not None
            and old.spec.reservations == new.spec.reservations
        ):
            return
        if old is not None:
            for res in old.spec.reservations.values():
                self._ref(res.node, -1)
        if new is not None:
            for res in new.spec.reservations.values():
                self._ref(res.node, +1)

    def _on_rr_mutation(self, old: Any, new: Any) -> None:
        with self._lock:
            self._apply_rr(old, new)
            self.events_applied += 1

    def _on_rr_batch(self, pairs) -> None:
        with self._lock:
            for old, new in pairs:
                self._apply_rr(old, new)
            self.events_applied += 1

    def _on_soft_delta(self, node: str, _resources, sign: int) -> None:
        with self._lock:
            self._ref(node, sign)
            self.events_applied += 1
