"""Unschedulable-pod marker.

Rebuilds internal/extender/unschedulablepods.go:40-188: periodically scan
pending drivers older than the timeout and check whether the gang could fit
an EMPTY cluster (zero usage, only non-schedulable overhead); set the
`PodExceedsClusterCapacity` condition accordingly (both directions, so a
cluster scale-up clears it).
"""

from __future__ import annotations

import threading
import time

from spark_scheduler_tpu.models.kube import Pod, PodCondition
from spark_scheduler_tpu.core.binpacker import Binpacker
from spark_scheduler_tpu.core.solver import PlacementSolver
from spark_scheduler_tpu.core.sparkpods import (
    ROLE_DRIVER,
    SPARK_ROLE_LABEL,
    SPARK_SCHEDULER_NAME,
    SparkPodError,
    pod_matches_node,
    spark_resources,
)

POD_EXCEEDS_CLUSTER_CAPACITY_CONDITION = "PodExceedsClusterCapacity"
POLLING_INTERVAL_S = 60.0  # unschedulablePollingInterval
DEFAULT_TIMEOUT_S = 600.0  # 10 min default (unschedulablepods.go:61-63)


class UnschedulablePodMarker:
    def __init__(
        self,
        backend,
        overhead_computer,
        binpacker: Binpacker,
        solver: PlacementSolver,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        clock=time.time,
    ):
        self._backend = backend
        self._overhead = overhead_computer
        self._binpacker = binpacker
        self._solver = solver
        self._timeout_s = timeout_s if timeout_s > 0 else DEFAULT_TIMEOUT_S
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="unschedulable-marker"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(POLLING_INTERVAL_S):
            try:
                self.scan_for_unschedulable_pods()
            except Exception:  # background loop must not die
                pass

    def scan_for_unschedulable_pods(self) -> None:
        now = self._clock()
        for pod in self._backend.list_pods():
            if (
                pod.scheduler_name == SPARK_SCHEDULER_NAME
                and not pod.node_name
                and pod.deletion_timestamp is None
                and pod.labels.get(SPARK_ROLE_LABEL) == ROLE_DRIVER
                and pod.creation_timestamp + self._timeout_s < now
            ):
                try:
                    exceeds = self.does_pod_exceed_cluster_capacity(pod)
                except SparkPodError:
                    continue
                pod.set_condition(
                    PodCondition(
                        type=POD_EXCEEDS_CLUSTER_CAPACITY_CONDITION,
                        status=exceeds,
                        last_transition_time=now,
                    )
                )

    def does_pod_exceed_cluster_capacity(self, driver: Pod) -> bool:
        """Gang-fit against empty-cluster capacity (unschedulablepods.go:131-170)."""
        nodes = [
            n for n in self._backend.list_nodes() if pod_matches_node(driver, n)
        ]
        overhead = self._overhead.get_non_schedulable_overhead(nodes)
        tensors = self._solver.build_tensors(nodes, {}, overhead)
        app_resources = spark_resources(driver)
        packing = self._solver.pack(
            self._binpacker.name,
            tensors,
            app_resources.driver_resources,
            app_resources.executor_resources,
            app_resources.min_executor_count,
            [n.name for n in nodes],
        )
        return not packing.has_capacity
