"""Host-side greedy packing — the reference's literal semantics in Python.

Promoted from tests/greedy_oracle.py (ISSUE 9): per-slot reimplementation
of the reference's packing (binpack/pack_tightly.go, distribute_evenly.go,
minimal_fragmentation.go, binpack.go, single_az.go, sort/nodesorting.go)
over numpy arrays. Two consumers:

  - the golden parity suite (tests/ imports it via the old path): the
    vectorized XLA kernels must reproduce these placements slot-for-slot;
  - DEGRADED-MODE serving (core/fallback.py): when every device slot is
    quarantined, the extender packs on the host through these functions —
    same decisions the device would have made, O(nodes) Python instead of
    one device program.

Nodes are integer indices; resources are [3] int arrays (same fixed-point
units as the framework).
"""

from __future__ import annotations

import numpy as np

INF = 10**9


def greedy_fits(avail, req) -> bool:
    return bool(np.all(req <= avail))


def greedy_capacity(avail, reserved, req) -> int:
    cap = INF
    for d in range(3):
        if reserved[d] > avail[d]:
            return 0
        if req[d] == 0:
            continue
        cap = min(cap, (avail[d] - reserved[d]) // req[d])
    return max(int(cap), 0)


def greedy_tightly(avail, exec_req, count, order, reserved):
    out = []
    if count == 0:
        return out, True
    for n in order:
        while True:
            reserved[n] = reserved[n] + exec_req
            if np.any(reserved[n] > avail[n]):
                reserved[n] = reserved[n] - exec_req
                break
            out.append(n)
            if len(out) == count:
                return out, True
    return None, False


def greedy_distribute(avail, exec_req, count, order, reserved):
    open_nodes = set(order)
    out = []
    if count == 0:
        return out, True
    while open_nodes:
        for n in order:
            if n not in open_nodes:
                continue
            reserved[n] = reserved[n] + exec_req
            if np.any(reserved[n] > avail[n]):
                open_nodes.discard(n)
                reserved[n] = reserved[n] - exec_req
            else:
                out.append(n)
                if len(out) == count:
                    return out, True
    return None, False


def greedy_minimal_fragmentation(avail, exec_req, count, order, reserved):
    out = []
    if count == 0:
        return out, True
    caps = [
        (n, greedy_capacity(avail[n], reserved.get(n, np.zeros(3, np.int64)), exec_req))
        for n in order
    ]
    caps = [(n, c) for (n, c) in caps if c > 0]
    caps.sort(key=lambda t: t[1])  # stable ascending by capacity
    remaining = count
    while caps:
        fit_all = next((i for i, (_, c) in enumerate(caps) if c >= remaining), None)
        if fit_all is not None:
            out.extend([caps[fit_all][0]] * remaining)
            return out, True
        max_cap = caps[-1][1]
        first_max = next(i for i, (_, c) in enumerate(caps) if c >= max_cap)
        cur = first_max
        while remaining >= max_cap and cur < len(caps):
            out.extend([caps[cur][0]] * max_cap)
            remaining -= max_cap
            cur += 1
        if remaining == 0:
            return out, True
        caps = caps[:first_max] + caps[cur:]
    return None, False


GREEDY_FILLS = {
    "tightly-pack": greedy_tightly,
    "distribute-evenly": greedy_distribute,
    "minimal-fragmentation": greedy_minimal_fragmentation,
}


class _ReservedMap(dict):
    """dict defaulting to a zero resource vector (NodeGroupResources)."""

    def __getitem__(self, k):
        if k not in self:
            dict.__setitem__(self, k, np.zeros(3, np.int64))
        return dict.__getitem__(self, k)


def greedy_spark_bin_pack(
    avail, driver_req, exec_req, count, driver_order, exec_order, fill
):
    """binpack.go:60-87: first driver candidate whose executors still pack."""
    fill_fn = GREEDY_FILLS[fill]
    for d in driver_order:
        if not greedy_fits(avail[d], driver_req):
            continue
        r = _ReservedMap()
        r[d] = driver_req.astype(np.int64).copy()
        nodes, ok = fill_fn(avail, exec_req, count, exec_order, r)
        if ok:
            return d, nodes, True, r
    return -1, [], False, {}


def greedy_priority_order(avail, zone_of, names, eligible, domain=None, label_rank=None):
    """sort/nodesorting.go:84-134: (az priority, mem asc, cpu asc, name),
    then optional stable label-priority re-sort. Zone totals are computed
    over the full metadata `domain` (PotentialNodes sorts the whole domain,
    then filters to eligible, preserving order)."""
    if domain is None:
        domain = eligible
    idxs = [i for i in range(len(names)) if eligible[i]]
    dom = [i for i in range(len(names)) if domain[i]]
    zones = sorted(
        {zone_of[i] for i in dom},
        key=lambda z: (
            sum(int(avail[i][1]) for i in dom if zone_of[i] == z),
            sum(int(avail[i][0]) for i in dom if zone_of[i] == z),
            z,
        ),
    )
    zprio = {z: r for r, z in enumerate(zones)}
    out = sorted(
        idxs,
        key=lambda i: (zprio[zone_of[i]], int(avail[i][1]), int(avail[i][0]), names[i]),
    )
    if label_rank is not None:
        out.sort(key=lambda i: label_rank[i])  # stable
    return out


def greedy_avg_efficiency(
    avail, schedulable, driver, exec_nodes, driver_req, exec_req,
    include_executors_in_reserved=True,
):
    """efficiency.go:107-156 over the packing's entries (duplicates kept),
    with exact (unrounded) ratios. `include_executors_in_reserved=False`
    mirrors minimalFragmentation never mutating reservedResources."""
    entries = ([driver] if driver >= 0 else []) + list(exec_nodes)
    if not entries:
        return 0.0
    new_res = {}
    for n in entries:
        new_res.setdefault(n, np.zeros(3, np.int64))
    new_res[driver] = new_res[driver] + driver_req
    if include_executors_in_reserved:
        for n in exec_nodes:
            new_res[n] = new_res[n] + exec_req
    max_sum = 0.0
    for n in entries:
        reserved = (schedulable[n] - avail[n]) + new_res[n]
        denom = np.where(schedulable[n] == 0, 1, schedulable[n]).astype(float)
        eff = reserved.astype(float) / denom
        gpu_eff = eff[2] if schedulable[n][2] != 0 else 0.0
        max_sum += max(eff[0], eff[1], gpu_eff)
    return max_sum / len(entries)


# --------------------------------------------------------------- strategies


def greedy_single_az_bin_pack(
    avail, sched, zone_of, driver_req, exec_req, count,
    d_order_all, e_order_all, fill,
):
    """single_az.go:23-97: run the inner fill per zone (zones in the
    driver order's first-appearance order), keep the best average
    efficiency; chooseBestResult starts at 0.0 and replaces on strictly
    greater, so zero-efficiency zones are rejected outright."""
    zones_in_order: list = []
    for i in d_order_all:
        if zone_of[i] not in zones_in_order:
            zones_in_order.append(zone_of[i])
    best = None
    for z in zones_in_order:
        d_order = [i for i in d_order_all if zone_of[i] == z]
        e_order = [i for i in e_order_all if zone_of[i] == z]
        if not e_order:
            continue
        d, ex, ok, _ = greedy_spark_bin_pack(
            avail, driver_req, exec_req, count, d_order, e_order, fill
        )
        if not ok:
            continue
        eff = greedy_avg_efficiency(
            avail, sched, d, ex, driver_req, exec_req,
            include_executors_in_reserved=(fill != "minimal-fragmentation"),
        )
        if eff > (best[0] if best is not None else 0.0):
            best = (eff, d, ex)
    if best is None:
        return -1, [], False
    return best[1], list(best[2]), True


def greedy_strategy_pack(
    strategy,
    *,
    avail,
    schedulable,
    zone_of,
    names,
    valid,
    unschedulable,
    ready,
    label_rank_driver,
    label_rank_executor,
    cand_mask,
    domain_mask,
    driver_req,
    exec_req,
    count,
):
    """One gang pack under any registered strategy (the 5-way plug board),
    with the kernels' exact eligibility conventions: the domain is
    `domain & valid`; driver candidates additionally need the candidate
    mask; executors need schedulable (not cordoned) + ready. Returns
    (driver_idx, exec_idx_list, ok). All arrays are host numpy."""
    dom = np.asarray(domain_mask, bool) & np.asarray(valid, bool)
    d_elig = dom & np.asarray(cand_mask, bool)
    e_elig = dom & ~np.asarray(unschedulable, bool) & np.asarray(ready, bool)
    avail64 = np.asarray(avail).astype(np.int64)
    drv64 = np.asarray(driver_req).astype(np.int64)
    exc64 = np.asarray(exec_req).astype(np.int64)
    d_order = greedy_priority_order(
        avail64, zone_of, names, d_elig, domain=dom,
        label_rank=label_rank_driver,
    )
    e_order = greedy_priority_order(
        avail64, zone_of, names, e_elig, domain=dom,
        label_rank=label_rank_executor,
    )
    if strategy.startswith("single-az-"):
        fill = strategy[len("single-az-"):]
        d, ex, ok = greedy_single_az_bin_pack(
            avail64, np.asarray(schedulable).astype(np.int64), zone_of,
            drv64, exc64, count, d_order, e_order, fill,
        )
        return d, ex, ok
    d, ex, ok, _ = greedy_spark_bin_pack(
        avail64, drv64, exc64, count, d_order, e_order, strategy
    )
    return d, list(ex) if ok else [], ok
