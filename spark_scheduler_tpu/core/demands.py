"""Demand lifecycle: creation on failed fits, deletion on success/schedule.

Rebuilds internal/extender/demand.go:58-198 and demand_gc.go:27-51. Demands
are named "demand-<pod>" and carry the resources the pod's application could
not get; the DemandGC deletes a pod's demand when the pod gets scheduled
(covering races the inline deletions miss).
"""

from __future__ import annotations

from typing import Optional

from spark_scheduler_tpu.models.demands import (
    Demand,
    DemandSpec,
    DemandUnit,
    demand_name_for_pod,
)
from spark_scheduler_tpu.models.kube import Pod, PodCondition
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.core.sparkpods import (
    SPARK_APP_ID_LABEL,
    SparkApplicationResources,
    find_instance_group,
    is_spark_scheduler_pod,
)

POD_DEMAND_CREATED_CONDITION = "PodDemandCreated"


class DemandManager:
    def __init__(self, backend, demand_cache, instance_group_label: str,
                 is_single_az_binpacker: bool = False, events=None, waste=None,
                 clock=None):
        import time as _time

        self._backend = backend
        self._cache = demand_cache
        self._instance_group_label = instance_group_label
        self._is_single_az = is_single_az_binpacker
        self._events = events
        self._waste = waste
        self._clock = clock or _time.time

    def deferred_sync(self):
        """Window-scoped write-back batching (WriteThroughCache.deferred_sync)
        for this manager's demand cache."""
        return self._cache.deferred_sync()

    # -- creation -----------------------------------------------------------

    def create_demand_for_application(
        self, driver: Pod, app_resources: SparkApplicationResources
    ) -> Optional[Demand]:
        """Driver unit (count 1, attributed to the driver pod) + one unit of
        min-executor count (demand.go:172-198)."""
        if not self._cache.crd_exists():
            return None
        units = [
            DemandUnit(
                resources=app_resources.driver_resources.copy(),
                count=1,
                pod_names_by_namespace={driver.namespace: [driver.name]},
            )
        ]
        if app_resources.min_executor_count > 0:
            units.append(
                DemandUnit(
                    resources=app_resources.executor_resources.copy(),
                    count=app_resources.min_executor_count,
                )
            )
        return self._create(driver, units, zone=None)

    def create_demand_for_executor(
        self, executor: Pod, executor_resources: Resources, zone: str | None = None
    ) -> Optional[Demand]:
        if not self._cache.crd_exists():
            return None
        units = [
            DemandUnit(
                resources=executor_resources.copy(),
                count=1,
                pod_names_by_namespace={executor.namespace: [executor.name]},
            )
        ]
        return self._create(executor, units, zone=zone)

    def _create(self, pod: Pod, units: list[DemandUnit], zone: str | None) -> Optional[Demand]:
        instance_group = find_instance_group(pod, self._instance_group_label)
        if instance_group is None:
            return None  # no instance group -> skip demand (demand.go:93-99)
        app_id = pod.labels.get(SPARK_APP_ID_LABEL)
        if app_id is None:
            return None
        demand = Demand(
            name=demand_name_for_pod(pod),
            namespace=pod.namespace,
            labels={SPARK_APP_ID_LABEL: app_id},
            owner_pod_uid=pod.uid,
            # creationTimestamp rides the uninterpreted-metadata slot (it
            # survives webhook conversion verbatim); the autoscaler anchors
            # demand-to-fulfilled latency on it.
            metadata_extra={"creationTimestamp": self._clock()},
            spec=DemandSpec(
                instance_group=instance_group,
                units=units,
                enforce_single_zone_scheduling=self._is_single_az,
                zone=zone,
            ),
        )
        created = self._cache.create(demand)
        if not created:
            # already exists for the pod -> no action (demand.go:118-126)
            return self._cache.get(demand.namespace, demand.name)
        if self._events is not None:
            self._events.emit_demand_created(demand)
        if self._waste is not None:
            self._waste.on_demand_created(pod.key)
        pod.set_condition(PodCondition(type=POD_DEMAND_CREATED_CONDITION, status=True))
        return demand

    # -- deletion -----------------------------------------------------------

    def delete_demand_if_exists(self, pod: Pod, source: str = "extender") -> None:
        if not self._cache.crd_exists():
            return
        name = demand_name_for_pod(pod)
        demand = self._cache.get(pod.namespace, name)
        if demand is not None:
            self._cache.delete(pod.namespace, name)
            if self._events is not None:
                self._events.emit_demand_deleted(demand, source)


def start_demand_gc(backend, demand_manager: DemandManager) -> None:
    """Delete a pod's demand when it transitions to scheduled
    (demand_gc.go:35-51 + common/utils/pods.go OnPodScheduled)."""

    def on_update(old: Pod, new: Pod) -> None:
        if not is_spark_scheduler_pod(new):
            return
        if not old.node_name and new.node_name:
            demand_manager.delete_demand_if_exists(new, source="DemandGC")

    backend.subscribe("pods", on_update=on_update)
