"""Incremental reserved-usage aggregate — delta-maintained device-state feed.

The reference rebuilds per-node reservation usage from every reservation of
every application on every request (`GetReservedResources`,
internal/extender/resourcereservations.go:228-233 → `UsageForNodes`,
resources.go:150-166 — an O(apps x slots) walk). That is fine for Go maps at
hundreds of apps; the TPU rebuild targets 1k concurrent apps x 10k nodes
with a <50 ms budget (SURVEY.md §7 "Host↔device latency budget"), where the
per-request walk, not the kernel, becomes the latency floor.

`ReservedUsageTracker` replaces the walk with a dense int64 `[cap, 3]`
usage array over the solver's stable node-index space, maintained by
scatter-add deltas:

  - hard reservations: mutation listener on the ResourceReservation
    write-through cache (the cache owner is the sole writer, so every
    change flows through it — cache.go:27-89 ownership invariant);
  - soft reservations: delta listener on SoftReservationStore.

Per-request cost is O(1): `array()` hands the maintained buffer straight to
`build_cluster_tensors` (a single vectorized pad/copy), and every mutation
costs O(slots of the touched app). `rebuild()` recomputes from scratch —
used at attach time, after failover resyncs, and by the consistency tests
(delta-maintained state == from-scratch rebuild).
"""

from __future__ import annotations

import threading

import numpy as np

from spark_scheduler_tpu.core.dirty_feed import DirtyRowFeed
from spark_scheduler_tpu.models.cluster import NodeRegistry
from spark_scheduler_tpu.models.resources import NUM_DIMS, Resources
from spark_scheduler_tpu.store.cache import BatchableListener


class ReservedUsageTracker:
    def __init__(self, registry: NodeRegistry, rr_cache, soft_store):
        self._registry = registry
        self._rr_cache = rr_cache
        self._soft_store = soft_store
        self._lock = threading.RLock()
        self._dense = np.zeros((0, NUM_DIMS), dtype=np.int64)
        # Monotonic change counter: bumped under the lock by every applied
        # delta / rebuild. The HostFeatureStore keys its zero-copy snapshot
        # on it — an unchanged version proves the cached copy is current.
        self.version = 0
        # Instrumentation: number of scatter deltas applied since attach —
        # the "per-request host work proportional to the delta" evidence.
        self.deltas_applied = 0
        self.rebuilds = 0
        # Dirty-row feed for the HostFeatureStore's resident usage master
        # (ISSUE 13): every scatter records its row so the store patches
        # O(changed) rows instead of copying the whole [cap, 3] array per
        # serving window (core/dirty_feed.py — the drain protocol shared
        # with the overhead mirror).
        self._dirty = DirtyRowFeed()
        # Batch-aware: a serving window's coalesced reservation write-back
        # (create_reservations_batch under rr_cache.deferred_notifications)
        # applies all its per-slot diffs under ONE lock hold instead of one
        # per reservation.
        rr_cache.add_mutation_listener(
            BatchableListener(self._on_rr_mutation, self._on_rr_mutation_batch)
        )
        soft_store.add_delta_listener(self._on_soft_delta)
        self.rebuild()

    # -- queries -------------------------------------------------------------

    def array(self, min_rows: int | None = None) -> np.ndarray:
        """The dense [cap, 3] int64 usage array (a copy, padded to at least
        `min_rows`). One vectorized op per request — no per-reservation walk."""
        with self._lock:
            out = self._dense
            rows = max(min_rows or 0, out.shape[0])
            if rows > out.shape[0]:
                out = np.pad(out, ((0, rows - out.shape[0]), (0, 0)))
            else:
                out = out.copy()
            return out

    def as_map(self) -> dict[str, Resources]:
        """{node: Resources} view for map-shaped consumers (reporters,
        failover). O(nodes with nonzero usage), vectorized scan."""
        with self._lock:
            nz = np.flatnonzero(self._dense.any(axis=1))
            out: dict[str, Resources] = {}
            for idx in nz:
                name = self._registry.name_of(int(idx))
                if name is not None:
                    out[name] = Resources.from_array(self._dense[idx])
            return out

    # -- maintenance ---------------------------------------------------------

    def rebuild(self) -> None:
        """Recompute the aggregate from the caches (the from-scratch oracle)."""
        with self._lock:
            self._dense = np.zeros(
                (max(self._registry.capacity, 1), NUM_DIMS), dtype=np.int64
            )
            for rr in self._rr_cache.list():
                for res in rr.spec.reservations.values():
                    self._scatter(res.node, res.resources, +1)
            for node, res in self._soft_store.used_soft_reservation_resources().items():
                self._scatter(node, res, +1)
            self.rebuilds += 1
            self.version += 1
            self._dirty.mark_unknown()

    def collect_delta(self):
        """Drain the dirty-row feed (single consumer: the feature store's
        resident usage master). Returns (version, rows, vals):

          rows  int64 registry rows whose usage changed since the last
                drain (deduplicated), or None when the tracker cannot name
                them (a from-scratch rebuild happened) — the consumer then
                pays one full `array()` copy;
          vals  the current [len(rows), 3] int64 values of those rows,
                copied under the tracker lock (consistent with `version`).
        """
        with self._lock:
            rows, vals = self._dirty.drain(self._dense)
            return self.version, rows, vals

    def _ensure_row(self, idx: int) -> None:
        if idx >= self._dense.shape[0]:
            grow = max(idx + 1, self._dense.shape[0] * 2, 8)
            self._dense = np.pad(
                self._dense, ((0, grow - self._dense.shape[0]), (0, 0))
            )

    def _scatter(self, node: str, res: Resources, sign: int) -> None:
        idx = self._registry.intern(node)
        self._ensure_row(idx)
        self._dense[idx] += sign * res.as_array().astype(np.int64)
        self.deltas_applied += 1
        self.version += 1
        self._dirty.note(idx)

    # -- listeners -----------------------------------------------------------

    def _apply_rr_mutation(self, old, new) -> None:
        """Per-slot diff of one ResourceReservation change (caller holds the
        lock): O(slots of one app). Status-only updates (executor pod
        bindings — the most common RR mutation) change no Spec slot and are
        skipped outright."""
        if (
            old is not None
            and new is not None
            and old.spec.reservations == new.spec.reservations
        ):
            return
        if old is not None:
            for res in old.spec.reservations.values():
                self._scatter(res.node, res.resources, -1)
        if new is not None:
            for res in new.spec.reservations.values():
                self._scatter(res.node, res.resources, +1)

    def _on_rr_mutation(self, old, new) -> None:
        with self._lock:
            self._apply_rr_mutation(old, new)

    def _on_rr_mutation_batch(self, pairs) -> None:
        """A whole serving window's reservation commits as ONE update: one
        lock hold, all per-slot diffs applied back to back."""
        with self._lock:
            for old, new in pairs:
                self._apply_rr_mutation(old, new)

    def _on_soft_delta(self, node: str, res: Resources, sign: int) -> None:
        with self._lock:
            self._scatter(node, res, sign)
