"""Tiny insertion-ordered LRU used by the serving-path memo caches.

Every hot-path cache here used to `clear()` on overflow — wiping all 64
entries and forcing a full re-warm the moment a 65th signature appeared
(the exact workload shape of a fleet cycling through ~65 selector
signatures). LRU eviction keeps the hottest entries resident instead.

Plain dict + move-to-end on hit: Python dicts preserve insertion order, so
the first key is always the least-recently-used one. A small internal lock
serializes mutations — most consumers are single-threaded by the batcher
contract, but the solver's candidate-mask cache is also touched from the
unschedulable-marker thread, and the del+reinsert pair must not interleave.
"""

from __future__ import annotations

import threading
from typing import Any, Iterator


class LRUCache:
    __slots__ = ("_d", "_cap", "_lock")

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._d: dict = {}
        self._cap = capacity
        self._lock = threading.Lock()

    def get(self, key) -> Any | None:
        with self._lock:
            d = self._d
            v = d.get(key)
            if v is not None:
                # Move to end: most-recently-used keys live at the back.
                del d[key]
                d[key] = v
            return v

    def put(self, key, value) -> None:
        with self._lock:
            d = self._d
            if key in d:
                del d[key]
            elif len(d) >= self._cap:
                del d[next(iter(d))]  # evict least-recently-used
            d[key] = value

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        return key in self._d

    def keys(self) -> Iterator:
        return iter(self._d)
