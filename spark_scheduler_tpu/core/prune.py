"""Sound top-K candidate pruning for the window solve (the two-tier solve).

At 100k nodes the window kernel scans every row per scan step even though a
32-driver window can only ever touch a few hundred of them. The two-tier
solve makes the device program O(K):

  Tier 1 (host prefilter, this module): rank the window domain's nodes by
  the solver's own placement key — the priority order the kernels sort by,
  (zone rank, available mem asc, cpu asc, name rank) — riding the
  feature-rank index's resident PER-ZONE orders (core/feature_store.
  RankIndex), and gather the top-K candidate rows per zone, K sized from
  the window's aggregate demand x `solver.prune-slack`. The device then
  solves a [K,3] gathered sub-cluster with one small h2d instead of
  shipping [B,N] masks.

  Tier 2 (the certificate, also this module): soundness is ENFORCED, not
  assumed. After the pruned solve, `certify_window` replays the window's
  availability thread host-side and verifies that no pruned-away row could
  have altered any decision:

    - zone ranks are byte-exact by construction (the excluded rows' per-zone
      availability sums ship into the kernel as constant offsets,
      ops/sorting.zone_ranks zone_base);
    - a DENIAL is certified only if no excluded row could have cured it
      (capacity-bound test over the excluded rows' per-zone availability
      maxima, for both the driver fit and the executor capacity);
    - an ADMISSION is certified only if (a) no excluded driver candidate
      with a better priority key could fit the driver, (b) no excluded
      executor-capable row ranks before the worst chosen executor row,
      (c) excluded capacity could not have flipped the feasibility of a
      better-ranked kept driver candidate the pruned solve rejected, and
      (d) strategy-specific order hazards are absent (minimal-fragmentation
      consumes by capacity DESC, so any excluded capacity escalates;
      distribute-evenly escalates on multi-round fills).

  A failed certificate ESCALATES the window: the solver re-solves it from
  the exact host reconstruction via the greedy oracle (core/fallback.py —
  slot-for-slot the kernels' semantics), so decisions stay byte-identical
  to the unpruned path by construction, and the escalation is counted in
  `foundry.spark.scheduler.solver.prune.*`.

Every test here is CONSERVATIVE (it may escalate a window the full solve
would have decided identically, never the reverse): per-dim maxima over
excluded rows overestimate fit, candidate masks are ignored for excluded
driver checks, and any uncertainty (a prior window's placement landing on
an excluded row, a non-kept index in the blob) escalates outright.

O(K + changed) planning (ISSUE 12). The planner used to pay O(N) host
sweeps per window (per-zone bincounts, excluded-row sums, per-zone maxima
over N−K rows) even when nothing outside the kept rows moved between
windows. `PrunePlanner` retires them:

  - per-zone availability TOTALS live in resident, event-maintained
    aggregates (core/zone_aggregates.ZoneAggregates — the census/
    soft-mirror pattern), so a window's `zone_base` excluded sums derive
    as `total − Σ kept` in O(K);
  - the top-K kept rows, the excluded lexmin keys and the excluded
    per-dim maxima are CACHED per zone and reused while the zone's
    excluded rows are untouched. The cache is sound by construction:
    every certificate input about excluded rows depends only on excluded
    rows, so churn confined to the kept rows (gang placements — the
    steady serving case) reuses the entry verbatim; a newly-valid row
    (node ADD) merges in exactly (min/max/flag updates are exact for a
    set gaining a member); ANY other change touching a zone's excluded
    rows re-scans just that zone's order (O(zone), counted);
  - consequently a no-churn window re-serves the identical kept row set
    (`plan_reuse`), which is what keys the solver's statics-gather reuse.

Subset-domain windows (a shared non-default domain) take the legacy
vectorized sweep (`sweep_rows` counts them); the pooled partition path
prunes per-partition the same way.

Gating (checked by the solver before planning): plain fills only (the
single-AZ wrappers score zones by subset-dependent efficiencies), no
configured label priorities (the keys above assume the label rank is
uniformly INT32_INF), and one shared domain per window (the pooled
partition path prunes per-partition instead, where each partition's domain
is uniform by construction).
"""

from __future__ import annotations

import dataclasses
import time as _time

import numpy as np

from spark_scheduler_tpu.models.resources import CPU_DIM, MEM_DIM

PLAIN_FILLS = frozenset(
    {"tightly-pack", "distribute-evenly", "minimal-fragmentation"}
)

_I64_MAX = np.iinfo(np.int64).max
_I64_MIN = np.iinfo(np.int64).min


from spark_scheduler_tpu.models.cluster import pad_bucket as _bucket  # noqa: E402


def _zone_sum(zones: np.ndarray, vals: np.ndarray, zb: int) -> np.ndarray:
    """Exact per-zone int64 sums. bincount accumulates in float64 —
    exact while |sum| < 2^53, guaranteed for < 2^22 int32 rows (2^22 x
    2^31/2 = 2^52); larger row sets take the exact-but-slow np.add.at.
    (The resident-aggregate fast path never calls this — only the
    subset-domain sweep does.)"""
    if vals.size >= (1 << 22):
        out = np.zeros(zb, np.int64)
        np.add.at(out, zones, vals.astype(np.int64))
        return out
    return np.bincount(
        zones, weights=vals, minlength=zb
    ).astype(np.int64)


def zone_ranks_host(
    mem_sum: np.ndarray,  # [Z] int64 — per-zone available-memory sums
    cpu_sum: np.ndarray,  # [Z] int64
    present: np.ndarray,  # [Z] bool — zone has a (domain & valid) node
) -> np.ndarray:  # [Z] int32 — rank of each zone (0 = highest priority)
    """Host replica of ops/sorting.zone_ranks: ascending (mem, cpu), absent
    zones last, zone-id tiebreak. The kernel's chunked int32 aggregation is
    an exact int64 sum in normal form, so comparing int64 sums here yields
    the identical order — the certificate depends on that equality."""
    z = mem_sum.shape[0]
    absent = np.where(present, 0, 1)
    order = np.lexsort((np.arange(z), cpu_sum, mem_sum, absent))
    ranks = np.empty(z, np.int32)
    ranks[order] = np.arange(z, dtype=np.int32)
    return ranks


def split_zone_sums(sums: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 per-zone sums -> (hi, lo) int32 limbs for the device offset
    (hi = S >> 24 arithmetic, lo = S & 0xFFFFFF; exact for |S| < 2^55)."""
    return (
        (sums >> 24).astype(np.int32),
        (sums & 0xFFFFFF).astype(np.int32),
    )


def _lex_lt(a0, a1, a2, a3, b0, b1, b2, b3):
    """Vectorized (a0,a1,a2,a3) < (b0,b1,b2,b3) — the priority-key compare
    (az rank, mem, cpu, name rank), lower = higher priority."""
    return (a0 < b0) | (
        (a0 == b0)
        & (
            (a1 < b1)
            | (
                (a1 == b1)
                & ((a2 < b2) | ((a2 == b2) & (a3 < b3)))
            )
        )
    )


@dataclasses.dataclass
class PrunePlan:
    """One window's candidate-pruning decision: the kept row set, the
    device zone-sum offsets, and the excluded-row summaries the
    certificate tests against. All arrays are host numpy."""

    keep: np.ndarray  # [Kp] int32 — kept global rows, real part SORTED
    #                     ascending, padding repeats keep[0]
    k_real: int  # number of real kept rows
    kept_mask: np.ndarray  # [N] bool
    dom_mask: np.ndarray  # [N] bool — window domain & valid
    num_zones: int  # the solver's zone bucket Zb
    # Device offsets: excluded-row zone sums as int32 limbs + present.
    zone_base: tuple  # (mem_hi, mem_lo, cpu_hi, cpu_lo, present) [Zb] each
    # Dispatch-time zone sums over the WHOLE domain (kept + excluded) —
    # the certificate threads these (minus committed placements) to
    # replicate the kernel's per-segment zone ranks.
    zone_mem: np.ndarray  # [Zb] int64
    zone_cpu: np.ndarray  # [Zb] int64
    present: np.ndarray  # [Zb] bool
    # Excluded-row summaries, per zone, over rows RELEVANT to this window
    # (rows fitting the window's per-dim minimum demand; rows that fit no
    # request are provably transparent — zero capacity, no driver fit).
    # e_cnt_* is consumed as a PRESENCE flag (> 0) by the certificate; the
    # resident-cache fast path stores 0/1.
    e_cnt_exec: np.ndarray  # [Zb] int64 — relevant excluded exec-eligible
    e_max_exec: np.ndarray  # [Zb,3] int64 — per-dim avail max (conservative fit)
    e_key_exec: np.ndarray  # [Zb,3] int64 — lexmin (mem,cpu,name), I64_MAX pad
    e_cnt_drv: np.ndarray  # [Zb] int64
    e_max_drv: np.ndarray  # [Zb,3] int64
    e_key_drv: np.ndarray  # [Zb,3] int64
    # Per-request driver candidate masks gathered onto the kept rows.
    cand_kept: list  # [B_req] of [Kp] bool
    dom_rows: int  # |domain| (stats)
    # True when the kept row set (`keep` array object) was re-served from
    # the per-zone cache unchanged — the key for the solver's
    # statics-gather reuse.
    reused: bool = False
    plan_ms: float = 0.0  # prefilter planning wall time
    offset_ms: float = 0.0  # zone_base offset derivation wall time


class _ZoneEntry:
    """Cached per-zone prefilter state: the kept rows and the excluded-row
    summaries for one zone. An excluded-row change keeps the entry SOUND
    by merging the row's new state (exact-direction: min/max/presence
    can only extend) while the old contribution lingers as a
    conservative leftover; `stale` counts those leftovers so the zone
    re-scans before conservatism drifts into spurious escalations."""

    __slots__ = (
        "kept_e", "kept_d", "keep", "has_e", "has_d",
        "key_e", "key_d", "max_e", "max_d", "stale", "depleted",
        "last_key_e", "last_key_d",
    )

    def __init__(self, kept_e, kept_d, has_e, has_d, key_e, key_d,
                 max_e, max_d, last_key_e=None, last_key_d=None):
        self.kept_e = kept_e
        self.kept_d = kept_d
        self.keep = np.unique(np.concatenate([kept_e, kept_d]))
        self.has_e = has_e
        self.has_d = has_d
        self.key_e = key_e  # int64[3] lexmin (mem, cpu, name) or I64_MAX
        self.key_d = key_d
        self.max_e = max_e  # int64[3] per-dim max or I64_MIN
        self.max_d = max_d
        self.stale = 0
        # Kept rows whose availability dropped below the window minima:
        # still sound to keep (the kernel just skips them), but a zone
        # whose kept set depletes while fresh excluded capacity sits
        # outside WILL eventually fail the certificate (the full solve
        # would place there) — refresh the entry before that costs an
        # escalation.
        self.depleted = 0
        # Key of the K-th (worst) kept row per class at build time — the
        # kept-set BOUNDARY. A merged row whose key beats it would have
        # been kept by a fresh selection (e.g. a node ADD whose name
        # sorts before the roster's): the entry re-scans instead of
        # parking a top-K row in the excluded summaries, where the next
        # placement in the zone would escalate. None = the zone kept
        # every fitting row, so ANY new fitting row belongs in the set.
        self.last_key_e = last_key_e
        self.last_key_d = last_key_d


def _key_lt(a, b) -> bool:
    """Lexicographic (mem, cpu, name) triple compare."""
    for x, y in zip(a, b):
        if x != y:
            return x < y
    return False


class PrunePlanner:
    """O(K + changed) window planning over resident per-zone state.

    Owns the per-zone RankIndex (priority orders), the ZoneAggregates
    (availability totals) and the per-zone plan cache. The solver feeds it
    the EXACT changed rows it already knows (pipelined-build delta rows,
    static row-deltas, fetched placement rows); a serving path that cannot
    name its rows marks the planner UNKNOWN and the next sync pays one
    vectorized snapshot compare instead.
    """

    def __init__(self, stats: dict | None = None):
        from spark_scheduler_tpu.core.feature_store import RankIndex
        from spark_scheduler_tpu.core.zone_aggregates import ZoneAggregates

        self.index = RankIndex()
        self.agg = ZoneAggregates()
        self._entries: dict[int, _ZoneEntry] = {}
        self._min_dr: np.ndarray | None = None  # int64[3] at last full build
        self._min_er: np.ndarray | None = None
        self._k = 0
        self._keep: np.ndarray | None = None  # assembled padded keep
        self._keep_real = 0
        # Pending change feed (drained at sync): explicit dirty rows,
        # static-delta rows, or None = unknown (snapshot compare).
        self._dirty: list | None = []
        self._static: list = []
        self.stats = stats if stats is not None else {}
        for key in (
            "planner_rows_scanned", "planner_cold_rows",
            "planner_sweep_rows", "planner_resync_rows",
            "planner_zone_rescans", "planner_merges", "plan_reuse",
        ):
            self.stats.setdefault(key, 0)

    # -- change feed ---------------------------------------------------------

    def invalidate(self) -> None:
        self.index.invalidate()
        self.agg.invalidate()
        self._entries.clear()
        self._keep = None
        self._min_dr = None  # next build is COLD (counter attribution)
        self._min_er = None
        self._k = 0
        self._dirty = []
        self._static = []

    def note_dirty(self, rows) -> None:
        """Rows whose availability changed (exact — pipelined build deltas,
        fetched placement rows)."""
        if self._dirty is not None and len(rows):
            self._dirty.append(np.asarray(rows))

    def note_static(self, rows) -> None:
        """Rows whose STATIC fields changed (static row-delta: validity,
        zone, name rank, eligibility flags)."""
        if len(rows):
            self._static.append(np.asarray(rows))

    def mark_unknown(self) -> None:
        """A serving path touched availability without naming rows (dense
        unpruned fetch): the next sync diff-scans the snapshots."""
        self._dirty = None

    # -- sync ----------------------------------------------------------------

    def sync(self, host, num_zones: int) -> None:
        """Bring the resident index/aggregates/cache up to the CURRENT
        host view, in O(changed) when the change feed is exact."""
        avail = np.asarray(host.available)
        zid = np.asarray(host.zone_id)
        valid = np.asarray(host.valid)
        name_rank = np.asarray(host.name_rank)
        n = avail.shape[0]
        if (
            not self.index.valid
            or not self.agg.valid
            or self.index.rows != n
            or self.index.num_zones != num_zones
        ):
            self._rebuild(avail, name_rank, zid, valid, num_zones)
            return
        if self._dirty is None:
            dirty = self.agg.diff_rows(avail)
            self.stats["planner_resync_rows"] += n
        else:
            dirty = (
                np.unique(np.concatenate(self._dirty))
                if self._dirty
                else np.empty(0, np.int64)
            )
        static = (
            np.unique(np.concatenate(self._static))
            if self._static
            else np.empty(0, np.int64)
        )
        self._dirty = []
        self._static = []
        if dirty.size == 0 and static.size == 0:
            return
        all_dirty = (
            np.union1d(dirty, static) if static.size else dirty
        )
        if all_dirty.size > max(1024, n // 4):
            self._rebuild(avail, name_rank, zid, valid, num_zones)
            return
        self._classify(all_dirty, static, avail, zid, valid, host)
        self.index.update_rows(avail, name_rank, all_dirty, zone_id=zid)
        self.agg.update_rows(avail, zid, valid, all_dirty)

    def _rebuild(self, avail, name_rank, zid, valid, num_zones) -> None:
        self.index.rebuild(avail, name_rank, zid, num_zones)
        self.agg.rebuild(avail, zid, valid, num_zones)
        self._entries.clear()
        self._keep = None
        self._dirty = []
        self._static = []

    # Conservative-leftover budget per zone entry: each absorbed
    # excluded-row change leaves the row's OLD contribution behind in the
    # per-zone summaries (sound, but it can only over-approximate); past
    # this many leftovers the zone re-scans to restore exactness before
    # the drift causes spurious escalations.
    _STALE_BUDGET = 32

    def _classify(self, all_dirty, static, avail, zid, valid, host) -> None:
        """Absorb the changed rows into the per-zone cache, BEFORE the
        snapshots move:

          benign  — a non-static change to a KEPT row: the excluded-row
                    summaries depend only on excluded rows, so the entry
                    stands verbatim (the steady-serving case: gang
                    placements land on kept rows);
          merge   — any change to a NON-KEPT row (node add/update/delete,
                    external usage churn, eligibility flips): the row's
                    NEW state merges exactly (joining a summary can only
                    extend min/max/presence), while its old contribution
                    lingers as a conservative leftover — sound by the
                    certificate's over-approximation contract. Leftovers
                    are budgeted (`_STALE_BUDGET`) per zone;
          rescan  — a STATIC flip on a kept row (validity/zone/rank of a
                    kept row breaks the `total − kept` offset identity)
                    or an exhausted leftover budget: drop the zone's
                    entry; the next plan re-scans just that zone.
        """
        if not self._entries:
            return
        if all_dirty.size > 4096:
            # A bulk churn burst (resync after a dense fetch, a huge
            # delta): dropping every entry is cheaper and exact — the
            # next plan re-scans the zones it needs.
            self._entries.clear()
            self._keep = None
            return
        old_zone = self.agg.zone_of(all_dirty)
        new_zone = zid[all_dirty].astype(np.int32)
        was_valid = self.agg.valid_of(all_dirty)
        is_static = (
            np.isin(all_dirty, static) if static.size else
            np.zeros(all_dirty.shape[0], bool)
        )
        unsched = np.asarray(host.unschedulable, bool)
        ready = np.asarray(host.ready, bool)
        name_rank = np.asarray(host.name_rank)
        for i, r in enumerate(all_dirty):
            oz, nz = int(old_zone[i]), int(new_zone[i])
            entry = self._entries.get(nz)
            in_keep = False
            if entry is not None and entry.keep.size:
                p = np.searchsorted(entry.keep, r)
                in_keep = bool(
                    p < entry.keep.size and entry.keep[p] == r
                )
            if in_keep:
                if not is_static[i]:
                    # Benign: kept-row value churn. But track DEPLETION —
                    # a kept row that no longer fits either class minimum
                    # is dead weight, and a zone serving mostly-depleted
                    # kept rows while fresh excluded capacity exists will
                    # fail its certificate; refresh first.
                    av = avail[r]
                    if self._min_dr is not None and not (
                        (av >= self._min_dr).all()
                        or (av >= self._min_er).all()
                    ):
                        entry.depleted += 1
                        # Aggressive on purpose: a zone serving depleted
                        # kept rows ranks FIRST (lowest totals), so the
                        # full solve would reach for its excluded rows
                        # almost immediately — one O(zone) re-scan is
                        # far cheaper than the escalation it prevents.
                        if entry.depleted > max(1, self._k // 8):
                            self._entries.pop(nz, None)
                            self._keep = None
                    continue
                # Static flip (validity/zone/rank) of a KEPT row: the
                # offset identity needs every kept row live — re-scan.
                self._entries.pop(nz, None)
                self._keep = None
                continue
            # Non-kept row: merge its new state (exact direction), note
            # the leftover. A zone move leaves its old zone's summaries
            # as leftovers too.
            if oz != nz:
                old_entry = self._entries.get(oz)
                if old_entry is not None:
                    kp = old_entry.keep
                    p = np.searchsorted(kp, r) if kp.size else 0
                    if kp.size and p < kp.size and kp[p] == r:
                        # The moved row was KEPT under its old zone: the
                        # old entry's offset identity is broken — re-scan.
                        self._entries.pop(oz, None)
                        self._keep = None
                    else:
                        old_entry.stale += 1
                        if old_entry.stale > self._STALE_BUDGET:
                            self._entries.pop(oz, None)
                            self._keep = None
            if entry is None:
                continue
            if bool(valid[r]) and self._merge_row(
                entry, int(r), avail, unsched, ready, name_rank
            ):
                # The row beats the kept boundary: a fresh selection
                # would keep it — re-scan the zone.
                self._entries.pop(nz, None)
                self._keep = None
                continue
            if not was_valid[i]:
                # A brand-new valid row (node ADD) merged EXACTLY — it
                # has no old contribution, so no leftover to budget.
                continue
            entry.stale += 1
            if entry.stale > self._STALE_BUDGET:
                self._entries.pop(nz, None)
                self._keep = None

    def _merge_row(
        self, entry, r, avail, unsched, ready, name_rank
    ) -> bool:
        """Merge one non-kept row's NEW state into the zone entry.
        Returns True when the row BEATS the kept-set boundary — a fresh
        selection would have kept it, so the caller must drop the entry
        (re-scan) instead of parking a top-K row among the excluded."""
        av = avail[r].astype(np.int64)
        key = (
            int(avail[r, MEM_DIM]),
            int(avail[r, CPU_DIM]),
            int(name_rank[r]),
        )
        if (av >= self._min_dr).all():
            if entry.last_key_d is None or _key_lt(key, entry.last_key_d):
                return True
            entry.has_d = True
            if _key_lt(key, entry.key_d):
                entry.key_d = key
            entry.max_d = np.maximum(entry.max_d, av)
        if (av >= self._min_er).all() and not unsched[r] and ready[r]:
            if entry.last_key_e is None or _key_lt(key, entry.last_key_e):
                return True
            entry.has_e = True
            if _key_lt(key, entry.key_e):
                entry.key_e = key
            entry.max_e = np.maximum(entry.max_e, av)
        self.stats["planner_merges"] += 1
        return False

    # -- planning ------------------------------------------------------------

    def plan_full_domain(
        self, host, *, cand_per_req, drv_arr, exc_arr, counts,
        num_zones, top_k, slack,
    ) -> PrunePlan | None:
        """O(K + changed) plan for a window whose shared domain is the
        full valid mask (the resident aggregates' coverage)."""
        t0 = _time.perf_counter()
        avail = np.asarray(host.available)
        valid = np.asarray(host.valid)
        zid = np.asarray(host.zone_id)
        b = drv_arr.shape[0]
        min_dr = drv_arr.min(axis=0).astype(np.int64)
        min_er = exc_arr.min(axis=0).astype(np.int64)
        demand = int(counts.sum()) + b
        # Power-of-two bucketed K: keeps the per-zone cache (and the kept
        # row set) stable across window-demand jitter at the cost of at
        # most 2x extra kept rows.
        k = _bucket(max(int(top_k), int(np.ceil(demand * slack))), 1)
        agg = self.agg
        # Cache-key drift: a LOWER per-dim minimum demand or a LARGER K
        # widens the relevant-row sets, which the cached excluded
        # summaries cannot soundly describe — full re-scan.
        # COLD = building from nothing (first plan, or right after an
        # invalidate — invalidate() resets the cached minima). Everything
        # else (K/minima widening, churn-dropped entries) counts as rows
        # SCANNED, so the CI O(K) assertion sees every incremental sweep.
        cold = self._min_dr is None
        if cold or (
            k > self._k
            or (min_dr < self._min_dr).any()
            or (min_er < self._min_er).any()
        ):
            self._entries.clear()
            self._keep = None
            self._min_dr = min_dr
            self._min_er = min_er
            self._k = k
        counter = "planner_cold_rows" if cold else "planner_rows_scanned"
        unsched = np.asarray(host.unschedulable, bool)
        ready = np.asarray(host.ready, bool)
        name_rank = np.asarray(host.name_rank)
        zones = np.flatnonzero(agg.cnt > 0)
        changed = self._keep is None
        for z in zones:
            if int(z) not in self._entries:
                self._rescan_zone(
                    int(z), avail, valid, unsched, ready, name_rank,
                    counter,
                )
                changed = True
        dom_rows = int(agg.cnt.sum())
        if changed:
            keeps = [
                self._entries[int(z)].keep
                for z in zones
                if int(z) in self._entries
            ]
            keep_real = (
                np.sort(np.concatenate(keeps)).astype(np.int32)
                if keeps
                else np.empty(0, np.int32)
            )
            k_real = int(keep_real.shape[0])
            if k_real == 0 or k_real >= 0.7 * dom_rows:
                self._keep = None
                return None
            kp = _bucket(k_real, 64)
            keep_padded = np.full(kp, keep_real[0], np.int32)
            keep_padded[:k_real] = keep_real
            self._keep = keep_padded
            self._keep_real = k_real
        else:
            keep_padded = self._keep
            k_real = self._keep_real
            if k_real == 0 or k_real >= 0.7 * dom_rows:
                return None
            self.stats["plan_reuse"] += 1
        keep_real_v = keep_padded[:k_real]

        # Assemble the certificate's per-zone summary arrays from the
        # entries (Zb is small).
        zb = num_zones
        e_cnt_e = np.zeros(zb, np.int64)
        e_cnt_d = np.zeros(zb, np.int64)
        e_max_e = np.full((zb, avail.shape[1]), _I64_MIN, np.int64)
        e_max_d = np.full((zb, avail.shape[1]), _I64_MIN, np.int64)
        e_key_e = np.full((zb, 3), _I64_MAX, np.int64)
        e_key_d = np.full((zb, 3), _I64_MAX, np.int64)
        for z in zones:
            entry = self._entries.get(int(z))
            if entry is None:
                continue
            if entry.has_e:
                e_cnt_e[z] = 1
                e_max_e[z] = entry.max_e
                e_key_e[z] = entry.key_e
            if entry.has_d:
                e_cnt_d[z] = 1
                e_max_d[z] = entry.max_d
                e_key_d[z] = entry.key_d

        # Offsets: excluded sums = resident totals − Σ kept, O(K).
        t1 = _time.perf_counter()
        kept_avail = avail[keep_real_v].astype(np.int64)
        kz = zid[keep_real_v]
        kept_mem = np.zeros(zb, np.int64)
        kept_cpu = np.zeros(zb, np.int64)
        np.add.at(kept_mem, kz, kept_avail[:, MEM_DIM])
        np.add.at(kept_cpu, kz, kept_avail[:, CPU_DIM])
        s_mem = agg.mem - kept_mem
        s_cpu = agg.cpu - kept_cpu
        present = agg.cnt > 0
        mem_hi, mem_lo = split_zone_sums(s_mem)
        cpu_hi, cpu_lo = split_zone_sums(s_cpu)
        t2 = _time.perf_counter()

        kept_mask = np.zeros(avail.shape[0], dtype=bool)
        kept_mask[keep_real_v] = True
        return PrunePlan(
            keep=keep_padded,
            k_real=k_real,
            kept_mask=kept_mask,
            dom_mask=valid,
            num_zones=zb,
            zone_base=(mem_hi, mem_lo, cpu_hi, cpu_lo, present),
            zone_mem=agg.mem.copy(),
            zone_cpu=agg.cpu.copy(),
            present=present,
            e_cnt_exec=e_cnt_e,
            e_max_exec=e_max_e,
            e_key_exec=e_key_e,
            e_cnt_drv=e_cnt_d,
            e_max_drv=e_max_d,
            e_key_drv=e_key_d,
            cand_kept=[np.asarray(c)[keep_padded] for c in cand_per_req],
            dom_rows=dom_rows,
            reused=not changed,
            plan_ms=(t2 - t0) * 1e3,
            offset_ms=(t2 - t1) * 1e3,
        )

    def _rescan_zone(
        self, z, avail, valid, unsched, ready, name_rank, counter,
    ) -> None:
        """Exact per-zone prefilter state from the zone's resident order:
        first K fitting rows per class, the first fitting row beyond them
        (the excluded lexmin by construction — the order IS sorted by the
        key), and the per-dim maxima over the rest."""
        zo = self.index.zone_order(z)
        self.stats[counter] += int(zo.shape[0])
        self.stats["planner_zone_rescans"] += 1
        rows = zo[valid[zo]]
        k = self._k
        if not rows.size:
            self._entries[z] = _ZoneEntry(
                np.empty(0, np.int32), np.empty(0, np.int32),
                False, False,
                (_I64_MAX,) * 3, (_I64_MAX,) * 3,
                np.full(avail.shape[1], _I64_MIN, np.int64),
                np.full(avail.shape[1], _I64_MIN, np.int64),
            )
            return
        av = avail[rows]
        fit_d = (av >= self._min_dr).all(axis=1)
        fit_e = (
            (av >= self._min_er).all(axis=1)
            & ~unsched[rows]
            & ready[rows]
        )
        sel_e = np.flatnonzero(fit_e)
        sel_d = np.flatnonzero(fit_d)
        kept_e = rows[sel_e[:k]].astype(np.int32)
        kept_d = rows[sel_d[:k]].astype(np.int32)
        # Excluded = fitting rows beyond the UNION of both classes' kept
        # prefixes (a row kept for the exec class is kept, full stop —
        # the legacy sweep's excl semantics, which the exactness oracle
        # pins): the first such row in order is the class's lexmin key.
        un = np.zeros(rows.shape[0], bool)
        un[sel_e[:k]] = True
        un[sel_d[:k]] = True

        def _class(sel):
            rel = sel[~un[sel]]
            if rel.size:
                first = rows[rel[0]]
                key = (
                    int(avail[first, MEM_DIM]),
                    int(avail[first, CPU_DIM]),
                    int(name_rank[first]),
                )
                mx = av[rel].max(axis=0).astype(np.int64)
                return True, key, mx
            return (
                False, (_I64_MAX,) * 3,
                np.full(avail.shape[1], _I64_MIN, np.int64),
            )

        has_e, key_e, max_e = _class(sel_e)
        has_d, key_d, max_d = _class(sel_d)

        def _last_key(sel):
            if sel.size < k:
                return None  # every fitting row kept: new rows belong in
            last = rows[sel[k - 1]]
            return (
                int(avail[last, MEM_DIM]),
                int(avail[last, CPU_DIM]),
                int(name_rank[last]),
            )

        self._entries[z] = _ZoneEntry(
            kept_e, kept_d, has_e, has_d, key_e, key_d, max_e, max_d,
            last_key_e=_last_key(sel_e), last_key_d=_last_key(sel_d),
        )

    # -- subset domains (legacy sweep) --------------------------------------

    def plan_with_masks(
        self, host, *, dom_mask, cand_per_req, drv_arr, exc_arr, counts,
        num_zones, top_k, slack,
    ) -> PrunePlan | None:
        """The pre-ISSUE-12 vectorized O(N) planner, kept for windows whose
        shared domain is a SUBSET of the cluster (instance-group pinned
        domains): the resident aggregates cover the full valid mask only.
        Counted in `planner_sweep_rows`."""
        t0 = _time.perf_counter()
        avail = np.asarray(host.available)
        zone_id = np.asarray(host.zone_id)
        n = avail.shape[0]
        self.stats["planner_sweep_rows"] += n

        min_dr = drv_arr.min(axis=0)
        min_er = exc_arr.min(axis=0)
        exec_elig = (
            dom_mask
            & ~np.asarray(host.unschedulable, bool)
            & np.asarray(host.ready, bool)
        )
        fit_e = (avail >= min_er[None, :]).all(axis=1) & exec_elig
        fit_d = (avail >= min_dr[None, :]).all(axis=1) & dom_mask

        b = drv_arr.shape[0]
        demand = int(counts.sum()) + b
        k_per_zone = max(int(top_k), int(np.ceil(demand * slack)))

        zb = num_zones
        dom_zcnt = (
            np.bincount(zone_id[dom_mask], minlength=zb)
            if dom_mask.any()
            else np.zeros(zb, np.int64)
        )
        zids = np.flatnonzero(dom_zcnt)
        name_rank = np.asarray(host.name_rank)
        # Per-zone top-K off the zone's resident order, separately for
        # executor-capable and driver-capable rows: a per-zone prefix
        # stays a prefix under any zone-rank permutation, so mid-window
        # zone-rank drift cannot promote an excluded row past a kept one
        # within its zone.
        sel: list[np.ndarray] = []
        per_zone: dict[int, tuple] = {}
        for z in zids:
            zo = self.index.zone_order(int(z))
            fo = zo[fit_e[zo]]
            do = zo[fit_d[zo]]
            per_zone[int(z)] = (fo, do)
            sel.append(fo[:k_per_zone])
            sel.append(do[:k_per_zone])
        kept_mask = np.zeros(n, dtype=bool)
        if sel:
            kept_mask[np.concatenate(sel)] = True
        keep = np.flatnonzero(kept_mask).astype(np.int32)
        k_real = len(keep)
        dom_rows = int(dom_mask.sum())
        if k_real == 0 or k_real >= 0.7 * dom_rows:
            return None  # pruning buys nothing on this window

        excl = dom_mask & ~kept_mask
        e_rows = np.flatnonzero(excl)
        e_zone = zone_id[e_rows]

        # Device zone-sum offsets: ALL excluded domain rows.
        s_mem = _zone_sum(e_zone, avail[e_rows, MEM_DIM], zb)
        s_cpu = _zone_sum(e_zone, avail[e_rows, CPU_DIM], zb)
        present = dom_zcnt > 0

        # Whole-domain dispatch sums = kept sums + excluded sums.
        zone_mem = s_mem.copy()
        zone_cpu = s_cpu.copy()
        kept_avail = avail[keep].astype(np.int64)
        kept_zone = zone_id[keep]
        np.add.at(zone_mem, kept_zone, kept_avail[:, MEM_DIM])
        np.add.at(zone_cpu, kept_zone, kept_avail[:, CPU_DIM])

        def _summaries(which: int):
            cnt = np.zeros(zb, np.int64)
            mx = np.full((zb, avail.shape[1]), _I64_MIN, np.int64)
            key = np.full((zb, 3), _I64_MAX, np.int64)
            for z, orders in per_zone.items():
                zo = orders[which]
                rel = zo[excl[zo]]  # relevant excluded, in priority order
                if not rel.size:
                    continue
                cnt[z] = rel.size
                mx[z] = avail[rel].max(axis=0)
                fr = rel[0]  # first in order == the zone's lexmin key
                key[z, 0] = avail[fr, MEM_DIM]
                key[z, 1] = avail[fr, CPU_DIM]
                key[z, 2] = name_rank[fr]
            return cnt, mx, key

        e_cnt_exec, e_max_exec, e_key_exec = _summaries(0)
        e_cnt_drv, e_max_drv, e_key_drv = _summaries(1)

        kp = _bucket(k_real, 64)
        keep_padded = np.full(kp, keep[0], np.int32)
        keep_padded[:k_real] = keep

        t1 = _time.perf_counter()
        mem_hi, mem_lo = split_zone_sums(s_mem)
        cpu_hi, cpu_lo = split_zone_sums(s_cpu)
        t2 = _time.perf_counter()
        return PrunePlan(
            keep=keep_padded,
            k_real=k_real,
            kept_mask=kept_mask,
            dom_mask=dom_mask,
            num_zones=zb,
            zone_base=(mem_hi, mem_lo, cpu_hi, cpu_lo, present),
            zone_mem=zone_mem,
            zone_cpu=zone_cpu,
            present=present,
            e_cnt_exec=e_cnt_exec,
            e_max_exec=e_max_exec,
            e_key_exec=e_key_exec,
            e_cnt_drv=e_cnt_drv,
            e_max_drv=e_max_drv,
            e_key_drv=e_key_drv,
            cand_kept=[np.asarray(c)[keep_padded] for c in cand_per_req],
            dom_rows=dom_rows,
            reused=False,
            plan_ms=(t2 - t0) * 1e3,
            offset_ms=(t2 - t1) * 1e3,
        )

    def index_stats(self) -> dict:
        return {
            "index": self.index.stats(),
            "aggregates": self.agg.stats(),
            "cached_zones": len(self._entries),
        }


def certify_window(
    plan: PrunePlan,
    *,
    strategy: str,
    requests,  # the window's WindowRequests (row counts per segment)
    drivers: np.ndarray,  # [B] int64 GLOBAL node indices (-1 = none)
    admitted: np.ndarray,  # [B] bool
    packed: np.ndarray,  # [B] bool
    execs: np.ndarray,  # [B, Emax] int64 GLOBAL indices
    drv64: np.ndarray,  # [B, 3] int64 per-row driver request
    exc64: np.ndarray,  # [B, 3] int64 per-row executor request
    base_kept: np.ndarray,  # [k_real, 3] int64 — EXACT dispatch base on the
    #                     kept rows (host view minus in-flight priors'
    #                     placements); OWNED by the certificate (mutated)
    host,  # host ClusterTensors view at dispatch
    prior_rows: np.ndarray,  # rows any in-flight prior placed on (global)
    prior_deltas: np.ndarray,  # [len(prior_rows), 3] int64 — the priors'
    #                     summed placements on those rows
) -> tuple[bool, str | None]:
    """Replay the window's availability thread and certify that the pruned
    solve's decisions equal the full solve's. Returns (ok, reason) —
    reason names the first failed test (telemetry label).

    O(K + rows) since ISSUE 12: every input is either per-kept-row or
    per-zone — the [N]-shaped lut/base of the original implementation is
    gone (the caller gathers `base_kept` on the kept rows)."""
    # The device offsets assumed excluded rows kept their host-view
    # availability; a prior window's placement on an excluded row breaks
    # that (the plan was built before the prior's placements were known).
    # Rows outside the window domain are transparent to every choice
    # (masked from eligibility and zone sums alike), so only domain rows
    # are tested.
    in_dom = plan.dom_mask[prior_rows]
    prior_rows = prior_rows[in_dom]
    prior_deltas = prior_deltas[in_dom]
    if prior_rows.size and not plan.kept_mask[prior_rows].all():
        return False, "prior-placed-excluded"

    zone_id = np.asarray(host.zone_id)
    name_rank = np.asarray(host.name_rank)
    keep = plan.keep[: plan.k_real]  # sorted ascending

    def to_local(g: np.ndarray) -> np.ndarray:
        """Global rows -> kept-local indices, -1 for non-kept."""
        p = np.searchsorted(keep, g)
        pc = np.clip(p, 0, keep.size - 1)
        return np.where(
            (g >= 0) & (keep[pc] == g), pc, -1
        ).astype(np.int64)

    # Hoisted once for the whole window: the per-row loop below only
    # indexes into these (the old [N] lut without the [N] allocation).
    drivers_local = to_local(drivers)
    execs_local = to_local(execs)

    k_zone = zone_id[keep]
    k_name = name_rank[keep].astype(np.int64)
    zs_mem = plan.zone_mem.copy()
    zs_cpu = plan.zone_cpu.copy()
    # Priors placed only on kept rows (verified above): fold their
    # placements out of the dispatch sums to reach the true base sums.
    # base == host view - priors, and plan sums were over the host view.
    if prior_rows.size:
        np.add.at(
            zs_mem, zone_id[prior_rows], -prior_deltas[:, MEM_DIM]
        )
        np.add.at(
            zs_cpu, zone_id[prior_rows], -prior_deltas[:, CPU_DIM]
        )

    # Per-row conservative excluded-fit tables, vectorized across the batch.
    fit_e_zb = (
        (plan.e_max_exec[None, :, :] >= exc64[:, None, :]).all(axis=2)
        & (plan.e_cnt_exec > 0)[None, :]
    )  # [B, Zb]
    fit_d_zb = (
        (plan.e_max_drv[None, :, :] >= drv64[:, None, :]).all(axis=2)
        & (plan.e_cnt_drv > 0)[None, :]
    )

    az = zone_ranks_host(zs_mem, zs_cpu, plan.present)
    az_dirty = False
    row = 0
    for req_i, req in enumerate(requests):
        nrows = len(req.rows)
        if az_dirty:
            az = zone_ranks_host(zs_mem, zs_cpu, plan.present)
            az_dirty = False
        # Segment-start keys: the kernel computes priority orders ONCE per
        # segment from the segment-start availability and reuses them while
        # only availability mutates (resource.go:299 semantics) — so every
        # key comparison below uses these, while fit/capacity tests use the
        # current in-segment availability.
        k_az = az[k_zone].astype(np.int64)
        k_mem = base_kept[:, MEM_DIM].copy()
        k_cpu = base_kept[:, CPU_DIM].copy()
        cand_k = plan.cand_kept[req_i][: plan.k_real]
        seg_kept = None  # lazy copy — only hypothetical commits mutate it
        for j in range(nrows):
            r = row + j
            cur = base_kept if seg_kept is None else seg_kept
            dr = drv64[r]
            er = exc64[r]
            any_e = bool(fit_e_zb[r].any())
            any_d = bool(fit_d_zb[r].any())
            if not packed[r]:
                # Denial: could an excluded row have cured it? Excluded
                # rows' availability is static during the window, so the
                # per-zone maxima are a sound (conservative) upper bound.
                if any_e or any_d:
                    return False, "denial-curable"
            elif admitted[r]:
                # Only admitted rows subtract availability, so only their
                # CHOICES must be pinned; a packed-but-blocked row's flags
                # are already implied identical by the preceding checks.
                if strategy == "minimal-fragmentation" and any_e:
                    # Consumption order is capacity DESC — any excluded
                    # capacity can reorder it regardless of priority rank.
                    return False, "minfrag-excluded-capacity"
                d = int(drivers[r])
                dl = int(drivers_local[r])
                sel = execs[r] >= 0
                ev = execs[r][sel]
                el = execs_local[r][sel]
                if d < 0 or dl < 0 or (ev.size and (el < 0).any()):
                    return False, "non-kept-choice"  # cannot happen; belt+braces
                key_d = (k_az[dl], k_mem[dl], k_cpu[dl], k_name[dl])
                # (a) Excluded driver candidate with a better key that fits.
                zsel = fit_d_zb[r]
                if zsel.any():
                    better = _lex_lt(
                        az[zsel].astype(np.int64),
                        plan.e_key_drv[zsel, 0],
                        plan.e_key_drv[zsel, 1],
                        plan.e_key_drv[zsel, 2],
                        *key_d,
                    )
                    if better.any():
                        return False, "driver-excluded-better"
                # (c) Feasibility flip: the pruned solve rejected every
                # better-ranked kept fitting candidate for capacity; with
                # excluded capacity in play the full solve might not have.
                if any_e:
                    fits_kept = (cur >= dr[None, :]).all(axis=1) & cand_k
                    if fits_kept.any():
                        better_kept = fits_kept & _lex_lt(
                            k_az, k_mem, k_cpu, k_name, *key_d
                        )
                        if better_kept.any():
                            return False, "driver-feasibility-flip"
                if ev.size:
                    # (b) Worst chosen executor row vs best excluded
                    # executor-capable row, by segment-start keys.
                    cu = np.unique(el)
                    worst = cu[
                        np.lexsort(
                            (k_name[cu], k_cpu[cu], k_mem[cu], k_az[cu])
                        )[-1]
                    ]
                    key_w = (
                        k_az[worst], k_mem[worst], k_cpu[worst], k_name[worst]
                    )
                    zsel = fit_e_zb[r]
                    if zsel.any():
                        better = _lex_lt(
                            az[zsel].astype(np.int64),
                            plan.e_key_exec[zsel, 0],
                            plan.e_key_exec[zsel, 1],
                            plan.e_key_exec[zsel, 2],
                            *key_w,
                        )
                        if better.any():
                            return False, "executor-excluded-better"
                    # (d) distribute-evenly revisits nodes round-robin: a
                    # second round would have visited excluded open rows
                    # before re-filling kept ones.
                    if (
                        strategy == "distribute-evenly"
                        and any_e
                        and ev.size > len(cu)
                    ):
                        return False, "distribute-multi-round"
                # Apply the row's placements to the thread.
                is_commit = j == nrows - 1
                if is_commit:
                    target = base_kept
                    if dl >= 0:
                        np.add.at(zs_mem, [k_zone[dl]], -int(dr[MEM_DIM]))
                        np.add.at(zs_cpu, [k_zone[dl]], -int(dr[CPU_DIM]))
                    if ev.size:
                        np.add.at(
                            zs_mem, k_zone[el], -int(er[MEM_DIM])
                        )
                        np.add.at(
                            zs_cpu, k_zone[el], -int(er[CPU_DIM])
                        )
                    az_dirty = True
                else:
                    if seg_kept is None:
                        seg_kept = base_kept.copy()
                    target = seg_kept
                target[dl] -= dr
                np.subtract.at(target, el, er[None, :])
        row += nrows
    return True, None
