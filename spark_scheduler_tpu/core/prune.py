"""Sound top-K candidate pruning for the window solve (the two-tier solve).

At 100k nodes the window kernel scans every row per scan step even though a
32-driver window can only ever touch a few hundred of them. The two-tier
solve makes the device program O(K):

  Tier 1 (host prefilter, this module): rank the window domain's nodes by
  the solver's own placement key — the priority order the kernels sort by,
  (zone rank, available mem asc, cpu asc, name rank) — riding the
  feature-rank index's resident PER-ZONE orders (core/feature_store.
  RankIndex), and gather the top-K candidate rows per zone, K sized from
  the window's aggregate demand x `solver.prune-slack`. The device then
  solves a [K,3] gathered sub-cluster with one small h2d instead of
  shipping [B,N] masks.

  Tier 2 (the certificate, also this module): soundness is ENFORCED, not
  assumed. After the pruned solve, `certify_window` replays the window's
  availability thread host-side and verifies that no pruned-away row could
  have altered any decision:

    - zone ranks are byte-exact by construction (the excluded rows' per-zone
      availability sums ship into the kernel as constant offsets,
      ops/sorting.zone_ranks zone_base);
    - a DENIAL is certified only if no excluded row could have cured it
      (capacity-bound test over the excluded rows' per-zone availability
      maxima, for both the driver fit and the executor capacity);
    - an ADMISSION is certified only if (a) no excluded driver candidate
      with a better priority key could fit the driver, (b) no excluded
      executor-capable row ranks before the worst chosen executor row,
      (c) excluded capacity could not have flipped the feasibility of a
      better-ranked kept driver candidate the pruned solve rejected, and
      (d) strategy-specific order hazards are absent (minimal-fragmentation
      consumes by capacity DESC, so any excluded capacity escalates;
      distribute-evenly escalates on multi-round fills).

  A failed certificate ESCALATES the window: the solver re-solves it from
  the exact host reconstruction via the greedy oracle (core/fallback.py —
  slot-for-slot the kernels' semantics), so decisions stay byte-identical
  to the unpruned path by construction, and the escalation is counted in
  `foundry.spark.scheduler.solver.prune.*`.

Every test here is CONSERVATIVE (it may escalate a window the full solve
would have decided identically, never the reverse): per-dim maxima over
excluded rows overestimate fit, candidate masks are ignored for excluded
driver checks, and any uncertainty (a prior window's placement landing on
an excluded row, a non-kept index in the blob) escalates outright.

O(K + changed) planning (ISSUE 12, generalized to per-domain contexts in
ISSUE 15). The planner used to pay O(N) host sweeps per window; the
resident `PrunePlanner` retires them:

  - per-zone availability TOTALS live in resident, event-maintained
    aggregates — the full valid mask reads core/zone_aggregates.
    ZoneAggregates directly, and every SUBSET domain (the pooled engine's
    partition domains) keeps its own [Zb] totals, delta-maintained from
    the same dirty-row feed — so a window's `zone_base` excluded sums
    derive as `total − Σ kept` in O(K) for full AND partitioned windows;
  - the top-K kept rows, the excluded lexmin keys and the excluded
    per-dim maxima are CACHED per (domain, zone) and reused while the
    zone's excluded rows are untouched. The cache is sound by
    construction: every certificate input about excluded rows depends
    only on excluded rows, so churn confined to the kept rows (gang
    placements — the steady serving case) reuses the entry verbatim; a
    newly-valid row (node ADD) merges in exactly; a merged row BEATING
    the kept-set boundary is INSERTED into the kept order directly (the
    old K-th row evicts into the excluded summaries — O(K), ISSUE 15
    tentpole (c)) instead of forcing the historical O(zone) re-scan,
    which survives only for depletion / static flips on kept rows /
    exhausted leftover budgets;
  - consequently a no-churn window re-serves the identical kept row set
    (`plan_reuse`), which is what keys the solver's statics-gather reuse
    — per partition too, since each domain context owns its keep array.

A subset domain's FIRST plan still pays one vectorized O(N) sweep to
derive its per-zone membership and totals (`sweep_rows` counts it); after
that the domain context absorbs churn in O(changed) exactly like the full
domain. A domain MEMBERSHIP change (node add/delete inside the domain's
instance group) re-keys the window's domain mask object and cold-starts a
fresh context — the documented residual.

Gating (checked by the solver before planning): plain fills only (the
single-AZ wrappers score zones by subset-dependent efficiencies), no
configured label priorities (the keys above assume the label rank is
uniformly INT32_INF), and one shared domain per window (the pooled
partition path prunes per-partition instead, where each partition's domain
is uniform by construction).
"""

from __future__ import annotations

import dataclasses
import time as _time

import numpy as np

from spark_scheduler_tpu.models.resources import CPU_DIM, MEM_DIM

PLAIN_FILLS = frozenset(
    {"tightly-pack", "distribute-evenly", "minimal-fragmentation"}
)

_I64_MAX = np.iinfo(np.int64).max
_I64_MIN = np.iinfo(np.int64).min


from spark_scheduler_tpu.models.cluster import pad_bucket as _bucket  # noqa: E402


def zone_ranks_host(
    mem_sum: np.ndarray,  # [Z] int64 — per-zone available-memory sums
    cpu_sum: np.ndarray,  # [Z] int64
    present: np.ndarray,  # [Z] bool — zone has a (domain & valid) node
) -> np.ndarray:  # [Z] int32 — rank of each zone (0 = highest priority)
    """Host replica of ops/sorting.zone_ranks: ascending (mem, cpu), absent
    zones last, zone-id tiebreak. The kernel's chunked int32 aggregation is
    an exact int64 sum in normal form, so comparing int64 sums here yields
    the identical order — the certificate depends on that equality."""
    z = mem_sum.shape[0]
    absent = np.where(present, 0, 1)
    order = np.lexsort((np.arange(z), cpu_sum, mem_sum, absent))
    ranks = np.empty(z, np.int32)
    ranks[order] = np.arange(z, dtype=np.int32)
    return ranks


def split_zone_sums(sums: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 per-zone sums -> (hi, lo) int32 limbs for the device offset
    (hi = S >> 24 arithmetic, lo = S & 0xFFFFFF; exact for |S| < 2^55)."""
    return (
        (sums >> 24).astype(np.int32),
        (sums & 0xFFFFFF).astype(np.int32),
    )


def _lex_lt(a0, a1, a2, a3, b0, b1, b2, b3):
    """Vectorized (a0,a1,a2,a3) < (b0,b1,b2,b3) — the priority-key compare
    (az rank, mem, cpu, name rank), lower = higher priority."""
    return (a0 < b0) | (
        (a0 == b0)
        & (
            (a1 < b1)
            | (
                (a1 == b1)
                & ((a2 < b2) | ((a2 == b2) & (a3 < b3)))
            )
        )
    )


@dataclasses.dataclass
class PrunePlan:
    """One window's candidate-pruning decision: the kept row set, the
    device zone-sum offsets, and the excluded-row summaries the
    certificate tests against. All arrays are host numpy. Kept-row
    MEMBERSHIP is answered by bisecting the sorted real part of `keep`
    (the dense [N] kept_mask of the original implementation was an O(N)
    allocation per window — ISSUE 15 tentpole (d))."""

    keep: np.ndarray  # [Kp] int32 — kept global rows, real part SORTED
    #                     ascending, padding repeats keep[0]
    k_real: int  # number of real kept rows
    dom_mask: np.ndarray  # [N] bool — window domain & valid
    num_zones: int  # the solver's zone bucket Zb
    # Device offsets: excluded-row zone sums as int32 limbs + present.
    zone_base: tuple  # (mem_hi, mem_lo, cpu_hi, cpu_lo, present) [Zb] each
    # Dispatch-time zone sums over the WHOLE domain (kept + excluded) —
    # the certificate threads these (minus committed placements) to
    # replicate the kernel's per-segment zone ranks.
    zone_mem: np.ndarray  # [Zb] int64
    zone_cpu: np.ndarray  # [Zb] int64
    present: np.ndarray  # [Zb] bool
    # Excluded-row summaries, per zone, over rows RELEVANT to this window
    # (rows fitting the window's per-dim minimum demand; rows that fit no
    # request are provably transparent — zero capacity, no driver fit).
    # e_cnt_* is consumed as a PRESENCE flag (> 0) by the certificate; the
    # resident-cache fast path stores 0/1.
    e_cnt_exec: np.ndarray  # [Zb] int64 — relevant excluded exec-eligible
    e_max_exec: np.ndarray  # [Zb,3] int64 — per-dim avail max (conservative fit)
    e_key_exec: np.ndarray  # [Zb,3] int64 — lexmin (mem,cpu,name), I64_MAX pad
    e_cnt_drv: np.ndarray  # [Zb] int64
    e_max_drv: np.ndarray  # [Zb,3] int64
    e_key_drv: np.ndarray  # [Zb,3] int64
    # Per-request driver candidate masks gathered onto the kept rows.
    cand_kept: list  # [B_req] of [Kp] bool
    dom_rows: int  # |domain| (stats)
    # True when the kept row set (`keep` array object) was re-served from
    # the per-zone cache unchanged — the key for the solver's
    # statics-gather reuse.
    reused: bool = False
    plan_ms: float = 0.0  # prefilter planning wall time
    offset_ms: float = 0.0  # zone_base offset derivation wall time


class _ZoneEntry:
    """Cached per-(domain, zone) prefilter state: the kept rows and the
    excluded-row summaries for one zone. An excluded-row change keeps the
    entry SOUND by merging the row's new state (exact-direction: min/max/
    presence can only extend) while the old contribution lingers as a
    conservative leftover; `stale` counts those leftovers so the zone
    re-scans before conservatism drifts into spurious escalations."""

    __slots__ = (
        "kept_e", "kept_d", "keep", "has_e", "has_d",
        "key_e", "key_d", "max_e", "max_d", "stale", "depleted",
        "last_key_e", "last_key_d",
    )

    def __init__(self, kept_e, kept_d, has_e, has_d, key_e, key_d,
                 max_e, max_d, last_key_e=None, last_key_d=None):
        self.kept_e = kept_e
        self.kept_d = kept_d
        self.keep = np.unique(np.concatenate([kept_e, kept_d]))
        self.has_e = has_e
        self.has_d = has_d
        self.key_e = key_e  # int64[3] lexmin (mem, cpu, name) or I64_MAX
        self.key_d = key_d
        self.max_e = max_e  # int64[3] per-dim max or I64_MIN
        self.max_d = max_d
        self.stale = 0
        # Kept rows whose availability dropped below the window minima:
        # still sound to keep (the kernel just skips them), but a zone
        # whose kept set depletes while fresh excluded capacity sits
        # outside WILL eventually fail the certificate (the full solve
        # would place there) — refresh the entry before that costs an
        # escalation.
        self.depleted = 0
        # Key of the K-th (worst) kept row per class at build time — the
        # kept-set BOUNDARY. A merged row whose key beats it belongs in
        # the kept set: it is INSERTED directly (the old K-th row evicts
        # into the excluded summaries — O(K), ISSUE 15) instead of
        # forcing the O(zone) re-scan. None = the zone kept every
        # fitting row, so ANY new fitting row simply joins the set.
        self.last_key_e = last_key_e
        self.last_key_d = last_key_d


class _DomCtx:
    """Resident planning context for ONE window domain: the per-zone
    entries, the assembled kept set, and the minima/K the entries were
    built for. The FULL-domain context (`dom_mask is None`) reads its
    per-zone availability totals live from the resident ZoneAggregates;
    a SUBSET domain (a pooled partition's instance group) owns [Zb]
    totals of its member rows, delta-maintained from the same dirty-row
    feed — the per-partition analog of the aggregates."""

    __slots__ = (
        "dom_mask", "entries", "keep", "keep_real",
        "min_dr", "min_er", "k", "zone_mem", "zone_cpu", "zcnt",
    )

    def __init__(self, dom_mask=None):
        self.dom_mask = dom_mask  # None = the full valid mask
        self.entries: dict[int, _ZoneEntry] = {}
        self.keep: np.ndarray | None = None  # assembled padded keep
        self.keep_real = 0
        self.min_dr: np.ndarray | None = None  # None = COLD
        self.min_er: np.ndarray | None = None
        self.k = 0
        # Subset domains only: event-maintained per-zone totals.
        self.zone_mem: np.ndarray | None = None
        self.zone_cpu: np.ndarray | None = None
        self.zcnt: np.ndarray | None = None


def _key_lt(a, b) -> bool:
    """Lexicographic (mem, cpu, name) triple compare."""
    for x, y in zip(a, b):
        if x != y:
            return x < y
    return False


def _merge_excluded(
    entry, r: int, avail, min_dr, min_er, unsched, ready, name_rank
) -> None:
    """Fold one EXCLUDED row's current state into a zone entry's
    summaries — presence / lexmin key / per-dim maxima, per class, exact
    direction (joining a summary can only extend it). The single shared
    body of the merge, boundary-insert eviction and depletion-refresh
    paths: the certificate's summary contract lives here once."""
    av = avail[r].astype(np.int64)
    key = (
        int(avail[r, MEM_DIM]),
        int(avail[r, CPU_DIM]),
        int(name_rank[r]),
    )
    if (av >= min_dr).all():
        entry.has_d = True
        if _key_lt(key, entry.key_d):
            entry.key_d = key
        entry.max_d = np.maximum(entry.max_d, av)
    if (av >= min_er).all() and not unsched[r] and ready[r]:
        entry.has_e = True
        if _key_lt(key, entry.key_e):
            entry.key_e = key
        entry.max_e = np.maximum(entry.max_e, av)


class PrunePlanner:
    """O(K + changed) window planning over resident per-(domain, zone)
    state.

    Owns the per-zone RankIndex (priority orders), the ZoneAggregates
    (availability totals), the full-domain plan context and one cached
    context per subset domain (the pooled partition path). The solver
    feeds it the EXACT changed rows it already knows (pipelined-build
    delta rows, static row-deltas, fetched placement rows); a serving
    path that cannot name its rows marks the planner UNKNOWN and the next
    sync pays one vectorized snapshot compare instead.
    """

    # Cached subset-domain contexts (pooled partitions): enough for a
    # realistic instance-group fan-out; overflow clears the oldest-built.
    _MAX_DOM_CTXS = 16

    def __init__(self, stats: dict | None = None):
        from spark_scheduler_tpu.core.feature_store import RankIndex
        from spark_scheduler_tpu.core.zone_aggregates import ZoneAggregates

        self.index = RankIndex()
        self.agg = ZoneAggregates()
        self._full = _DomCtx(None)
        self._dom_ctxs: dict = {}  # dom_key -> _DomCtx (subset domains)
        # [N] bool exec-eligibility snapshot (~unschedulable & ready):
        # distinguishes a RANK-only static relabel (benign for a kept
        # row) from an eligibility flip (re-scan) at absorb time.
        self._elig: np.ndarray | None = None
        # Pending change feed (drained at sync): explicit dirty rows,
        # static-delta rows, or None = unknown (snapshot compare).
        self._dirty: list | None = []
        self._static: list = []
        self.stats = stats if stats is not None else {}
        for key in (
            "planner_rows_scanned", "planner_cold_rows",
            "planner_sweep_rows", "planner_resync_rows",
            "planner_zone_rescans", "planner_zone_refreshes",
            "planner_merges", "planner_boundary_inserts", "plan_reuse",
        ):
            self.stats.setdefault(key, 0)

    # -- change feed ---------------------------------------------------------

    def invalidate(self) -> None:
        self.index.invalidate()
        self.agg.invalidate()
        self._full = _DomCtx(None)  # next build is COLD (counter attribution)
        self._dom_ctxs.clear()
        self._dirty = []
        self._static = []

    def note_dirty(self, rows) -> None:
        """Rows whose availability changed (exact — pipelined build deltas,
        fetched placement rows)."""
        if self._dirty is not None and len(rows):
            self._dirty.append(np.asarray(rows))

    def note_static(self, rows) -> None:
        """Rows whose STATIC fields changed (static row-delta: validity,
        zone, name rank, eligibility flags)."""
        if len(rows):
            self._static.append(np.asarray(rows))

    def mark_unknown(self) -> None:
        """A serving path touched availability without naming rows (dense
        unpruned fetch): the next sync diff-scans the snapshots."""
        self._dirty = None

    def reset_plan_entries(self) -> None:
        """Drop every cached kept set / excluded summary while KEEPING
        the resident index and aggregates (re-scans are O(zone), not the
        O(N log N) cold rebuild). Called after a certificate escalation:
        conservative drift (depletion-refresh carry-overs, stale merge
        leftovers) may have caused it, and re-scanning to exactness
        guarantees an escalation can never loop on the same stale entry."""
        self._full.entries.clear()
        self._full.keep = None
        for ctx in self._dom_ctxs.values():
            ctx.entries.clear()
            ctx.keep = None

    # -- sync ----------------------------------------------------------------

    def sync(self, host, num_zones: int) -> None:
        """Bring the resident index/aggregates/contexts up to the CURRENT
        host view, in O(changed) when the change feed is exact."""
        avail = np.asarray(host.available)
        zid = np.asarray(host.zone_id)
        valid = np.asarray(host.valid)
        name_rank = np.asarray(host.name_rank)
        n = avail.shape[0]
        if (
            not self.index.valid
            or not self.agg.valid
            or self.index.rows != n
            or self.index.num_zones != num_zones
        ):
            self._rebuild(avail, name_rank, zid, valid, num_zones)
            return
        if self._elig is None or self._elig.shape[0] != n:
            # Eligibility snapshot as of THIS sync's entry (pre-absorb):
            # initialized here — never inside absorb, where host already
            # reflects the very events being classified.
            self._elig = (
                ~np.asarray(host.unschedulable, bool)
                & np.asarray(host.ready, bool)
            ).copy()
        if self._dirty is None:
            dirty = self.agg.diff_rows(avail)
            self.stats["planner_resync_rows"] += n
        else:
            dirty = (
                np.unique(np.concatenate(self._dirty))
                if self._dirty
                else np.empty(0, np.int64)
            )
        static = (
            np.unique(np.concatenate(self._static))
            if self._static
            else np.empty(0, np.int64)
        )
        self._dirty = []
        self._static = []
        if dirty.size == 0 and static.size == 0:
            return
        all_dirty = (
            np.union1d(dirty, static) if static.size else dirty
        )
        if all_dirty.size > max(1024, n // 4):
            self._rebuild(avail, name_rank, zid, valid, num_zones)
            return
        self._absorb(all_dirty, static, avail, zid, valid, host)
        self.index.update_rows(avail, name_rank, all_dirty, zone_id=zid)
        self.agg.update_rows(avail, zid, valid, all_dirty)
        if self._elig is not None and all_dirty.size:
            rows = all_dirty[all_dirty < self._elig.shape[0]]
            self._elig[rows] = (
                ~np.asarray(host.unschedulable, bool)[rows]
                & np.asarray(host.ready, bool)[rows]
            )

    def _rebuild(self, avail, name_rank, zid, valid, num_zones) -> None:
        self.index.rebuild(avail, name_rank, zid, num_zones)
        self.agg.rebuild(avail, zid, valid, num_zones)
        self._full.entries.clear()
        self._full.keep = None
        self._dom_ctxs.clear()
        self._dirty = []
        self._static = []
        self._elig = None  # re-snapshotted lazily at the next absorb

    # Conservative-leftover budget per zone entry: each absorbed
    # excluded-row change leaves the row's OLD contribution behind in the
    # per-zone summaries (sound, but it can only over-approximate); past
    # this many leftovers the zone re-scans to restore exactness before
    # the drift causes spurious escalations.
    _STALE_BUDGET = 32

    def _absorb(self, all_dirty, static, avail, zid, valid, host) -> None:
        """Absorb the changed rows into every cached plan context, BEFORE
        the snapshots move:

          benign  — a non-static change to a KEPT row: the excluded-row
                    summaries depend only on excluded rows, so the entry
                    stands verbatim (the steady-serving case: gang
                    placements land on kept rows);
          insert  — a change to a NON-KEPT row whose key BEATS the kept
                    boundary (a node ADD whose name sorts first): the row
                    is inserted into the kept order directly and the old
                    K-th row evicts into the excluded summaries — O(K),
                    no re-scan (ISSUE 15 tentpole (c));
          merge   — any other change to a NON-KEPT row: the row's NEW
                    state merges exactly (joining a summary can only
                    extend min/max/presence), while its old contribution
                    lingers as a conservative leftover — sound by the
                    certificate's over-approximation contract. Leftovers
                    are budgeted (`_STALE_BUDGET`) per zone;
          rescan  — a STATIC flip on a kept row (validity/zone/rank of a
                    kept row breaks the `total − kept` offset identity),
                    kept-set depletion past the budget, or an exhausted
                    leftover budget: drop the zone's entry; the next plan
                    re-scans just that zone.
        """
        ctxs = [self._full] + list(self._dom_ctxs.values())
        live = [
            c for c in ctxs
            if c.entries or (c.dom_mask is not None and c.zcnt is not None)
        ]
        if not live:
            return
        if all_dirty.size > 4096:
            # A bulk churn burst (resync after a dense fetch, a huge
            # delta): dropping every context is cheaper and exact — the
            # next plan re-scans the zones (or domains) it needs.
            self._full.entries.clear()
            self._full.keep = None
            self._dom_ctxs.clear()
            return
        n = avail.shape[0]
        all_dirty = all_dirty[all_dirty < n]
        if not all_dirty.size:
            return
        old_zone = self.agg.zone_of(all_dirty)
        new_zone = zid[all_dirty].astype(np.int32)
        was_valid = self.agg.valid_of(all_dirty)
        is_static = (
            np.isin(all_dirty, static) if static.size else
            np.zeros(all_dirty.shape[0], bool)
        )
        unsched = np.asarray(host.unschedulable, bool)
        ready = np.asarray(host.ready, bool)
        name_rank = np.asarray(host.name_rank)
        # A kept row's static flip forces a zone re-scan ONLY when it
        # breaks the `total − kept` offset identity (zone move, validity
        # flip) or the row's exec eligibility. Rank/label relabels — the
        # name-rank REBALANCE a node-ADD burst scatters over the insert
        # point's neighborhood — leave sums, membership, eligibility and
        # the excluded summaries exact: treating them as re-scans made
        # every burst add O(zone) again (the pre-ISSUE-15 ADD-burst p99).
        elig_new = ~unsched[all_dirty] & ready[all_dirty]
        keeps_identity = (
            (old_zone == new_zone)
            & (was_valid == np.asarray(valid, bool)[all_dirty])
            & (self._elig[all_dirty] == elig_new)
        )
        for ctx in live:
            if ctx.dom_mask is not None and ctx.zcnt is not None:
                # Per-domain totals: subtract the rows' old contribution
                # (agg snapshots — not yet updated this sync) and add the
                # new, restricted to domain members.
                sel = all_dirty[ctx.dom_mask[all_dirty]]
                if sel.size:
                    self._ctx_totals_update(ctx, sel, avail, zid, valid)
            if not ctx.entries:
                continue
            self._absorb_ctx(
                ctx, all_dirty, old_zone, new_zone, was_valid, is_static,
                keeps_identity, avail, valid, unsched, ready, name_rank,
            )

    def _ctx_totals_update(self, ctx, rows, avail, zid, valid) -> None:
        old_v = self.agg.valid_of(rows)
        ov = rows[old_v]
        if ov.size:
            oz = self.agg.zone_of(ov)
            np.add.at(ctx.zcnt, oz, -1)
            np.add.at(ctx.zone_mem, oz, -self.agg.mem_of(ov))
            np.add.at(ctx.zone_cpu, oz, -self.agg.cpu_of(ov))
        nv = rows[np.asarray(valid, bool)[rows]]
        if nv.size:
            nz = np.asarray(zid)[nv]
            np.add.at(ctx.zcnt, nz, 1)
            np.add.at(ctx.zone_mem, nz, avail[nv, MEM_DIM].astype(np.int64))
            np.add.at(ctx.zone_cpu, nz, avail[nv, CPU_DIM].astype(np.int64))

    def _absorb_ctx(
        self, ctx, all_dirty, old_zone, new_zone, was_valid, is_static,
        keeps_identity, avail, valid, unsched, ready, name_rank,
    ) -> None:
        dm = ctx.dom_mask
        for i, r in enumerate(all_dirty):
            if dm is not None and not dm[r]:
                continue
            oz, nz = int(old_zone[i]), int(new_zone[i])
            entry = ctx.entries.get(nz)
            in_keep = False
            if entry is not None and entry.keep.size:
                p = np.searchsorted(entry.keep, r)
                in_keep = bool(
                    p < entry.keep.size and entry.keep[p] == r
                )
            if in_keep:
                if not is_static[i] or keeps_identity[i]:
                    # Benign: kept-row value churn, or a static relabel
                    # (name/label rank) that leaves zone and validity —
                    # the offset identity's inputs — untouched. Track
                    # DEPLETION either way: a kept row that no longer
                    # fits either class minimum (or lost exec
                    # eligibility) is dead weight, and a zone serving
                    # mostly-depleted kept rows while fresh excluded
                    # capacity exists will fail its certificate;
                    # refresh first.
                    av = avail[r]
                    if ctx.min_dr is not None and (
                        not (
                            (av >= ctx.min_dr).all()
                            or (av >= ctx.min_er).all()
                        )
                        or (is_static[i] and (unsched[r] or not ready[r]))
                    ):
                        entry.depleted += 1
                        # Aggressive on purpose: a zone serving depleted
                        # kept rows ranks FIRST (lowest totals), so the
                        # full solve would reach for its excluded rows
                        # almost immediately. The refresh re-picks the
                        # kept set by an EARLY-EXIT walk of the order —
                        # O(K + consumed prefix), not O(zone) — far
                        # cheaper than the escalation it prevents.
                        if entry.depleted > max(1, ctx.k // 8):
                            self._refresh_zone(
                                ctx, nz, entry, avail, valid, unsched,
                                ready, name_rank,
                            )
                    continue
                # Zone move / validity flip of a KEPT row: the offset
                # identity needs every kept row live in its zone —
                # re-scan.
                ctx.entries.pop(nz, None)
                ctx.keep = None
                continue
            # Non-kept row: merge its new state (exact direction), note
            # the leftover. A zone move leaves its old zone's summaries
            # as leftovers too.
            if oz != nz:
                old_entry = ctx.entries.get(oz)
                if old_entry is not None:
                    kp = old_entry.keep
                    p = np.searchsorted(kp, r) if kp.size else 0
                    if kp.size and p < kp.size and kp[p] == r:
                        # The moved row was KEPT under its old zone: the
                        # old entry's offset identity is broken — re-scan.
                        ctx.entries.pop(oz, None)
                        ctx.keep = None
                    else:
                        old_entry.stale += 1
                        if old_entry.stale > self._STALE_BUDGET:
                            ctx.entries.pop(oz, None)
                            ctx.keep = None
            if entry is None:
                continue
            if bool(valid[r]):
                self._merge_row(
                    ctx, entry, int(r), avail, unsched, ready, name_rank
                )
            if not was_valid[i]:
                # A brand-new valid row (node ADD) merged EXACTLY — it
                # has no old contribution, so no leftover to budget.
                continue
            if is_static[i] and keeps_identity[i]:
                # Rank/label-only relabel of an excluded row (the ADD
                # burst's rebalance neighborhood): sums, counts and
                # per-dim maxima are untouched; only the lexmin keys'
                # NAME component can go conservative-stale. Charging the
                # leftover budget made every ~32 relabels force an
                # O(zone) re-scan — a steady add stream relabels
                # hundreds. Certificate soundness is unaffected (stale
                # keys only over-approximate).
                continue
            entry.stale += 1
            if entry.stale > self._STALE_BUDGET:
                ctx.entries.pop(nz, None)
                ctx.keep = None

    def _merge_row(
        self, ctx, entry, r, avail, unsched, ready, name_rank
    ) -> None:
        """Absorb one non-kept row's NEW state into the zone entry. A row
        BEATING a class's kept-set boundary is inserted into that class's
        kept order directly (evicting the tail into the excluded
        summaries — O(K), no re-scan); anything else merges into the
        excluded summaries (exact direction)."""
        av = avail[r].astype(np.int64)
        key = (
            int(avail[r, MEM_DIM]),
            int(avail[r, CPU_DIM]),
            int(name_rank[r]),
        )
        fits_d = bool((av >= ctx.min_dr).all())
        fits_e = bool(
            (av >= ctx.min_er).all() and not unsched[r] and ready[r]
        )
        ins_d = fits_d and (
            entry.last_key_d is None or _key_lt(key, entry.last_key_d)
        )
        ins_e = fits_e and (
            entry.last_key_e is None or _key_lt(key, entry.last_key_e)
        )
        if ins_d or ins_e:
            self._boundary_insert(
                ctx, entry, r, key, ins_d, ins_e,
                avail, unsched, ready, name_rank,
            )
            return
        _merge_excluded(
            entry, r, avail, ctx.min_dr, ctx.min_er,
            unsched, ready, name_rank,
        )
        self.stats["planner_merges"] += 1

    def _boundary_insert(
        self, ctx, entry, r, key, ins_d, ins_e,
        avail, unsched, ready, name_rank,
    ) -> None:
        """Insert a boundary-beating row into the kept order (tentpole
        (c)): O(K) — the row takes its key position per class, the old
        K-th row evicts into the excluded summaries exactly (an evicted
        row joins a summary for the first time, so there is no leftover
        to budget), and the class boundary key refreshes from the new
        tail. The assembled window keep is invalidated (reassembled in
        O(K) at the next plan); the per-zone summaries stay exact."""
        self.stats["planner_boundary_inserts"] += 1
        evicted: list[int] = []
        for cls, ins in (("d", ins_d), ("e", ins_e)):
            if not ins:
                continue
            kept = entry.kept_d if cls == "d" else entry.kept_e
            mem = avail[kept, MEM_DIM].astype(np.int64)
            cpu = avail[kept, CPU_DIM].astype(np.int64)
            nr = name_rank[kept].astype(np.int64)
            after = (mem > key[0]) | (
                (mem == key[0])
                & ((cpu > key[1]) | ((cpu == key[1]) & (nr > key[2])))
            )
            pos = int(np.argmax(after)) if bool(after.any()) else int(kept.size)
            new = np.insert(kept, pos, np.int32(r))
            if new.size > ctx.k:
                evicted.append(int(new[-1]))
                new = new[: ctx.k]
            if new.size >= ctx.k:
                last = int(new[-1])
                lk = (
                    int(avail[last, MEM_DIM]),
                    int(avail[last, CPU_DIM]),
                    int(name_rank[last]),
                )
            else:
                lk = None
            if cls == "d":
                entry.kept_d, entry.last_key_d = new, lk
            else:
                entry.kept_e, entry.last_key_e = new, lk
        entry.keep = np.unique(
            np.concatenate([entry.kept_e, entry.kept_d])
        )
        keep = entry.keep
        for ev in evicted:
            p = np.searchsorted(keep, ev)
            if p < keep.size and keep[p] == ev:
                continue  # still kept via the other class
            _merge_excluded(
                entry, ev, avail, ctx.min_dr, ctx.min_er,
                unsched, ready, name_rank,
            )
        ctx.keep = None

    def _refresh_zone(
        self, ctx, z, entry, avail, valid, unsched, ready, name_rank
    ) -> None:
        """Depletion refresh (ISSUE 15 residual (d)): re-pick the zone's
        kept rows by walking the resident order with EARLY EXIT — the
        depleted (most-consumed) rows sort FIRST in the order, so the
        walk costs O(K + consumed prefix), not O(zone). Rows leaving the
        kept set merge into the excluded summaries exactly; everything
        beyond the scanned prefix keeps its old (excluded) contribution
        — conservative, and budgeted like any other leftover, so the
        exact O(zone) re-scan still runs when conservatism accumulates.
        """
        zo = self.index.zone_order(z)
        k = ctx.k
        if zo.size <= max(4096, 8 * k):
            # Small zone: the exact re-scan costs about the same as the
            # walk — take exactness (no conservative carry-over).
            ctx.entries.pop(z, None)
            ctx.keep = None
            return
        dm = ctx.dom_mask
        sel_e: list = []
        sel_d: list = []
        n_e = n_d = 0
        pos = 0
        step = max(512, 4 * k)
        scanned = 0
        while pos < zo.size and (n_e <= k or n_d <= k):
            chunk = zo[pos:pos + step]
            pos += step
            scanned += int(chunk.size)
            live = (
                valid[chunk] if dm is None else (dm[chunk] & valid[chunk])
            )
            chunk = chunk[live]
            if not chunk.size:
                continue
            av = avail[chunk]
            fd = (av >= ctx.min_dr).all(axis=1)
            fe = (
                (av >= ctx.min_er).all(axis=1)
                & ~unsched[chunk]
                & ready[chunk]
            )
            if fd.any():
                sel_d.append(chunk[fd])
                n_d += int(fd.sum())
            if fe.any():
                sel_e.append(chunk[fe])
                n_e += int(fe.sum())
        self.stats["planner_rows_scanned"] += scanned
        self.stats["planner_zone_refreshes"] = (
            self.stats.get("planner_zone_refreshes", 0) + 1
        )
        fit_d = (
            np.concatenate(sel_d).astype(np.int32)
            if sel_d
            else np.empty(0, np.int32)
        )
        fit_e = (
            np.concatenate(sel_e).astype(np.int32)
            if sel_e
            else np.empty(0, np.int32)
        )

        def _key_of(r: int):
            return (
                int(avail[r, MEM_DIM]),
                int(avail[r, CPU_DIM]),
                int(name_rank[r]),
            )

        old_keep = entry.keep
        entry.kept_d = fit_d[:k]
        entry.kept_e = fit_e[:k]
        entry.keep = np.unique(
            np.concatenate([entry.kept_e, entry.kept_d])
        )
        entry.depleted = 0
        entry.stale += 1  # conservative carry-over: budget the drift
        entry.last_key_d = (
            _key_of(int(entry.kept_d[k - 1]))
            if entry.kept_d.size >= k
            else None
        )
        entry.last_key_e = (
            _key_of(int(entry.kept_e[k - 1]))
            if entry.kept_e.size >= k
            else None
        )
        # First fitting row past each kept prefix joins the lexmin/max
        # conservatively (it is the class's new excluded best within the
        # scanned prefix; beyond-scan rows were excluded before and keep
        # their old contributions).
        if fit_d.size > k:
            _merge_excluded(
                entry, int(fit_d[k]), avail, ctx.min_dr, ctx.min_er,
                unsched, ready, name_rank,
            )
        if fit_e.size > k:
            _merge_excluded(
                entry, int(fit_e[k]), avail, ctx.min_dr, ctx.min_er,
                unsched, ready, name_rank,
            )
        # Rows LEAVING the kept set merge in exactly (first membership in
        # the excluded summaries — their current state).
        if old_keep.size and entry.keep.size:
            p = np.clip(
                np.searchsorted(entry.keep, old_keep),
                0, entry.keep.size - 1,
            )
            gone = old_keep[entry.keep[p] != old_keep]
        else:
            gone = old_keep
        for r in gone:
            r = int(r)
            if bool(valid[r]) and (dm is None or bool(dm[r])):
                _merge_excluded(
                    entry, r, avail, ctx.min_dr, ctx.min_er,
                    unsched, ready, name_rank,
                )
        if entry.stale > self._STALE_BUDGET:
            ctx.entries.pop(z, None)  # exact re-scan at the next plan
        ctx.keep = None

    # -- planning ------------------------------------------------------------

    def plan_full_domain(
        self, host, *, cand_per_req, drv_arr, exc_arr, counts,
        num_zones, top_k, slack,
    ) -> PrunePlan | None:
        """O(K + changed) plan for a window whose shared domain is the
        full valid mask (the resident aggregates' coverage)."""
        return self._plan_ctx(
            self._full, host,
            cand_per_req=cand_per_req, drv_arr=drv_arr, exc_arr=exc_arr,
            counts=counts, num_zones=num_zones, top_k=top_k, slack=slack,
        )

    def plan_with_masks(
        self, host, *, dom_mask, cand_per_req, drv_arr, exc_arr, counts,
        num_zones, top_k, slack, dom_key=None,
    ) -> PrunePlan | None:
        """Plan for a window whose shared domain is a SUBSET of the
        cluster (instance-group pinned domains — the pooled partition
        path). The FIRST plan per domain pays one vectorized O(N) sweep
        to derive the domain's per-zone membership and totals (counted in
        `planner_sweep_rows`); the resulting context is cached under
        `dom_key` and every later window plans in O(K + changed) exactly
        like the full domain — including kept-set reuse, which keys the
        solver's per-partition statics-gather reuse (ISSUE 15 tentpole
        (b)). Reuse requires the SAME dom_mask object: a domain
        MEMBERSHIP change re-keys the mask and cold-starts the context."""
        ctx = self._dom_ctxs.get(dom_key) if dom_key is not None else None
        if ctx is not None and ctx.dom_mask is not dom_mask:
            dm = np.asarray(dom_mask, bool)
            if ctx.dom_mask.shape == dm.shape and np.array_equal(
                ctx.dom_mask, dm
            ):
                # A node event ELSEWHERE re-keyed the mask object without
                # changing this domain's content (an add/delete in another
                # instance group flips `valid` rows outside the domain):
                # adopt the new object and keep the context. One O(N)
                # compare per node event per domain — never per window.
                ctx.dom_mask = dm
            else:
                ctx = None  # membership changed: cold-start fresh
        if ctx is None:
            ctx = self._cold_dom_ctx(host, dom_mask, num_zones)
            if dom_key is not None:
                while len(self._dom_ctxs) >= self._MAX_DOM_CTXS:
                    # Evict the oldest-built context only — clearing the
                    # whole cache would cold-start every warm domain.
                    self._dom_ctxs.pop(next(iter(self._dom_ctxs)))
                self._dom_ctxs[dom_key] = ctx
        return self._plan_ctx(
            ctx, host,
            cand_per_req=cand_per_req, drv_arr=drv_arr, exc_arr=exc_arr,
            counts=counts, num_zones=num_zones, top_k=top_k, slack=slack,
        )

    def _cold_dom_ctx(self, host, dom_mask, num_zones) -> _DomCtx:
        """One vectorized sweep deriving a subset domain's per-zone
        membership counts and availability totals — the context's only
        O(N) moment (legacy `planner_sweep_rows` semantics)."""
        avail = np.asarray(host.available)
        zone_id = np.asarray(host.zone_id)
        valid = np.asarray(host.valid)
        n = avail.shape[0]
        self.stats["planner_sweep_rows"] += n
        ctx = _DomCtx(np.asarray(dom_mask, bool))
        live = ctx.dom_mask & valid
        lz = zone_id[live]
        ctx.zcnt = np.bincount(lz, minlength=num_zones).astype(np.int64)
        ctx.zone_mem = np.zeros(num_zones, np.int64)
        ctx.zone_cpu = np.zeros(num_zones, np.int64)
        np.add.at(ctx.zone_mem, lz, avail[live, MEM_DIM].astype(np.int64))
        np.add.at(ctx.zone_cpu, lz, avail[live, CPU_DIM].astype(np.int64))
        return ctx

    def _plan_ctx(
        self, ctx, host, *, cand_per_req, drv_arr, exc_arr, counts,
        num_zones, top_k, slack,
    ) -> PrunePlan | None:
        t0 = _time.perf_counter()
        avail = np.asarray(host.available)
        valid = np.asarray(host.valid)
        zid = np.asarray(host.zone_id)
        b = drv_arr.shape[0]
        min_dr = drv_arr.min(axis=0).astype(np.int64)
        min_er = exc_arr.min(axis=0).astype(np.int64)
        demand = int(counts.sum()) + b
        # Power-of-two bucketed K: keeps the per-zone cache (and the kept
        # row set) stable across window-demand jitter at the cost of at
        # most 2x extra kept rows.
        k = _bucket(max(int(top_k), int(np.ceil(demand * slack))), 1)
        full = ctx.dom_mask is None
        # Cache-key drift: a LOWER per-dim minimum demand or a LARGER K
        # widens the relevant-row sets, which the cached excluded
        # summaries cannot soundly describe — full re-scan.
        # COLD = building from nothing (first plan, or right after an
        # invalidate — invalidate() resets the cached minima). Everything
        # else (K/minima widening, churn-dropped entries) counts as rows
        # SCANNED, so the CI O(K) assertion sees every incremental sweep.
        cold = ctx.min_dr is None
        if cold or (
            k > ctx.k
            or (min_dr < ctx.min_dr).any()
            or (min_er < ctx.min_er).any()
        ):
            ctx.entries.clear()
            ctx.keep = None
            ctx.min_dr = min_dr
            ctx.min_er = min_er
            ctx.k = k
        counter = "planner_cold_rows" if cold else "planner_rows_scanned"
        unsched = np.asarray(host.unschedulable, bool)
        ready = np.asarray(host.ready, bool)
        name_rank = np.asarray(host.name_rank)
        zcnt = self.agg.cnt if full else ctx.zcnt
        zones = np.flatnonzero(zcnt > 0)
        changed = ctx.keep is None
        for z in zones:
            if int(z) not in ctx.entries:
                self._rescan_zone(
                    ctx, int(z), avail, valid, unsched, ready, name_rank,
                    counter,
                )
                changed = True
        dom_rows = int(zcnt.sum())
        if changed:
            keeps = [
                ctx.entries[int(z)].keep
                for z in zones
                if int(z) in ctx.entries
            ]
            keep_real = (
                np.sort(np.concatenate(keeps)).astype(np.int32)
                if keeps
                else np.empty(0, np.int32)
            )
            k_real = int(keep_real.shape[0])
            if k_real == 0 or k_real >= 0.7 * dom_rows:
                ctx.keep = None
                return None
            kp = _bucket(k_real, 64)
            keep_padded = np.full(kp, keep_real[0], np.int32)
            keep_padded[:k_real] = keep_real
            ctx.keep = keep_padded
            ctx.keep_real = k_real
        else:
            keep_padded = ctx.keep
            k_real = ctx.keep_real
            if k_real == 0 or k_real >= 0.7 * dom_rows:
                return None
            self.stats["plan_reuse"] += 1
        keep_real_v = keep_padded[:k_real]

        # Assemble the certificate's per-zone summary arrays from the
        # entries (Zb is small).
        zb = num_zones
        e_cnt_e = np.zeros(zb, np.int64)
        e_cnt_d = np.zeros(zb, np.int64)
        e_max_e = np.full((zb, avail.shape[1]), _I64_MIN, np.int64)
        e_max_d = np.full((zb, avail.shape[1]), _I64_MIN, np.int64)
        e_key_e = np.full((zb, 3), _I64_MAX, np.int64)
        e_key_d = np.full((zb, 3), _I64_MAX, np.int64)
        for z in zones:
            entry = ctx.entries.get(int(z))
            if entry is None:
                continue
            if entry.has_e:
                e_cnt_e[z] = 1
                e_max_e[z] = entry.max_e
                e_key_e[z] = entry.key_e
            if entry.has_d:
                e_cnt_d[z] = 1
                e_max_d[z] = entry.max_d
                e_key_d[z] = entry.key_d

        # Offsets: excluded sums = resident totals − Σ kept, O(K).
        t1 = _time.perf_counter()
        tot_mem = self.agg.mem if full else ctx.zone_mem
        tot_cpu = self.agg.cpu if full else ctx.zone_cpu
        kept_avail = avail[keep_real_v].astype(np.int64)
        kz = zid[keep_real_v]
        kept_mem = np.zeros(zb, np.int64)
        kept_cpu = np.zeros(zb, np.int64)
        np.add.at(kept_mem, kz, kept_avail[:, MEM_DIM])
        np.add.at(kept_cpu, kz, kept_avail[:, CPU_DIM])
        s_mem = tot_mem - kept_mem
        s_cpu = tot_cpu - kept_cpu
        present = zcnt > 0
        mem_hi, mem_lo = split_zone_sums(s_mem)
        cpu_hi, cpu_lo = split_zone_sums(s_cpu)
        t2 = _time.perf_counter()

        # Gather the per-request candidate masks onto the kept rows,
        # deduplicated by mask identity — serving requests overwhelmingly
        # share ONE candidate ticket, so the window pays one [K] gather
        # instead of B (the 16-wide residual, ISSUE 15 tentpole (d)).
        gather_memo: dict[int, np.ndarray] = {}
        cand_kept = []
        for c in cand_per_req:
            g = gather_memo.get(id(c))
            if g is None:
                g = np.asarray(c)[keep_padded]
                gather_memo[id(c)] = g
            cand_kept.append(g)
        return PrunePlan(
            keep=keep_padded,
            k_real=k_real,
            dom_mask=valid if full else ctx.dom_mask,
            num_zones=zb,
            zone_base=(mem_hi, mem_lo, cpu_hi, cpu_lo, present),
            zone_mem=np.asarray(tot_mem).copy(),
            zone_cpu=np.asarray(tot_cpu).copy(),
            present=present,
            e_cnt_exec=e_cnt_e,
            e_max_exec=e_max_e,
            e_key_exec=e_key_e,
            e_cnt_drv=e_cnt_d,
            e_max_drv=e_max_d,
            e_key_drv=e_key_d,
            cand_kept=cand_kept,
            dom_rows=dom_rows,
            reused=not changed,
            plan_ms=(t2 - t0) * 1e3,
            offset_ms=(t2 - t1) * 1e3,
        )

    def _rescan_zone(
        self, ctx, z, avail, valid, unsched, ready, name_rank, counter,
    ) -> None:
        """Exact per-zone prefilter state from the zone's resident order:
        first K fitting rows per class, the first fitting row beyond them
        (the excluded lexmin by construction — the order IS sorted by the
        key), and the per-dim maxima over the rest. Subset domains filter
        the zone order through their membership mask and refresh their
        zone totals exactly in the same pass."""
        zo = self.index.zone_order(z)
        self.stats[counter] += int(zo.shape[0])
        self.stats["planner_zone_rescans"] += 1
        if ctx.dom_mask is None:
            rows = zo[valid[zo]]
        else:
            rows = zo[ctx.dom_mask[zo] & valid[zo]]
            # Re-derive this zone's domain totals exactly: after a churn
            # drop the delta-maintained values are still exact, but the
            # recompute is O(zone) and kills any possibility of drift.
            ctx.zcnt[z] = rows.size
            ctx.zone_mem[z] = int(avail[rows, MEM_DIM].astype(np.int64).sum())
            ctx.zone_cpu[z] = int(avail[rows, CPU_DIM].astype(np.int64).sum())
        k = ctx.k
        if not rows.size:
            ctx.entries[z] = _ZoneEntry(
                np.empty(0, np.int32), np.empty(0, np.int32),
                False, False,
                (_I64_MAX,) * 3, (_I64_MAX,) * 3,
                np.full(avail.shape[1], _I64_MIN, np.int64),
                np.full(avail.shape[1], _I64_MIN, np.int64),
            )
            return
        av = avail[rows]
        fit_d = (av >= ctx.min_dr).all(axis=1)
        fit_e = (
            (av >= ctx.min_er).all(axis=1)
            & ~unsched[rows]
            & ready[rows]
        )
        sel_e = np.flatnonzero(fit_e)
        sel_d = np.flatnonzero(fit_d)
        kept_e = rows[sel_e[:k]].astype(np.int32)
        kept_d = rows[sel_d[:k]].astype(np.int32)
        # Excluded = fitting rows beyond the UNION of both classes' kept
        # prefixes (a row kept for the exec class is kept, full stop —
        # the legacy sweep's excl semantics, which the exactness oracle
        # pins): the first such row in order is the class's lexmin key.
        un = np.zeros(rows.shape[0], bool)
        un[sel_e[:k]] = True
        un[sel_d[:k]] = True

        def _class(sel):
            rel = sel[~un[sel]]
            if rel.size:
                first = rows[rel[0]]
                key = (
                    int(avail[first, MEM_DIM]),
                    int(avail[first, CPU_DIM]),
                    int(name_rank[first]),
                )
                mx = av[rel].max(axis=0).astype(np.int64)
                return True, key, mx
            return (
                False, (_I64_MAX,) * 3,
                np.full(avail.shape[1], _I64_MIN, np.int64),
            )

        has_e, key_e, max_e = _class(sel_e)
        has_d, key_d, max_d = _class(sel_d)

        def _last_key(sel):
            if sel.size < k:
                return None  # every fitting row kept: new rows belong in
            last = rows[sel[k - 1]]
            return (
                int(avail[last, MEM_DIM]),
                int(avail[last, CPU_DIM]),
                int(name_rank[last]),
            )

        ctx.entries[z] = _ZoneEntry(
            kept_e, kept_d, has_e, has_d, key_e, key_d, max_e, max_d,
            last_key_e=_last_key(sel_e), last_key_d=_last_key(sel_d),
        )

    def index_stats(self) -> dict:
        return {
            "index": self.index.stats(),
            "aggregates": self.agg.stats(),
            "cached_zones": len(self._full.entries),
            "cached_domains": len(self._dom_ctxs),
        }


def certify_window(
    plan: PrunePlan,
    *,
    strategy: str,
    requests,  # the window's WindowRequests (row counts per segment)
    drivers: np.ndarray,  # [B] int64 GLOBAL node indices (-1 = none)
    admitted: np.ndarray,  # [B] bool
    packed: np.ndarray,  # [B] bool
    execs: np.ndarray,  # [B, Emax] int64 GLOBAL indices
    drv64: np.ndarray,  # [B, 3] int64 per-row driver request
    exc64: np.ndarray,  # [B, 3] int64 per-row executor request
    base_kept: np.ndarray,  # [k_real, 3] int64 — EXACT dispatch base on the
    #                     kept rows (host view minus in-flight priors'
    #                     placements); OWNED by the certificate (mutated)
    host,  # host ClusterTensors view at dispatch
    prior_rows: np.ndarray,  # rows any in-flight prior placed on (global)
    prior_deltas: np.ndarray,  # [len(prior_rows), 3] int64 — the priors'
    #                     summed placements on those rows
) -> tuple[bool, str | None]:
    """Replay the window's availability thread and certify that the pruned
    solve's decisions equal the full solve's. Returns (ok, reason) —
    reason names the first failed test (telemetry label).

    O(K + rows) since ISSUE 12: every input is either per-kept-row or
    per-zone — the [N]-shaped lut/base/kept-mask of the original
    implementation is gone (the caller gathers `base_kept` on the kept
    rows; membership tests bisect the sorted keep)."""
    keep = plan.keep[: plan.k_real]  # sorted ascending

    # The device offsets assumed excluded rows kept their host-view
    # availability; a prior window's placement on an excluded row breaks
    # that (the plan was built before the prior's placements were known).
    # Rows outside the window domain are transparent to every choice
    # (masked from eligibility and zone sums alike), so only domain rows
    # are tested.
    in_dom = plan.dom_mask[prior_rows]
    prior_rows = prior_rows[in_dom]
    prior_deltas = prior_deltas[in_dom]
    if prior_rows.size:
        pp = np.clip(
            np.searchsorted(keep, prior_rows), 0, max(keep.size - 1, 0)
        )
        if keep.size == 0 or not bool(
            (keep[pp] == prior_rows).all()
        ):
            return False, "prior-placed-excluded"

    zone_id = np.asarray(host.zone_id)
    name_rank = np.asarray(host.name_rank)

    def to_local(g: np.ndarray) -> np.ndarray:
        """Global rows -> kept-local indices, -1 for non-kept."""
        p = np.searchsorted(keep, g)
        pc = np.clip(p, 0, keep.size - 1)
        return np.where(
            (g >= 0) & (keep[pc] == g), pc, -1
        ).astype(np.int64)

    # Hoisted once for the whole window: the per-row loop below only
    # indexes into these (the old [N] lut without the [N] allocation).
    drivers_local = to_local(drivers)
    execs_local = to_local(execs)

    k_zone = zone_id[keep]
    k_name = name_rank[keep].astype(np.int64)
    zs_mem = plan.zone_mem.copy()
    zs_cpu = plan.zone_cpu.copy()
    # Priors placed only on kept rows (verified above): fold their
    # placements out of the dispatch sums to reach the true base sums.
    # base == host view - priors, and plan sums were over the host view.
    if prior_rows.size:
        np.add.at(
            zs_mem, zone_id[prior_rows], -prior_deltas[:, MEM_DIM]
        )
        np.add.at(
            zs_cpu, zone_id[prior_rows], -prior_deltas[:, CPU_DIM]
        )

    # Per-row conservative excluded-fit tables, vectorized across the batch.
    fit_e_zb = (
        (plan.e_max_exec[None, :, :] >= exc64[:, None, :]).all(axis=2)
        & (plan.e_cnt_exec > 0)[None, :]
    )  # [B, Zb]
    fit_d_zb = (
        (plan.e_max_drv[None, :, :] >= drv64[:, None, :]).all(axis=2)
        & (plan.e_cnt_drv > 0)[None, :]
    )

    az = zone_ranks_host(zs_mem, zs_cpu, plan.present)
    az_dirty = False
    row = 0
    for req_i, req in enumerate(requests):
        nrows = len(req.rows)
        if az_dirty:
            az = zone_ranks_host(zs_mem, zs_cpu, plan.present)
            az_dirty = False
        # Segment-start keys: the kernel computes priority orders ONCE per
        # segment from the segment-start availability and reuses them while
        # only availability mutates (resource.go:299 semantics) — so every
        # key comparison below uses these, while fit/capacity tests use the
        # current in-segment availability.
        k_az = az[k_zone].astype(np.int64)
        k_mem = base_kept[:, MEM_DIM].copy()
        k_cpu = base_kept[:, CPU_DIM].copy()
        cand_k = plan.cand_kept[req_i][: plan.k_real]
        seg_kept = None  # lazy copy — only hypothetical commits mutate it
        for j in range(nrows):
            r = row + j
            cur = base_kept if seg_kept is None else seg_kept
            dr = drv64[r]
            er = exc64[r]
            any_e = bool(fit_e_zb[r].any())
            any_d = bool(fit_d_zb[r].any())
            if not packed[r]:
                # Denial: could an excluded row have cured it? Excluded
                # rows' availability is static during the window, so the
                # per-zone maxima are a sound (conservative) upper bound.
                if any_e or any_d:
                    return False, "denial-curable"
            elif admitted[r]:
                # Only admitted rows subtract availability, so only their
                # CHOICES must be pinned; a packed-but-blocked row's flags
                # are already implied identical by the preceding checks.
                if strategy == "minimal-fragmentation" and any_e:
                    # Consumption order is capacity DESC — any excluded
                    # capacity can reorder it regardless of priority rank.
                    return False, "minfrag-excluded-capacity"
                d = int(drivers[r])
                dl = int(drivers_local[r])
                sel = execs[r] >= 0
                ev = execs[r][sel]
                el = execs_local[r][sel]
                if d < 0 or dl < 0 or (ev.size and (el < 0).any()):
                    return False, "non-kept-choice"  # cannot happen; belt+braces
                key_d = (k_az[dl], k_mem[dl], k_cpu[dl], k_name[dl])
                # (a) Excluded driver candidate with a better key that fits.
                zsel = fit_d_zb[r]
                if zsel.any():
                    better = _lex_lt(
                        az[zsel].astype(np.int64),
                        plan.e_key_drv[zsel, 0],
                        plan.e_key_drv[zsel, 1],
                        plan.e_key_drv[zsel, 2],
                        *key_d,
                    )
                    if better.any():
                        return False, "driver-excluded-better"
                # (c) Feasibility flip: the pruned solve rejected every
                # better-ranked kept fitting candidate for capacity; with
                # excluded capacity in play the full solve might not have.
                if any_e:
                    fits_kept = (cur >= dr[None, :]).all(axis=1) & cand_k
                    if fits_kept.any():
                        better_kept = fits_kept & _lex_lt(
                            k_az, k_mem, k_cpu, k_name, *key_d
                        )
                        if better_kept.any():
                            return False, "driver-feasibility-flip"
                if ev.size:
                    # (b) Worst chosen executor row vs best excluded
                    # executor-capable row, by segment-start keys.
                    cu = np.unique(el)
                    worst = cu[
                        np.lexsort(
                            (k_name[cu], k_cpu[cu], k_mem[cu], k_az[cu])
                        )[-1]
                    ]
                    key_w = (
                        k_az[worst], k_mem[worst], k_cpu[worst], k_name[worst]
                    )
                    zsel = fit_e_zb[r]
                    if zsel.any():
                        better = _lex_lt(
                            az[zsel].astype(np.int64),
                            plan.e_key_exec[zsel, 0],
                            plan.e_key_exec[zsel, 1],
                            plan.e_key_exec[zsel, 2],
                            *key_w,
                        )
                        if better.any():
                            return False, "executor-excluded-better"
                    # (d) distribute-evenly revisits nodes round-robin: a
                    # second round would have visited excluded open rows
                    # before re-filling kept ones.
                    if (
                        strategy == "distribute-evenly"
                        and any_e
                        and ev.size > len(cu)
                    ):
                        return False, "distribute-multi-round"
                # Apply the row's placements to the thread.
                is_commit = j == nrows - 1
                if is_commit:
                    target = base_kept
                    if dl >= 0:
                        np.add.at(zs_mem, [k_zone[dl]], -int(dr[MEM_DIM]))
                        np.add.at(zs_cpu, [k_zone[dl]], -int(dr[CPU_DIM]))
                    if ev.size:
                        np.add.at(
                            zs_mem, k_zone[el], -int(er[MEM_DIM])
                        )
                        np.add.at(
                            zs_cpu, k_zone[el], -int(er[CPU_DIM])
                        )
                    az_dirty = True
                else:
                    if seg_kept is None:
                        seg_kept = base_kept.copy()
                    target = seg_kept
                target[dl] -= dr
                np.subtract.at(target, el, er[None, :])
        row += nrows
    return True, None
