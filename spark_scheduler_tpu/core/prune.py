"""Sound top-K candidate pruning for the window solve (the two-tier solve).

At 100k nodes the window kernel scans every row per scan step even though a
32-driver window can only ever touch a few hundred of them. The two-tier
solve makes the device program O(K):

  Tier 1 (host prefilter, this module): rank the window domain's nodes by
  the solver's own placement key — the priority order the kernels sort by,
  (zone rank, available mem asc, cpu asc, name rank) — riding the
  feature-rank index's resident order (core/feature_store.RankIndex), and
  gather the top-K candidate rows per zone, K sized from the window's
  aggregate demand x `solver.prune-slack`. The device then solves a [K,3]
  gathered sub-cluster with one small h2d instead of shipping [B,N] masks.

  Tier 2 (the certificate, also this module): soundness is ENFORCED, not
  assumed. After the pruned solve, `certify_window` replays the window's
  availability thread host-side and verifies that no pruned-away row could
  have altered any decision:

    - zone ranks are byte-exact by construction (the excluded rows' per-zone
      availability sums ship into the kernel as constant offsets,
      ops/sorting.zone_ranks zone_base);
    - a DENIAL is certified only if no excluded row could have cured it
      (capacity-bound test over the excluded rows' per-zone availability
      maxima, for both the driver fit and the executor capacity);
    - an ADMISSION is certified only if (a) no excluded driver candidate
      with a better priority key could fit the driver, (b) no excluded
      executor-capable row ranks before the worst chosen executor row,
      (c) excluded capacity could not have flipped the feasibility of a
      better-ranked kept driver candidate the pruned solve rejected, and
      (d) strategy-specific order hazards are absent (minimal-fragmentation
      consumes by capacity DESC, so any excluded capacity escalates;
      distribute-evenly escalates on multi-round fills).

  A failed certificate ESCALATES the window: the solver re-solves it from
  the exact host reconstruction via the greedy oracle (core/fallback.py —
  slot-for-slot the kernels' semantics), so decisions stay byte-identical
  to the unpruned path by construction, and the escalation is counted in
  `foundry.spark.scheduler.solver.prune.*`.

Every test here is CONSERVATIVE (it may escalate a window the full solve
would have decided identically, never the reverse): per-dim maxima over
excluded rows overestimate fit, candidate masks are ignored for excluded
driver checks, and any uncertainty (a prior window's placement landing on
an excluded row, a non-kept index in the blob) escalates outright.

Gating (checked by the solver before planning): plain fills only (the
single-AZ wrappers score zones by subset-dependent efficiencies), no
configured label priorities (the keys above assume the label rank is
uniformly INT32_INF), and one shared domain per window (the pooled
partition path prunes per-partition instead, where each partition's domain
is uniform by construction).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from spark_scheduler_tpu.models.resources import CPU_DIM, MEM_DIM

PLAIN_FILLS = frozenset(
    {"tightly-pack", "distribute-evenly", "minimal-fragmentation"}
)

_I64_MAX = np.iinfo(np.int64).max


def _bucket(n: int, minimum: int) -> int:
    out = minimum
    while out < n:
        out *= 2
    return out


def _zone_sum(zones: np.ndarray, vals: np.ndarray, zb: int) -> np.ndarray:
    """Exact per-zone int64 sums. bincount accumulates in float64 —
    exact while |sum| < 2^53, guaranteed for < 2^22 int32 rows (2^22 x
    2^31/2 = 2^52); larger row sets take the exact-but-slow np.add.at."""
    if vals.size >= (1 << 22):
        out = np.zeros(zb, np.int64)
        np.add.at(out, zones, vals.astype(np.int64))
        return out
    return np.bincount(
        zones, weights=vals, minlength=zb
    ).astype(np.int64)


def zone_ranks_host(
    mem_sum: np.ndarray,  # [Z] int64 — per-zone available-memory sums
    cpu_sum: np.ndarray,  # [Z] int64
    present: np.ndarray,  # [Z] bool — zone has a (domain & valid) node
) -> np.ndarray:  # [Z] int32 — rank of each zone (0 = highest priority)
    """Host replica of ops/sorting.zone_ranks: ascending (mem, cpu), absent
    zones last, zone-id tiebreak. The kernel's chunked int32 aggregation is
    an exact int64 sum in normal form, so comparing int64 sums here yields
    the identical order — the certificate depends on that equality."""
    z = mem_sum.shape[0]
    absent = np.where(present, 0, 1)
    order = np.lexsort((np.arange(z), cpu_sum, mem_sum, absent))
    ranks = np.empty(z, np.int32)
    ranks[order] = np.arange(z, dtype=np.int32)
    return ranks


def split_zone_sums(sums: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 per-zone sums -> (hi, lo) int32 limbs for the device offset
    (hi = S >> 24 arithmetic, lo = S & 0xFFFFFF; exact for |S| < 2^55)."""
    return (
        (sums >> 24).astype(np.int32),
        (sums & 0xFFFFFF).astype(np.int32),
    )


def _lex_lt(a0, a1, a2, a3, b0, b1, b2, b3):
    """Vectorized (a0,a1,a2,a3) < (b0,b1,b2,b3) — the priority-key compare
    (az rank, mem, cpu, name rank), lower = higher priority."""
    return (a0 < b0) | (
        (a0 == b0)
        & (
            (a1 < b1)
            | (
                (a1 == b1)
                & ((a2 < b2) | ((a2 == b2) & (a3 < b3)))
            )
        )
    )


@dataclasses.dataclass
class PrunePlan:
    """One window's candidate-pruning decision: the kept row set, the
    device zone-sum offsets, and the excluded-row summaries the
    certificate tests against. All arrays are host numpy."""

    keep: np.ndarray  # [Kp] int32 — kept global rows, real first, padded
    k_real: int  # number of real kept rows (padding repeats keep[0])
    kept_mask: np.ndarray  # [N] bool
    dom_mask: np.ndarray  # [N] bool — window domain & valid
    num_zones: int  # the solver's zone bucket Zb
    # Device offsets: excluded-row zone sums as int32 limbs + present.
    zone_base: tuple  # (mem_hi, mem_lo, cpu_hi, cpu_lo, present) [Zb] each
    # Dispatch-time zone sums over the WHOLE domain (kept + excluded) —
    # the certificate threads these (minus committed placements) to
    # replicate the kernel's per-segment zone ranks.
    zone_mem: np.ndarray  # [Zb] int64
    zone_cpu: np.ndarray  # [Zb] int64
    present: np.ndarray  # [Zb] bool
    # Excluded-row summaries, per zone, over rows RELEVANT to this window
    # (rows fitting the window's per-dim minimum demand; rows that fit no
    # request are provably transparent — zero capacity, no driver fit).
    e_cnt_exec: np.ndarray  # [Zb] int64 — relevant excluded exec-eligible
    e_max_exec: np.ndarray  # [Zb,3] int64 — per-dim avail max (conservative fit)
    e_key_exec: np.ndarray  # [Zb,3] int64 — lexmin (mem,cpu,name), I64_MAX pad
    e_cnt_drv: np.ndarray  # [Zb] int64
    e_max_drv: np.ndarray  # [Zb,3] int64
    e_key_drv: np.ndarray  # [Zb,3] int64
    # Per-request driver candidate masks gathered onto the kept rows.
    cand_kept: list  # [B_req] of [Kp] bool
    dom_rows: int  # |domain| (stats)


def plan_window_prune(
    host,
    *,
    order: np.ndarray,  # RankIndex order: all rows sorted by (mem,cpu,name)
    dom_mask: np.ndarray,  # [N] bool — shared window domain, already & valid
    cand_per_req: list,  # per-request [N] bool driver candidate masks
    drv_arr: np.ndarray,  # [B,3] i32 — per flat row
    exc_arr: np.ndarray,  # [B,3] i32
    counts: np.ndarray,  # [B] i32
    num_zones: int,
    top_k: int,
    slack: float,
) -> PrunePlan | None:
    """Build the window's pruning plan, or None when pruning cannot help
    (the kept set would cover most of the domain anyway)."""
    avail = np.asarray(host.available)
    zone_id = np.asarray(host.zone_id)
    n = avail.shape[0]

    # Per-dim minimum demand over every flat row (hypotheticals included):
    # a row that cannot fit this vector cannot host any driver/executor of
    # the window, so it is provably transparent to every choice the kernel
    # makes (zero capacity for every request, driver fit false) — only its
    # zone-sum contribution matters, and that ships as the device offset.
    min_dr = drv_arr.min(axis=0)
    min_er = exc_arr.min(axis=0)

    exec_elig = (
        dom_mask
        & ~np.asarray(host.unschedulable, bool)
        & np.asarray(host.ready, bool)
    )
    fit_e = (avail >= min_er[None, :]).all(axis=1) & exec_elig
    fit_d = (avail >= min_dr[None, :]).all(axis=1) & dom_mask

    b = drv_arr.shape[0]
    demand = int(counts.sum()) + b
    k_per_zone = max(int(top_k), int(np.ceil(demand * slack)))

    # Top-K PER ZONE of the priority order, separately for executor-capable
    # and driver-capable rows: a per-zone prefix stays a prefix under any
    # zone-rank permutation, so mid-window zone-rank drift cannot promote
    # an excluded row past a kept one within its zone.
    fo = order[fit_e[order]]
    do = order[fit_d[order]]
    # Per-zone domain counts via bincount (zone ids are < num_zones by
    # construction): np.unique sorts N values — a measured per-window
    # host cost at the million-node tier.
    zb = num_zones
    dom_zcnt = (
        np.bincount(zone_id[dom_mask], minlength=zb)
        if dom_mask.any()
        else np.zeros(zb, np.int64)
    )
    zids = np.flatnonzero(dom_zcnt)
    sel: list[np.ndarray] = []
    for z in zids:
        sel.append(fo[zone_id[fo] == z][:k_per_zone])
        sel.append(do[zone_id[do] == z][:k_per_zone])
    kept_mask = np.zeros(n, dtype=bool)
    if sel:
        kept_mask[np.concatenate(sel)] = True
    keep = np.flatnonzero(kept_mask).astype(np.int32)
    k_real = len(keep)
    dom_rows = int(dom_mask.sum())
    if k_real == 0 or k_real >= 0.7 * dom_rows:
        return None  # pruning buys nothing on this window

    excl = dom_mask & ~kept_mask
    e_rows = np.flatnonzero(excl)
    e_zone = zone_id[e_rows]

    # Device zone-sum offsets: ALL excluded domain rows (relevant or not).
    # bincount-with-weights accumulates in float64 — exact for |sum| <
    # 2^53, i.e. any cluster under ~4M int32 rows (guarded); np.add.at is
    # an order of magnitude slower at 1M rows.
    s_mem = _zone_sum(e_zone, avail[e_rows, MEM_DIM], zb)
    s_cpu = _zone_sum(e_zone, avail[e_rows, CPU_DIM], zb)
    present = dom_zcnt > 0

    # Whole-domain dispatch sums = kept sums + excluded sums.
    zone_mem = s_mem.copy()
    zone_cpu = s_cpu.copy()
    kept_avail = avail[keep].astype(np.int64)
    kept_zone = zone_id[keep]
    np.add.at(zone_mem, kept_zone, kept_avail[:, MEM_DIM])
    np.add.at(zone_cpu, kept_zone, kept_avail[:, CPU_DIM])

    name_rank = np.asarray(host.name_rank).astype(np.int64)

    def _summaries(rel_mask: np.ndarray):
        rows = np.flatnonzero(rel_mask & excl)
        rz = zone_id[rows]
        cnt = np.bincount(rz, minlength=zb).astype(np.int64)
        mx = np.full((zb, avail.shape[1]), np.iinfo(np.int64).min, np.int64)
        # Per-zone maxima: one vectorized pass per present zone (zones
        # are few) instead of np.maximum.at's per-element inner loop.
        av = avail[rows]
        for z in np.flatnonzero(cnt):
            mx[z] = av[rz == z].max(axis=0)
        # The priority order IS sorted by (mem, cpu, name): the first
        # relevant excluded row of each zone in order is that zone's lexmin
        # key — no per-window sort. First-occurrence per zone via argmax
        # on the present zones (np.unique sorts N values — measured at
        # the 1M tier); zones are few.
        key = np.full((zb, 3), _I64_MAX, np.int64)
        ro = order[(rel_mask & excl)[order]]
        rzo = zone_id[ro]
        for z in np.flatnonzero(cnt):
            fr = ro[int(np.argmax(rzo == z))]
            key[z, 0] = avail[fr, MEM_DIM]
            key[z, 1] = avail[fr, CPU_DIM]
            key[z, 2] = name_rank[fr]
        return cnt, mx, key

    e_cnt_exec, e_max_exec, e_key_exec = _summaries(fit_e)
    e_cnt_drv, e_max_drv, e_key_drv = _summaries(fit_d)

    kp = _bucket(k_real, 64)
    keep_padded = np.full(kp, keep[0], np.int32)
    keep_padded[:k_real] = keep

    mem_hi, mem_lo = split_zone_sums(s_mem)
    cpu_hi, cpu_lo = split_zone_sums(s_cpu)
    return PrunePlan(
        keep=keep_padded,
        k_real=k_real,
        kept_mask=kept_mask,
        dom_mask=dom_mask,
        num_zones=zb,
        zone_base=(mem_hi, mem_lo, cpu_hi, cpu_lo, present),
        zone_mem=zone_mem,
        zone_cpu=zone_cpu,
        present=present,
        e_cnt_exec=e_cnt_exec,
        e_max_exec=e_max_exec,
        e_key_exec=e_key_exec,
        e_cnt_drv=e_cnt_drv,
        e_max_drv=e_max_drv,
        e_key_drv=e_key_drv,
        cand_kept=[np.asarray(c)[keep_padded] for c in cand_per_req],
        dom_rows=dom_rows,
    )


def certify_window(
    plan: PrunePlan,
    *,
    strategy: str,
    requests,  # the window's WindowRequests (row counts per segment)
    drivers: np.ndarray,  # [B] int64 GLOBAL node indices (-1 = none)
    admitted: np.ndarray,  # [B] bool
    packed: np.ndarray,  # [B] bool
    execs: np.ndarray,  # [B, Emax] int64 GLOBAL indices
    drv64: np.ndarray,  # [B, 3] int64 per-row driver request
    exc64: np.ndarray,  # [B, 3] int64 per-row executor request
    base: np.ndarray,  # [N, 3] int64 — EXACT dispatch base (host view minus
    #                     in-flight priors' placements); NOT mutated
    host,  # host ClusterTensors view at dispatch
    prior_rows: np.ndarray,  # rows any in-flight prior placed on (global)
) -> tuple[bool, str | None]:
    """Replay the window's availability thread and certify that the pruned
    solve's decisions equal the full solve's. Returns (ok, reason) —
    reason names the first failed test (telemetry label)."""
    # The device offsets assumed excluded rows kept their host-view
    # availability; a prior window's placement on an excluded row breaks
    # that (the plan was built before the prior's placements were known).
    # Rows outside the window domain are transparent to every choice
    # (masked from eligibility and zone sums alike), so only domain rows
    # are tested.
    prior_rows = prior_rows[plan.dom_mask[prior_rows]]
    if prior_rows.size and not plan.kept_mask[prior_rows].all():
        return False, "prior-placed-excluded"

    zone_id = np.asarray(host.zone_id)
    name_rank = np.asarray(host.name_rank).astype(np.int64)
    keep = plan.keep[: plan.k_real]
    lut = np.full(zone_id.shape[0], -1, np.int32)
    lut[keep] = np.arange(plan.k_real, dtype=np.int32)

    k_zone = zone_id[keep]
    k_name = name_rank[keep]
    base_kept = base[keep].copy()  # threaded across segments (commits only)
    zs_mem = plan.zone_mem.copy()
    zs_cpu = plan.zone_cpu.copy()
    # Priors placed only on kept rows (verified above): fold their
    # placements out of the dispatch sums to reach the true base sums.
    # base == host view - priors, and plan sums were over the host view.
    if prior_rows.size:
        delta = np.asarray(host.available).astype(np.int64)[prior_rows] - base[prior_rows]
        np.add.at(zs_mem, zone_id[prior_rows], -delta[:, MEM_DIM])
        np.add.at(zs_cpu, zone_id[prior_rows], -delta[:, CPU_DIM])

    # Per-row conservative excluded-fit tables, vectorized across the batch.
    b = drv64.shape[0]
    fit_e_zb = (
        (plan.e_max_exec[None, :, :] >= exc64[:, None, :]).all(axis=2)
        & (plan.e_cnt_exec > 0)[None, :]
    )  # [B, Zb]
    fit_d_zb = (
        (plan.e_max_drv[None, :, :] >= drv64[:, None, :]).all(axis=2)
        & (plan.e_cnt_drv > 0)[None, :]
    )

    az = zone_ranks_host(zs_mem, zs_cpu, plan.present)
    az_dirty = False
    row = 0
    for req_i, req in enumerate(requests):
        nrows = len(req.rows)
        if az_dirty:
            az = zone_ranks_host(zs_mem, zs_cpu, plan.present)
            az_dirty = False
        # Segment-start keys: the kernel computes priority orders ONCE per
        # segment from the segment-start availability and reuses them while
        # only availability mutates (resource.go:299 semantics) — so every
        # key comparison below uses these, while fit/capacity tests use the
        # current in-segment availability.
        k_az = az[k_zone].astype(np.int64)
        k_mem = base_kept[:, MEM_DIM].copy()
        k_cpu = base_kept[:, CPU_DIM].copy()
        cand_k = plan.cand_kept[req_i][: plan.k_real]
        seg_kept = None  # lazy copy — only hypothetical commits mutate it
        for j in range(nrows):
            r = row + j
            cur = base_kept if seg_kept is None else seg_kept
            dr = drv64[r]
            er = exc64[r]
            any_e = bool(fit_e_zb[r].any())
            any_d = bool(fit_d_zb[r].any())
            if not packed[r]:
                # Denial: could an excluded row have cured it? Excluded
                # rows' availability is static during the window, so the
                # per-zone maxima are a sound (conservative) upper bound.
                if any_e or any_d:
                    return False, "denial-curable"
            elif admitted[r]:
                # Only admitted rows subtract availability, so only their
                # CHOICES must be pinned; a packed-but-blocked row's flags
                # are already implied identical by the preceding checks.
                if strategy == "minimal-fragmentation" and any_e:
                    # Consumption order is capacity DESC — any excluded
                    # capacity can reorder it regardless of priority rank.
                    return False, "minfrag-excluded-capacity"
                d = int(drivers[r])
                dl = lut[d] if d >= 0 else -1
                ev = execs[r][execs[r] >= 0]
                el = lut[ev] if ev.size else ev.astype(np.int32)
                if d < 0 or dl < 0 or (ev.size and (el < 0).any()):
                    return False, "non-kept-choice"  # cannot happen; belt+braces
                key_d = (k_az[dl], k_mem[dl], k_cpu[dl], k_name[dl])
                # (a) Excluded driver candidate with a better key that fits.
                zsel = fit_d_zb[r]
                if zsel.any():
                    better = _lex_lt(
                        az[zsel].astype(np.int64),
                        plan.e_key_drv[zsel, 0],
                        plan.e_key_drv[zsel, 1],
                        plan.e_key_drv[zsel, 2],
                        *key_d,
                    )
                    if better.any():
                        return False, "driver-excluded-better"
                # (c) Feasibility flip: the pruned solve rejected every
                # better-ranked kept fitting candidate for capacity; with
                # excluded capacity in play the full solve might not have.
                if any_e:
                    fits_kept = (cur >= dr[None, :]).all(axis=1) & cand_k
                    if fits_kept.any():
                        better_kept = fits_kept & _lex_lt(
                            k_az, k_mem, k_cpu, k_name, *key_d
                        )
                        if better_kept.any():
                            return False, "driver-feasibility-flip"
                if ev.size:
                    # (b) Worst chosen executor row vs best excluded
                    # executor-capable row, by segment-start keys.
                    cu = np.unique(el)
                    worst = cu[
                        np.lexsort(
                            (k_name[cu], k_cpu[cu], k_mem[cu], k_az[cu])
                        )[-1]
                    ]
                    key_w = (
                        k_az[worst], k_mem[worst], k_cpu[worst], k_name[worst]
                    )
                    zsel = fit_e_zb[r]
                    if zsel.any():
                        better = _lex_lt(
                            az[zsel].astype(np.int64),
                            plan.e_key_exec[zsel, 0],
                            plan.e_key_exec[zsel, 1],
                            plan.e_key_exec[zsel, 2],
                            *key_w,
                        )
                        if better.any():
                            return False, "executor-excluded-better"
                    # (d) distribute-evenly revisits nodes round-robin: a
                    # second round would have visited excluded open rows
                    # before re-filling kept ones.
                    if (
                        strategy == "distribute-evenly"
                        and any_e
                        and ev.size > len(cu)
                    ):
                        return False, "distribute-multi-round"
                # Apply the row's placements to the thread.
                is_commit = j == nrows - 1
                if is_commit:
                    target = base_kept
                    if dl >= 0:
                        np.add.at(zs_mem, [k_zone[dl]], -int(dr[MEM_DIM]))
                        np.add.at(zs_cpu, [k_zone[dl]], -int(dr[CPU_DIM]))
                    if ev.size:
                        np.add.at(
                            zs_mem, k_zone[el], -int(er[MEM_DIM])
                        )
                        np.add.at(
                            zs_cpu, k_zone[el], -int(er[CPU_DIM])
                        )
                    az_dirty = True
                else:
                    if seg_kept is None:
                        seg_kept = base_kept.copy()
                    target = seg_kept
                target[dl] -= dr
                np.subtract.at(target, el, er[None, :])
        row += nrows
    return True, None
