"""DirtyRowFeed — the shared dirty-row drain protocol of the resident
masters (ISSUE 13).

Both delta-maintained dense mirrors (core/usage_tracker.
ReservedUsageTracker, core/overhead.OverheadComputer's dense feed) name
the registry rows they change so the HostFeatureStore can patch its
resident masters O(changed) instead of copying the whole [cap, 3] array
per refresh. The protocol is identical in both and correctness-coupled
— the store's patch is sound only if every mutation is either noted or
surfaced as UNKNOWN — so it lives here once:

  note(idx)       record one changed row; past the cap the backlog is
                  dropped and the feed goes UNKNOWN (the single consumer
                  stopped draining — a full copy resyncs it);
  mark_unknown()  a from-scratch rebuild/attach cannot name its rows;
  drain(dense)    single-consumer drain: (rows, vals) of the changes
                  since the last drain — vals copied from `dense` so the
                  values are consistent with the owner's version counter
                  — or (None, None) when unknown. The OWNER'S lock must
                  be held (the same lock guarding `dense` mutations).
"""

from __future__ import annotations

import numpy as np


class DirtyRowFeed:
    __slots__ = ("_rows", "_unknown", "_cap")

    def __init__(self, cap: int = 1 << 20):
        self._rows: list[int] = []
        self._unknown = True
        self._cap = cap

    def note(self, idx: int) -> None:
        if self._unknown:
            return
        if len(self._rows) >= self._cap:
            self._rows.clear()
            self._unknown = True
        else:
            self._rows.append(idx)

    def mark_unknown(self) -> None:
        self._rows.clear()
        self._unknown = True

    def drain(self, dense: np.ndarray):
        """(rows, vals) changed since the last drain, or (None, None)
        when the feed cannot name them. Caller holds the owner's lock."""
        if self._unknown:
            self._rows.clear()
            self._unknown = False
            return None, None
        if not self._rows:
            return (
                np.empty(0, np.int64),
                np.empty((0, dense.shape[1]), np.int64),
            )
        rows = np.unique(np.asarray(self._rows, dtype=np.int64))
        self._rows.clear()
        return rows, dense[rows].copy()
