"""ResourceReservationManager — hard + soft reservation lifecycle.

Rebuilds internal/extender/resourcereservations.go:42-484: reservation
creation for admitted gangs, the executor binding ladder (already-bound /
unbound / rescheduled / soft), unbound-reservation discovery (slots whose
executor is missing, dead, or moved), free soft spots, reserved-usage
aggregation, and dynamic-allocation compaction (soft reservations migrate
into freed hard slots when executors die).
"""

from __future__ import annotations

import threading
from typing import Optional

from spark_scheduler_tpu.models.kube import Pod
from spark_scheduler_tpu.models.reservations import (
    Reservation,
    ResourceReservation,
    new_resource_reservation,
)
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.core.soft_reservations import SoftReservationStore
from spark_scheduler_tpu.core.sparkpods import (
    SPARK_APP_ID_LABEL,
    SparkApplicationResources,
    SparkPodLister,
    is_spark_scheduler_executor_pod,
    spark_resources,
)


class ReservationError(Exception):
    """Maps to failure-internal outcomes."""


class ResourceReservationManager:
    def __init__(
        self,
        backend,
        rr_cache,
        soft_reservation_store: SoftReservationStore,
        pod_lister: SparkPodLister,
    ):
        self._backend = backend
        self.rr_cache = rr_cache
        self.soft_store = soft_reservation_store
        self.pod_lister = pod_lister
        self._mutex = threading.RLock()
        self._compaction_lock = threading.Lock()
        self._compaction_apps: dict[str, str] = {}  # appID -> namespace
        # Optional delta-maintained usage aggregate (core/usage_tracker.py);
        # attached by the DI wiring once the solver's NodeRegistry exists.
        self.usage_tracker = None
        backend.subscribe("pods", on_delete=self._on_executor_pod_deletion)

    def attach_usage_tracker(self, tracker) -> None:
        self.usage_tracker = tracker

    # -- queries ------------------------------------------------------------

    def get_resource_reservation(
        self, app_id: str, namespace: str
    ) -> Optional[ResourceReservation]:
        return self.rr_cache.get(namespace, app_id)

    def pod_has_reservation(self, pod: Pod) -> bool:
        """Hard (Status.Pods) or soft reservation membership
        (resourcereservations.go:88-104)."""
        app_id = pod.labels.get(SPARK_APP_ID_LABEL)
        if app_id is None:
            return False
        rr = self.get_resource_reservation(app_id, pod.namespace)
        if rr is not None and pod.name in rr.status.pods.values():
            return True
        return is_spark_scheduler_executor_pod(
            pod
        ) and self.soft_store.executor_has_soft_reservation(pod)

    def get_reserved_resources(self) -> dict[str, Resources]:
        """Per-node hard+soft reservation usage (resourcereservations.go:228-233).
        With a tracker attached this is the O(nonzero) incremental view;
        otherwise the reference's full walk."""
        if self.usage_tracker is not None:
            return self.usage_tracker.as_map()
        usage: dict[str, Resources] = {}
        for rr in self.rr_cache.list():
            for res in rr.spec.reservations.values():
                usage.setdefault(res.node, Resources.zero()).add(res.resources)
        for node, res in self.soft_store.used_soft_reservation_resources().items():
            usage.setdefault(node, Resources.zero()).add(res)
        return usage

    def reserved_usage(self):
        """Hot-path usage view: the tracker's dense int64 array when attached
        (O(1) per request), else the map (O(apps x slots) fallback). Both
        shapes are accepted by PlacementSolver.build_tensors."""
        if self.usage_tracker is not None:
            return self.usage_tracker.array()
        return self.get_reserved_resources()

    # -- gang admission -----------------------------------------------------

    def create_reservations(
        self,
        driver: Pod,
        app_resources: SparkApplicationResources,
        driver_node: str,
        executor_nodes: list[str],
    ) -> ResourceReservation:
        app_id = driver.labels.get(SPARK_APP_ID_LABEL, driver.name)
        rr = self.get_resource_reservation(app_id, driver.namespace)
        if rr is None:
            rr = new_resource_reservation(
                driver_node,
                executor_nodes,
                driver,
                app_resources.driver_resources,
                app_resources.executor_resources,
            )
            if not self.rr_cache.create(rr):
                raise ReservationError(f"failed to create resource reservation {rr.name}")
        if app_resources.max_executor_count > app_resources.min_executor_count:
            # only dynamic-allocation apps get a soft-reservation shell
            self.soft_store.create_soft_reservation_if_not_exists(app_id)
        return rr

    def create_reservations_batch(
        self, entries: list[tuple]
    ) -> list[Optional[ReservationError]]:
        """A serving window's reservation commits COALESCED: every entry
        still goes through `create_reservations` (so per-entry semantics —
        idempotency, soft shells, failure raising, test fault injection —
        are exactly the serial path's), but under ONE deferred-notification
        context: the usage tracker and overhead store receive a single
        batched delta application per window instead of a listener fan-out
        per reservation.

        `entries` is [(driver, app_resources, driver_node, executor_nodes)]
        in window order. Returns one slot per entry: None on success, else
        the ReservationError that entry raised — the caller fails just that
        request, exactly as the serial path did."""
        out: list[Optional[ReservationError]] = []
        with self.rr_cache.deferred_notifications():
            for driver, app_resources, driver_node, executor_nodes in entries:
                try:
                    self.create_reservations(
                        driver, app_resources, driver_node, executor_nodes
                    )
                    out.append(None)
                except ReservationError as exc:
                    out.append(exc)
        return out

    # -- executor binding ladder -------------------------------------------

    def find_already_bound_reservation_node(
        self, executor: Pod
    ) -> tuple[Optional[str], bool]:
        """Idempotent retry path (resourcereservations.go:133-149)."""
        rr = self.get_resource_reservation(
            executor.labels.get(SPARK_APP_ID_LABEL, ""), executor.namespace
        )
        if rr is None:
            raise ReservationError("failed to get resource reservations")
        for name, res in rr.spec.reservations.items():
            if rr.status.pods.get(name) == executor.name:
                return res.node, True
        sr = self.soft_store.get_executor_soft_reservation(executor)
        if sr is not None:
            return sr.node, True
        return None, False

    def get_remaining_allowed_executor_count(
        self, app_id: str, namespace: str, *, unbound_count: int | None = None
    ) -> int:
        """`unbound_count` lets a caller that just scanned the unbound slots
        (reserve_executor_on_unbound) skip re-deriving them."""
        if unbound_count is None:
            unbound_count = len(self._get_unbound_reservations(app_id, namespace))
        return unbound_count + self._get_free_soft_reservation_spots(app_id, namespace)

    def reserve_executor_on_unbound(
        self, executor: Pod, node_names: list[str]
    ) -> tuple[Optional[str], int]:
        """The find-unbound + bind rungs fused into ONE unbound scan under
        the mutex (a split find -> re-scan -> bind pair would derive the
        active pod set twice per executor — the serving ladder's hot spot).
        Binds to the first OFFERED candidate (node_names order) holding an
        unbound slot, matching the split path's choice exactly
        (resource.go:389-400). Returns (bound node | None, unbound slot
        count); the count feeds get_remaining_allowed_executor_count."""
        with self._mutex:
            unbound = self._get_unbound_reservations(
                executor.labels.get(SPARK_APP_ID_LABEL, ""), executor.namespace
            )
            if unbound:
                nodes = set(unbound.values())
                chosen = next((n for n in node_names if n in nodes), None)
                if chosen is not None:
                    for res_name, res_node in unbound.items():
                        if res_node == chosen:
                            self._bind_executor_to_resource_reservation(
                                executor, res_name, chosen
                            )
                            return chosen, len(unbound)
            return None, len(unbound)

    def executor_ladder_batch(
        self, app_id: str, namespace: str, items: list[tuple[Pod, list[str]]]
    ) -> list[tuple[str, object]]:
        """Rungs 1-2 of the executor binding ladder for EVERY executor of
        one app in a serving window, in arrival order, under ONE mutex hold
        with one reservation fetch, one active-pod listing, and one cache
        write (the serial per-request ladder re-derived the active pod set
        and re-wrote the reservation once per executor — the serving path's
        host bottleneck at high executor arrival rates).

        `items` = [(executor_pod, offered_node_names)]. Returns one rung per
        executor, in order:
          ("already", node)      idempotent retry: bound (hard or soft) on an
                                 OFFERED node (resource.go:377-388)
          ("bound", node)        bound to an unbound slot on an offered node
                                 (resource.go:389-400)
          ("reschedule", had_unbound)
                                 a free spot exists and was pre-consumed from
                                 the working view; the caller solves the
                                 placement and applies the bind via
                                 reserve_for_executor_on_rescheduled_node
          ("dup-reschedule", None)
                                 duplicate submission of a pod already
                                 granted a reschedule in this batch — no
                                 second spot is consumed; the caller resolves
                                 it from the first occurrence's result (the
                                 serial path's rung 1 would return
                                 already-bound after the first bind applied)
          ("no-spots", None)     no unbound slots, no free soft spots

        Raises ReservationError when the app has no reservation or the
        batched cache write fails — the caller fails the app's whole batch
        failure-internal, as the solo rungs would.

        Documented deviation from strict arrival serialization: a
        reschedule's actual slot move (applied after the caller's grouped
        solve) picks from the then-committed unbound map, which can be a
        different — semantically equivalent — slot than a strict serial
        interleaving would have moved (any unbound slot satisfies the
        reservation; resourcereservations.go:202-225 itself picks
        arbitrarily)."""
        with self._mutex:
            rr = self.get_resource_reservation(app_id, namespace)
            if rr is None:
                raise ReservationError("failed to get resource reservations")
            active = self._get_active_pods(app_id, namespace)
            # Working views — binds made earlier in this batch must be
            # visible to later executors (duplicate submissions included).
            bound_by_pod: dict[str, str] = {}
            unbound: dict[str, str] = {}
            for res_name, res in rr.spec.reservations.items():
                pod_name = rr.status.pods.get(res_name)
                pod = active.get(pod_name) if pod_name is not None else None
                if (
                    pod_name is None
                    or pod is None
                    or (pod.node_name and pod.node_name != res.node)
                ):
                    unbound[res_name] = res.node
                if pod_name is not None:
                    bound_by_pod[pod_name] = res.node
            free_soft = self._get_free_soft_reservation_spots(app_id, namespace)
            binds: list[tuple[str, str, str]] = []  # (pod, slot, node)
            offered_sets: dict[int, frozenset] = {}
            resched_pods: set[str] = set()
            out: list[tuple[str, object]] = []
            for executor, node_names in items:
                offered = offered_sets.get(id(node_names))
                if offered is None:
                    offered = frozenset(node_names)
                    offered_sets[id(node_names)] = offered
                # Rung 1: already bound (hard slot or soft reservation).
                node = bound_by_pod.get(executor.name)
                if node is None:
                    sr = self.soft_store.get_executor_soft_reservation(executor)
                    if sr is not None:
                        node = sr.node
                if node is not None and node in offered:
                    out.append(("already", node))
                    continue
                # Bound but not offered falls through (resource.go:377-388).
                # Rung 2: first OFFERED candidate holding an unbound slot
                # (node_names order, matching the solo rung exactly).
                if unbound:
                    values = set(unbound.values())
                    chosen = next(
                        (n for n in node_names if n in values), None
                    )
                    if chosen is not None:
                        for res_name, res_node in unbound.items():
                            if res_node == chosen:
                                del unbound[res_name]
                                break
                        bound_by_pod[executor.name] = chosen
                        binds.append((executor.name, res_name, chosen))
                        out.append(("bound", chosen))
                        continue
                # Rung 3 classification: pre-consume a spot so later
                # executors of this window see the serialized budget. A
                # duplicate of a pod already granted a reschedule consumes
                # nothing (serially it would find itself already bound).
                if executor.name in resched_pods:
                    out.append(("dup-reschedule", None))
                    continue
                had_unbound = bool(unbound)
                if len(unbound) + free_soft > 0:
                    if unbound:
                        unbound.pop(next(iter(unbound)))
                    else:
                        free_soft -= 1
                    resched_pods.add(executor.name)
                    out.append(("reschedule", had_unbound))
                else:
                    out.append(("no-spots", None))
            if binds:
                updated = rr.copy()
                for pod_name, res_name, node in binds:
                    updated.spec.reservations[res_name].node = node
                    updated.status.pods[res_name] = pod_name
                if not self.rr_cache.update(updated):
                    raise ReservationError(
                        "failed to update resource reservation"
                    )
            return out

    def reserve_for_executor_on_rescheduled_node(
        self, executor: Pod, node: str
    ) -> None:
        """Bind to ANY unbound hard slot (moving it to `node`), else to a
        soft reservation (resourcereservations.go:202-225)."""
        with self._mutex:
            app_id = executor.labels.get(SPARK_APP_ID_LABEL, "")
            unbound = self._get_unbound_reservations(app_id, executor.namespace)
            if unbound:
                res_name = next(iter(unbound))
                self._bind_executor_to_resource_reservation(executor, res_name, node)
                return
            if self._get_free_soft_reservation_spots(app_id, executor.namespace) > 0:
                self._bind_executor_to_soft_reservation(executor, node)
                return
        raise ReservationError("failed to find free reservation for executor")

    # -- compaction ---------------------------------------------------------

    def compact_dynamic_allocation_applications(self) -> None:
        """Migrate soft reservations of live executors into freed hard slots
        (resourcereservations.go:238-268). Apps are queued by the executor
        pod-deletion handler and drained here, on the request path.

        One unbound-slot derivation and ONE reservation write per app: the
        per-pod form re-derived the active pod set and re-wrote the
        reservation once per soft executor — O(slots x pods) per
        compaction pass, a measured host cost at high dynamic-allocation
        churn. Slot choice per pod is unchanged (prefer a slot already on
        the pod's node, else the first unbound slot,
        resourcereservations.go:283-301); a consumed slot is not re-offered
        within the pass even when the bind leaves it node-mismatched —
        semantically equivalent, the same deviation contract as
        executor_ladder_batch (any unbound slot satisfies the reservation;
        the reference itself picks arbitrarily)."""
        with self._compaction_lock:
            drained, self._compaction_apps = self._compaction_apps, {}
        with self._mutex:
            for app_id, namespace in drained.items():
                sr, ok = self.soft_store.get_soft_reservation(app_id)
                if not ok:
                    continue
                pods = self._get_active_pods(app_id, namespace)
                live = [
                    pods[name] for name in sr.reservations if name in pods
                ]
                if not live:
                    continue
                self._compact_app(app_id, live, pods)

    def _compact_app(
        self, app_id: str, pods: list[Pod], active: dict[str, Pod]
    ) -> None:
        """`active` is the app's already-derived active-pod map — the
        caller pays that walk exactly once per compacted app."""
        if not pods:
            return
        namespace = pods[0].namespace
        rr = self.get_resource_reservation(app_id, namespace)
        if rr is None:
            return
        unbound = self._unbound_of(rr, active)
        if not unbound:
            return
        binds: list[tuple[Pod, str, str]] = []  # (pod, slot, node)
        for pod in pods:
            if not unbound:
                break
            res_name = next(
                (
                    name
                    for name, node in unbound.items()
                    if node == pod.node_name
                ),
                None,
            )
            if res_name is None:
                res_name = next(iter(unbound))
            binds.append((pod, res_name, unbound.pop(res_name)))
        if not binds:
            return
        updated = rr.copy()
        for pod, res_name, node in binds:
            updated.spec.reservations[res_name].node = node
            updated.status.pods[res_name] = pod.name
        if not self.rr_cache.update(updated):
            raise ReservationError("failed to update resource reservation")
        for pod, _res_name, _node in binds:
            self.soft_store.remove_executor_reservation(app_id, pod.name)

    # -- internals ----------------------------------------------------------

    def _bind_executor_to_resource_reservation(
        self, executor: Pod, reservation_name: str, node: str
    ) -> None:
        rr = self.get_resource_reservation(
            executor.labels.get(SPARK_APP_ID_LABEL, ""), executor.namespace
        )
        if rr is None:
            raise ReservationError(
                f"failed to get resource reservation {reservation_name}"
            )
        updated = rr.copy()
        res = updated.spec.reservations[reservation_name]
        res.node = node
        updated.status.pods[reservation_name] = executor.name
        if not self.rr_cache.update(updated):
            raise ReservationError(
                f"failed to update resource reservation {reservation_name}"
            )

    def _bind_executor_to_soft_reservation(self, executor: Pod, node: str) -> None:
        driver = self.pod_lister.get_driver_for_executor(executor)
        if driver is None:
            raise ReservationError("failed to get driver pod for executor")
        app_resources = spark_resources(driver)
        self.soft_store.add_reservation_for_pod(
            driver.labels.get(SPARK_APP_ID_LABEL, ""),
            executor.name,
            Reservation(node, app_resources.executor_resources.copy()),
        )

    @staticmethod
    def _unbound_of(rr: ResourceReservation, active: dict[str, Pod]) -> dict[str, str]:
        """Slots not bound to an active pod, bound to a dead pod, or bound to
        a pod that landed on a different node (resourcereservations.go:358-380),
        over an already-derived active-pod map."""
        unbound: dict[str, str] = {}
        for res_name, res in rr.spec.reservations.items():
            pod_name = rr.status.pods.get(res_name)
            pod = active.get(pod_name) if pod_name is not None else None
            if (
                pod_name is None
                or pod is None
                or (pod.node_name and pod.node_name != res.node)
            ):
                unbound[res_name] = res.node
        return unbound

    def _get_unbound_reservations(self, app_id: str, namespace: str) -> dict[str, str]:
        rr = self.get_resource_reservation(app_id, namespace)
        if rr is None:
            raise ReservationError("failed to get resource reservation")
        return self._unbound_of(rr, self._get_active_pods(app_id, namespace))

    def _get_free_soft_reservation_spots(self, app_id: str, namespace: str) -> int:
        sr, ok = self.soft_store.get_soft_reservation(app_id)
        if not ok:
            return 0
        used = len(sr.reservations)
        driver = self.pod_lister.get_driver_pod(app_id, namespace)
        if driver is None:
            return 0
        app_resources = spark_resources(driver)
        allowed = app_resources.max_executor_count - app_resources.min_executor_count
        return max(allowed - used, 0)

    def _get_active_pods(self, app_id: str, namespace: str) -> dict[str, Pod]:
        return {
            p.name: p
            for p in self.pod_lister.list_app_pods(app_id, namespace)
            if not p.is_terminated()
        }

    def _on_executor_pod_deletion(self, pod: Pod) -> None:
        if not is_spark_scheduler_executor_pod(pod):
            return
        _, has_app = self.soft_store.get_soft_reservation(
            pod.labels.get(SPARK_APP_ID_LABEL, "")
        )
        if has_app and not self.soft_store.executor_has_soft_reservation(pod):
            with self._compaction_lock:
                self._compaction_apps[pod.labels.get(SPARK_APP_ID_LABEL, "")] = (
                    pod.namespace
                )
