"""Overhead accounting: resource requests of pods outside our reservations.

Rebuilds internal/extender/overhead.go:32-209 — overhead(node) = requests of
pods on the node that have no hard or soft reservation; non-schedulable
overhead additionally counts only pods of OTHER schedulers.

Documented deviation: TERMINATED pods contribute nothing. The reference
keeps counting a terminated pod's requests until the pod object is deleted
(overhead.go:163-174 tracks by pod event, never checks the phase), but
kube-scheduler itself releases Succeeded/Failed pods' resources — counting
them both under-reports capacity and double-counts a dead executor whose
freed slot has been re-bound (reservation usage for the new holder + the
corpse's requests as overhead). The invariant soak caught exactly that
double-count (tests/test_invariant_soak.py).

The reference recomputes membership per node at query time (overhead.go:
120-168, an O(pods-on-node) walk with a cache lookup per pod). This rebuild
maintains the aggregates INCREMENTALLY, because at the 10k-node x 1k-app
target the per-request walk is the latency floor (SURVEY.md §7):

  total[node]     = sum of requests of pods bound to the node
  reserved[node]  = sum of requests of bound pods that HAVE a reservation
  overhead(node)  = total - reserved
  nonsched[node]  = sum of requests of unreserved pods of other schedulers

Membership of a pod changes only on: pod add/update/delete (backend watch),
its app's ResourceReservation changing (rr-cache mutation listener), or its
app's soft reservations changing (soft-store membership listener) — each
triggers an O(pods-of-one-app) recompute, never a full-cluster walk. The
from-scratch oracle (`compute_node_overhead_oracle`) stays for tests.
"""

from __future__ import annotations

import threading

import numpy as np

from spark_scheduler_tpu.models.kube import Pod
from spark_scheduler_tpu.models.resources import (
    NUM_DIMS,
    FrozenResources,
    Resources,
)
from spark_scheduler_tpu.core.dirty_feed import DirtyRowFeed
from spark_scheduler_tpu.core.sparkpods import SPARK_SCHEDULER_NAME
from spark_scheduler_tpu.store.cache import BatchableListener


class _PodState:
    __slots__ = ("node", "requests", "counted_overhead", "counted_nonsched")

    def __init__(self, node: str, requests: Resources):
        self.node = node
        self.requests = requests
        self.counted_overhead = False
        self.counted_nonsched = False


class OverheadComputer:
    def __init__(self, backend, reservation_manager):
        self._backend = backend
        self._rrm = reservation_manager
        self._lock = threading.RLock()
        self._pods: dict[tuple[str, str], _PodState] = {}  # (ns, name) -> state
        self._by_name: dict[str, set[tuple[str, str]]] = {}  # name -> keys
        self._overhead: dict[str, Resources] = {}
        self._nonsched: dict[str, Resources] = {}
        # Frozen per-node views handed out by the query methods, memoized
        # until that node's aggregate next changes — the old
        # copy-every-Resources-under-the-lock walk was a measured per-call
        # cost at 10k nodes, and no caller ever mutated the copies.
        self._frozen: dict[int, dict[str, FrozenResources]] = {
            id(self._overhead): {},
            id(self._nonsched): {},
        }
        # Optional dense [cap, 3] int64 mirror of the schedulable-overhead
        # aggregate over a NodeRegistry's index space (attach_registry) —
        # the HostFeatureStore's zero-walk feed. `overhead_version` bumps on
        # every applied overhead delta so snapshots can key on it.
        self._registry = None
        self._dense: np.ndarray | None = None
        self.overhead_version = 0
        # Dirty-row feed for the HostFeatureStore's resident overhead
        # master (ISSUE 13): rows the dense mirror changed since the last
        # drain, so the store patches O(changed) instead of copying the
        # whole [cap, 3] array (core/dirty_feed.py — the drain protocol
        # shared with the usage tracker).
        self._dirty = DirtyRowFeed()
        # Instrumentation: per-event membership recomputes (delta evidence).
        self.recomputes = 0
        backend.subscribe(
            "pods",
            on_add=self._on_pod_add,
            on_update=self._on_pod_update,
            on_delete=self._on_pod_delete,
        )
        # Reservation-membership feeds: an app's RR or soft reservations
        # changing flips its pods between overhead and reserved. Batch-aware
        # so a serving window's coalesced reservation write-back recomputes
        # under one lock hold.
        reservation_manager.rr_cache.add_mutation_listener(
            BatchableListener(self._on_rr_mutation, self._on_rr_mutation_batch)
        )
        if hasattr(reservation_manager.soft_store, "add_membership_listener"):
            reservation_manager.soft_store.add_membership_listener(
                self._on_soft_membership
            )
        for pod in backend.list_pods():
            self._on_pod_add(pod)

    # -- event handlers ------------------------------------------------------

    def _on_pod_add(self, pod: Pod) -> None:
        if not pod.node_name:
            return
        self._recompute(pod.namespace, pod.name)

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        # Catches the unbound->bound transition and node moves; membership is
        # re-evaluated from current state either way.
        if old.node_name or new.node_name:
            self._recompute(new.namespace, new.name)

    def _on_pod_delete(self, pod: Pod) -> None:
        self._recompute(pod.namespace, pod.name)

    @staticmethod
    def _rr_flipped_pods(old, new) -> set[tuple[str, str]]:
        """Pods whose Status.Pods membership actually flipped: only those
        can change overhead membership, so recompute the symmetric
        difference (one pod per executor bind), not the union — a union
        walk would make binding executor k of an n-gang O(k·n) and the
        whole gang O(n³) via pod_has_reservation's slot scan."""
        old_pods = set((old.namespace, p) for p in old.status.pods.values()) if old else set()
        new_pods = set((new.namespace, p) for p in new.status.pods.values()) if new else set()
        return old_pods.symmetric_difference(new_pods)

    def _on_rr_mutation(self, old, new) -> None:
        for ns, name in self._rr_flipped_pods(old, new):
            self._recompute(ns, name)

    def _on_rr_mutation_batch(self, pairs) -> None:
        """A whole serving window's reservation commits as one batched
        membership update: union of per-pair flips, recomputed under a
        single (reentrant) lock hold."""
        flipped: set[tuple[str, str]] = set()
        for old, new in pairs:
            flipped |= self._rr_flipped_pods(old, new)
        if not flipped:
            return
        with self._lock:
            for ns, name in flipped:
                self._recompute(ns, name)

    def _on_soft_membership(self, app_id: str, pod_name: str) -> None:
        """A soft reservation was added/removed for an executor. Namespace is
        not carried by the soft store; recompute every tracked pod with that
        name (pod names are unique per namespace; collisions across
        namespaces just cause a redundant recompute)."""
        with self._lock:
            keys = list(self._by_name.get(pod_name, ()))
        for ns, name in keys:
            self._recompute(ns, name)
        # The pod may not be tracked yet (soft reservation granted during
        # admission, before binding) — recompute on add covers that case.

    # -- membership ----------------------------------------------------------

    def _recompute(self, namespace: str, name: str) -> None:
        """Re-evaluate one pod's contribution to the aggregates. The backend
        read happens INSIDE the lock so two racing recomputes of the same pod
        can't apply a stale read after a delete retracted it."""
        with self._lock:
            pod = self._backend.get("pods", namespace, name)
            self.recomputes += 1
            key = (namespace, name)
            state = self._pods.get(key)
            # Retract the old contribution.
            if state is not None:
                if state.counted_overhead:
                    self._sub(self._overhead, state.node, state.requests)
                if state.counted_nonsched:
                    self._sub(self._nonsched, state.node, state.requests)
                del self._pods[key]
                peers = self._by_name.get(name)
                if peers is not None:
                    peers.discard(key)
                    if not peers:
                        del self._by_name[name]
            if pod is None or not pod.node_name or pod.is_terminated():
                return  # terminated pods free their resources (see module doc)
            state = _PodState(pod.node_name, pod.request())
            unreserved = not self._rrm.pod_has_reservation(pod)
            if unreserved:
                state.counted_overhead = True
                self._add(self._overhead, state.node, state.requests)
                if pod.scheduler_name != SPARK_SCHEDULER_NAME:
                    state.counted_nonsched = True
                    self._add(self._nonsched, state.node, state.requests)
            self._pods[key] = state
            self._by_name.setdefault(name, set()).add(key)

    def _add(self, agg: dict[str, Resources], node: str, res: Resources) -> None:
        agg.setdefault(node, Resources.zero()).add(res)
        self._on_agg_delta(agg, node, res, +1)

    def _sub(self, agg: dict[str, Resources], node: str, res: Resources) -> None:
        cur = agg.get(node)
        if cur is not None:
            cur.sub(res)
            if cur.is_zero():
                del agg[node]
            self._on_agg_delta(agg, node, res, -1)

    def _on_agg_delta(self, agg, node: str, res: Resources, sign: int) -> None:
        """One applied aggregate delta (caller holds the lock): invalidate
        the node's frozen view and scatter into the dense mirror."""
        self._frozen[id(agg)].pop(node, None)
        if agg is self._overhead:
            self.overhead_version += 1
            if self._dense is not None:
                idx = self._registry.intern(node)
                if idx >= self._dense.shape[0]:
                    grow = max(idx + 1, self._dense.shape[0] * 2, 8)
                    self._dense = np.pad(
                        self._dense, ((0, grow - self._dense.shape[0]), (0, 0))
                    )
                self._dense[idx] += sign * res.as_array().astype(np.int64)
                self._dirty.note(idx)

    # -- dense feed (HostFeatureStore) ---------------------------------------

    def attach_registry(self, registry) -> None:
        """Start maintaining the dense [cap, 3] int64 overhead mirror over
        `registry`'s node-index space. Idempotent; rebuilt from the current
        aggregate on (re)attach."""
        with self._lock:
            if self._registry is registry and self._dense is not None:
                return
            self._registry = registry
            dense = np.zeros((max(registry.capacity, 1), NUM_DIMS), np.int64)
            for node, res in self._overhead.items():
                idx = registry.intern(node)
                if idx >= dense.shape[0]:
                    dense = np.pad(dense, ((0, idx + 1 - dense.shape[0]), (0, 0)))
                dense[idx] += res.as_array().astype(np.int64)
            self._dense = dense
            self.overhead_version += 1
            self._dirty.mark_unknown()

    def collect_delta(self):
        """Drain the dirty-row feed (single consumer: the feature store's
        resident overhead master). Returns (version, rows, vals) — rows is
        None when the mirror cannot name its changes (a re-attach rebuild):
        the consumer then takes one full `overhead_snapshot` copy. vals are
        the current values of `rows`, copied under the lock (consistent
        with `version`). Requires attach_registry."""
        with self._lock:
            if self._dense is None:
                raise RuntimeError("attach_registry() before collect_delta()")
            rows, vals = self._dirty.drain(self._dense)
            return self.overhead_version, rows, vals

    def dense_values(self, rows: np.ndarray) -> np.ndarray:
        """Current dense-mirror values of `rows` (a consistent copy under
        the lock) — the feature store's live-mask-flip patch input. Rows
        beyond the mirror (interned after the last delta) read as zero."""
        with self._lock:
            if self._dense is None:
                raise RuntimeError("attach_registry() before dense_values()")
            rows = np.asarray(rows, dtype=np.int64)
            out = np.zeros((rows.shape[0], NUM_DIMS), np.int64)
            inside = rows < self._dense.shape[0]
            out[inside] = self._dense[rows[inside]]
            return out

    def overhead_snapshot(self, last_version: int | None = None):
        """(version, dense copy | None): None when nothing changed since
        `last_version` — the consistent-copy half of the feature store's
        zero-copy snapshot protocol. Requires attach_registry."""
        with self._lock:
            if self._dense is None:
                raise RuntimeError("attach_registry() before overhead_snapshot()")
            if last_version is not None and last_version == self.overhead_version:
                return self.overhead_version, None
            return self.overhead_version, self._dense.copy()

    # -- queries -------------------------------------------------------------

    def _frozen_views(
        self, agg: dict[str, Resources], nodes
    ) -> dict[str, FrozenResources]:
        memo = self._frozen[id(agg)]
        out: dict[str, FrozenResources] = {}
        for n in nodes:
            res = agg.get(n.name)
            if res is None:
                continue
            view = memo.get(n.name)
            if view is None:
                view = memo[n.name] = FrozenResources(
                    res.cpu_milli, res.mem_kib, res.gpu_milli
                )
            out[n.name] = view
        return out

    def get_overhead(self, nodes) -> dict[str, Resources]:
        """{node: overhead} for `nodes`, as immutable FrozenResources views
        (memoized until the node's aggregate changes — no per-call deep
        copies). Callers needing a mutable value must .copy()."""
        with self._lock:
            return self._frozen_views(self._overhead, nodes)

    def get_non_schedulable_overhead(self, nodes) -> dict[str, Resources]:
        with self._lock:
            return self._frozen_views(self._nonsched, nodes)

    # -- oracle (tests) ------------------------------------------------------

    def compute_node_overhead_oracle(self, node_name: str) -> tuple[Resources, Resources]:
        """The reference's per-query walk (overhead.go:120-168); used by the
        consistency tests to prove the incremental aggregates exact."""
        overhead = Resources.zero()
        non_schedulable = Resources.zero()
        for pod in self._backend.list_pods():
            if pod.node_name != node_name or pod.is_terminated():
                continue
            if not self._rrm.pod_has_reservation(pod):
                overhead.add(pod.request())
                if pod.scheduler_name != SPARK_SCHEDULER_NAME:
                    non_schedulable.add(pod.request())
        return overhead, non_schedulable
