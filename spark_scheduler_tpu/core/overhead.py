"""Overhead accounting: resource requests of pods outside our reservations.

Rebuilds internal/extender/overhead.go:32-209. The computer tracks pod
requests per node via backend add/delete events (only pods bound to a node),
and at query time counts a pod as overhead iff it has no hard or soft
reservation. Non-schedulable overhead additionally excludes pods that belong
to this scheduler (pods of OTHER schedulers only).
"""

from __future__ import annotations

import threading

from spark_scheduler_tpu.models.kube import Pod
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.core.sparkpods import SPARK_SCHEDULER_NAME


class OverheadComputer:
    def __init__(self, backend, reservation_manager):
        self._backend = backend
        self._rrm = reservation_manager
        self._lock = threading.RLock()
        # node -> {pod uid: (namespace, name, requests)}
        self._requests: dict[str, dict[str, tuple[str, str, Resources]]] = {}
        backend.subscribe(
            "pods",
            on_add=self._on_pod_add,
            on_update=self._on_pod_update,
            on_delete=self._on_pod_delete,
        )
        for pod in backend.list_pods():
            self._on_pod_add(pod)

    def _on_pod_add(self, pod: Pod) -> None:
        if not pod.node_name:
            return
        with self._lock:
            self._requests.setdefault(pod.node_name, {})[pod.uid] = (
                pod.namespace,
                pod.name,
                pod.request(),
            )

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        # The reference only watches add/delete (informers re-sync adds);
        # we also catch the unbound->bound transition explicitly. On a node
        # change, drop the stale entry first so the pod isn't double-counted.
        if new.node_name and (not old.node_name or old.node_name != new.node_name):
            if old.node_name:
                self._on_pod_delete(old)
            self._on_pod_add(new)

    def _on_pod_delete(self, pod: Pod) -> None:
        if not pod.node_name:
            return
        with self._lock:
            node = self._requests.get(pod.node_name)
            if node is not None:
                node.pop(pod.uid, None)
                if not node:
                    self._requests.pop(pod.node_name, None)

    def _compute_node_overhead(self, node_name: str) -> tuple[Resources, Resources]:
        """(overhead, non-schedulable overhead) for one node
        (overhead.go:120-168)."""
        with self._lock:
            entries = list(self._requests.get(node_name, {}).values())
        overhead = Resources.zero()
        non_schedulable = Resources.zero()
        for namespace, name, requests in entries:
            pod = self._backend.get("pods", namespace, name)
            if pod is None:
                continue
            if not self._rrm.pod_has_reservation(pod):
                overhead.add(requests)
                if pod.scheduler_name != SPARK_SCHEDULER_NAME:
                    non_schedulable.add(requests)
        return overhead, non_schedulable

    def get_overhead(self, nodes) -> dict[str, Resources]:
        return {n.name: self._compute_node_overhead(n.name)[0] for n in nodes}

    def get_non_schedulable_overhead(self, nodes) -> dict[str, Resources]:
        return {n.name: self._compute_node_overhead(n.name)[1] for n in nodes}
