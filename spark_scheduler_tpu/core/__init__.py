"""The gang-admission engine — rebuild of the reference's internal/extender.

Components: SparkPodLister-equivalent app-shape parsing (sparkpods),
SoftReservationStore, OverheadComputer, ResourceReservationManager, demand
lifecycle + GC, the PlacementSolver (host<->device glue around ops/ kernels),
the SparkSchedulerExtender predicate, failover reconciliation, and the
unschedulable-pod marker.
"""

from spark_scheduler_tpu.core.extender import SparkSchedulerExtender, ExtenderConfig  # noqa: F401
from spark_scheduler_tpu.core.solver import PlacementSolver, HostPacking  # noqa: F401
from spark_scheduler_tpu.core.binpacker import Binpacker, select_binpacker  # noqa: F401
