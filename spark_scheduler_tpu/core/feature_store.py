"""HostFeatureStore — the event-sourced host side of per-window featurize.

Before this store, every serving window re-derived its host features from
scratch: a full `backend.list_nodes()` snapshot, a fresh `{name: node}`
dict, an `OverheadComputer.get_overhead` dict walk with a copy per node,
and a `reserved_usage()` array copy — O(nodes) Python per decision window
even when nothing changed between windows. That is the per-request
state-rebuild anti-pattern the shared-state schedulers (Omega, Firmament)
warn against: scheduler state should stay resident and absorb deltas.

The store keeps every host feature RESIDENT and epoch-versioned:

  nodes / by_name   the node roster (tuple + name->Node map), refreshed
                    from the backend only when the backend's node-mutation
                    counter moved (the capture-before-list versioning dance
                    lives HERE now, its single owner);
  usage             dense int64 [cap, 3] reservation usage over the
                    solver's NodeRegistry index space, re-copied from the
                    ReservedUsageTracker only when its version moved;
  overhead          dense int64 [cap, 3] schedulable overhead, maintained
                    incrementally by OverheadComputer's dense mirror and
                    re-copied only when its version moved.

`snapshot()` is the serving window's single featurize read: when nothing
changed since the previous window it returns the SAME immutable arrays and
tuples (zero work, zero copies); when k rows changed it costs one
vectorized copy of the changed aggregate; only a node add/update/delete
pays the O(nodes) roster walk — i.e. per-window featurize is
O(window + dirty state), not O(nodes).

`statics_epoch` bumps exactly when the roster was re-walked; the solver's
pipelined builder keys its static-field equality check on it, skipping the
eight per-window O(nodes) array compares when no node event occurred.

Thread-safety: snapshots are built under the store lock against
version-consistent copies, so informer/listener threads mutating the
underlying aggregates can never tear a snapshot already handed out.
"""

from __future__ import annotations

import threading
from typing import Any, Mapping, NamedTuple, Optional

import numpy as np

from spark_scheduler_tpu.models.resources import NUM_DIMS


class FeatureSnapshot(NamedTuple):
    """One window's host-feature view. Arrays are frozen (writeable=False)
    and shared across snapshots until the underlying state changes — treat
    everything here as read-only."""

    epoch: int  # bumps on ANY tracked change
    statics_epoch: int  # bumps only on roster (node) changes
    nodes_version: Optional[int]  # backend nodes_version; None if racing
    nodes: tuple  # full node roster
    by_name: Mapping[str, Any]  # name -> Node over the same roster
    usage: Any  # dense int64 [cap,3] (or {node: Resources} w/o tracker)
    overhead: np.ndarray  # dense int64 [cap,3]


class HostFeatureStore:
    def __init__(self, backend, registry, overhead_computer, reservation_manager):
        self._backend = backend
        self._registry = registry
        self._overhead = overhead_computer
        self._rrm = reservation_manager
        self._lock = threading.Lock()
        self._nodes: tuple = ()
        self._by_name: dict[str, Any] = {}
        self._roster_topo: Optional[int] = None
        self._roster_dirty = True
        self._statics_epoch = 0
        self._epoch = 0
        self._usage: Optional[np.ndarray] = None
        self._usage_version: Optional[int] = None
        self._overhead_arr = np.zeros((1, NUM_DIMS), np.int64)
        self._overhead_arr.flags.writeable = False
        self._overhead_version: Optional[int] = None
        # Live-roster row mask over the registry index space: the overhead
        # copy zeroes non-live rows so the dense view equals the legacy
        # get_overhead(all_nodes) dict exactly (a deleted node whose pods
        # still exist keeps aggregate rows that the dict never surfaced).
        self._roster_mask: Optional[np.ndarray] = None
        # Instrumentation — the O(changed) claim as counters, consumed by
        # the tier-1 budget test and the featurize telemetry gauges.
        self.snapshots = 0
        self.roster_rebuilds = 0
        self.usage_refreshes = 0
        self.overhead_refreshes = 0
        overhead_computer.attach_registry(registry)
        # Node events only mark the roster dirty (O(1)); the next snapshot
        # pays the single re-list for the whole burst.
        backend.subscribe(
            "nodes",
            on_add=self._on_node_event,
            on_update=lambda old, new: self._on_node_event(new),
            on_delete=self._on_node_event,
        )

    # -- events ---------------------------------------------------------------

    def _on_node_event(self, *_args) -> None:
        with self._lock:
            self._roster_dirty = True

    # -- snapshot -------------------------------------------------------------

    def snapshot(self) -> FeatureSnapshot:
        with self._lock:
            self.snapshots += 1
            self._refresh_roster()
            usage = self._refresh_usage()
            self._refresh_overhead()
            return FeatureSnapshot(
                epoch=self._epoch,
                statics_epoch=self._statics_epoch,
                nodes_version=self._roster_topo,
                nodes=self._nodes,
                by_name=self._by_name,
                usage=usage,
                overhead=self._overhead_arr,
            )

    def _refresh_roster(self) -> None:
        """Re-list the roster only when a node event (or an unobserved
        backend version move) says it drifted. Version captured BEFORE the
        list and re-checked after — a concurrent mutation can only make the
        roster look stale (one extra walk next snapshot), never fresh over
        an unsynced list. This is the single owner of that dance; the
        extender's per-window copy of it is gone."""
        topo = getattr(self._backend, "nodes_version", None)
        if not (
            self._roster_dirty or topo is None or topo != self._roster_topo
        ):
            return
        nodes = self._backend.list_nodes()
        topo_after = getattr(self._backend, "nodes_version", None)
        self._nodes = tuple(nodes)
        self._by_name = {n.name: n for n in nodes}
        raced = topo is None or topo != topo_after
        self._roster_topo = None if raced else topo
        self._roster_dirty = raced
        # Rebuild the live-row mask (we are already on the O(nodes) path)
        # and force the overhead copy to re-mask against it.
        intern = self._registry.intern
        idx = [intern(n.name) for n in nodes]
        mask = np.zeros(max(self._registry.capacity, 1), dtype=bool)
        mask[idx] = True
        self._roster_mask = mask
        self._overhead_version = None
        self._statics_epoch += 1
        self._epoch += 1
        self.roster_rebuilds += 1

    def _refresh_usage(self):
        tracker = self._rrm.usage_tracker
        if tracker is None:
            # No tracker attached (legacy wiring): the map fallback has no
            # version to key on, so every snapshot is a fresh walk.
            self._epoch += 1
            return self._rrm.reserved_usage()
        version = tracker.version
        if self._usage is None or version != self._usage_version:
            arr = tracker.array()
            arr.flags.writeable = False
            self._usage = arr
            self._usage_version = version
            self._epoch += 1
            self.usage_refreshes += 1
        return self._usage

    def _refresh_overhead(self) -> None:
        version, arr = self._overhead.overhead_snapshot(self._overhead_version)
        if arr is not None:  # None = unchanged since our cached copy
            mask = self._roster_mask
            if mask is not None:
                rows = min(arr.shape[0], mask.shape[0])
                arr[:rows][~mask[:rows]] = 0
                arr[rows:] = 0  # interned-after-roster rows are not live
            arr.flags.writeable = False
            self._overhead_arr = arr
            self._overhead_version = version
            self._epoch += 1
            # Overhead feeds `schedulable = allocatable - overhead`, a
            # STATIC field of the cluster tensors: an overhead change must
            # invalidate the solver's statics-epoch skip (back to the
            # array compare, which sees the schedulable drift and forces
            # the full re-upload) or the device would score efficiencies
            # against a stale schedulable tensor.
            self._statics_epoch += 1
            self.overhead_refreshes += 1

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "snapshots": self.snapshots,
                "roster_rebuilds": self.roster_rebuilds,
                "usage_refreshes": self.usage_refreshes,
                "overhead_refreshes": self.overhead_refreshes,
                "nodes": len(self._nodes),
                "statics_epoch": self._statics_epoch,
            }
