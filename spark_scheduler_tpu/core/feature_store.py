"""HostFeatureStore — the event-sourced host side of per-window featurize.

Before this store, every serving window re-derived its host features from
scratch: a full `backend.list_nodes()` snapshot, a fresh `{name: node}`
dict, an `OverheadComputer.get_overhead` dict walk with a copy per node,
and a `reserved_usage()` array copy — O(nodes) Python per decision window
even when nothing changed between windows. That is the per-request
state-rebuild anti-pattern the shared-state schedulers (Omega, Firmament)
warn against: scheduler state should stay resident and absorb deltas.

The store keeps every host feature RESIDENT and epoch-versioned:

  nodes / by_name   the node roster (tuple + name->Node map), refreshed
                    from the backend only when the backend's node-mutation
                    counter moved (the capture-before-list versioning dance
                    lives HERE now, its single owner);
  usage             dense int64 [cap, 3] reservation usage over the
                    solver's NodeRegistry index space, re-copied from the
                    ReservedUsageTracker only when its version moved;
  overhead          dense int64 [cap, 3] schedulable overhead, maintained
                    incrementally by OverheadComputer's dense mirror and
                    re-copied only when its version moved.

`snapshot()` is the serving window's single featurize read: when nothing
changed since the previous window it returns the SAME immutable arrays and
tuples (zero work, zero copies); when k rows changed it costs one
vectorized copy of the changed aggregate; only a node add/update/delete
pays the O(nodes) roster walk — i.e. per-window featurize is
O(window + dirty state), not O(nodes).

`statics_epoch` bumps exactly when the roster was re-walked; the solver's
pipelined builder keys its static-field equality check on it, skipping the
eight per-window O(nodes) array compares when no node event occurred.

Thread-safety: snapshots are built under the store lock against
version-consistent copies, so informer/listener threads mutating the
underlying aggregates can never tear a snapshot already handed out.
"""

from __future__ import annotations

import threading
from typing import Any, Mapping, NamedTuple, Optional

import numpy as np

from spark_scheduler_tpu.models.resources import NUM_DIMS


class FeatureSnapshot(NamedTuple):
    """One window's host-feature view. Arrays are frozen (writeable=False)
    and shared across snapshots until the underlying state changes — treat
    everything here as read-only."""

    epoch: int  # bumps on ANY tracked change
    statics_epoch: int  # bumps only on roster (node) changes
    nodes_version: Optional[int]  # backend nodes_version; None if racing
    nodes: tuple  # full node roster
    by_name: Mapping[str, Any]  # name -> Node over the same roster
    usage: Any  # dense int64 [cap,3] (or {node: Resources} w/o tracker)
    overhead: np.ndarray  # dense int64 [cap,3]
    # Registry row of each node in `nodes` order (int32, frozen) — lets
    # the solver scatter its request mask instead of walking 100k
    # name->index lookups per cold build. None only when the registry was
    # churning under the rebuild.
    roster_rows: Optional[np.ndarray] = None
    # (previous nodes_version, changed Node objects) when this snapshot's
    # roster differs from the last one by UPDATES AND/OR ADDS only — the
    # solver upserts just those into its native arena (interning the new
    # names and inserting their name ranks incrementally) instead of the
    # O(nodes) identity walk. None = no hint (full walk on version
    # mismatch; deletes always rebuild).
    dirty_hint: Optional[tuple] = None


class RankIndex:
    """Incrementally-maintained PER-ZONE node priority ordering for the
    candidate prefilter (core/prune.py — the two-tier solve's tier 1).

    Keeps every row of the registry index space sorted by the solver's
    within-zone placement key — (available memory asc, cpu asc, name rank,
    row index) — exactly the per-node components of ops/sorting.
    priority_order. Since ISSUE 12 the resident structure is one order PER
    ZONE (zone_id is a static field): the planner's head-walk takes a
    zone's top-K fitting rows straight off that zone's order head, and a
    churn-dirty zone re-scans only its own rows instead of re-ranking all
    N per window. Per-group (per-domain) orderings are served by filtering
    a zone order through the group's row mask — subsetting preserves
    relative order.

    Maintenance is O(changed) key math like the rest of the store: a
    window's availability deltas touch a handful of rows, which are
    removed from their zone's order, re-keyed, binary-searched (vectorized
    lexicographic bisect) and merged back in linear memcpys over that
    zone's rows — versus a full O(N log N) re-sort per window. Only a
    roster/statics change (full upload) pays a rebuild.
    """

    __slots__ = (
        "_zorders", "_pos", "_zone", "_mem", "_cpu", "_name",
        "num_zones", "rebuilds", "incremental_updates",
    )

    def __init__(self):
        self._zorders: list | None = None  # [Zb] of [n_z] int32 row arrays
        self._pos: np.ndarray | None = None  # [N] int32 pos within zone order
        self._zone: np.ndarray | None = None  # [N] int32
        self._mem: np.ndarray | None = None  # [N] int64 key snapshots
        self._cpu: np.ndarray | None = None
        self._name: np.ndarray | None = None
        self.num_zones = 0
        self.rebuilds = 0
        self.incremental_updates = 0

    def invalidate(self) -> None:
        self._zorders = None

    @property
    def valid(self) -> bool:
        return self._zorders is not None

    @property
    def rows(self) -> int:
        return 0 if self._mem is None or not self.valid else int(
            self._mem.shape[0]
        )

    def rebuild(
        self,
        avail: np.ndarray,
        name_rank: np.ndarray,
        zone_id: np.ndarray,
        num_zones: int,
    ) -> None:
        n = avail.shape[0]
        self._mem = avail[:, 1].astype(np.int64)  # MEM_DIM
        self._cpu = avail[:, 0].astype(np.int64)  # CPU_DIM
        self._name = np.asarray(name_rank).astype(np.int64)
        self._zone = np.asarray(zone_id).astype(np.int32)
        self.num_zones = int(num_zones)
        rows = np.arange(n)
        order = np.lexsort(
            (rows, self._name, self._cpu, self._mem)
        ).astype(np.int32)
        # Split the global order by zone (stable: relative order within a
        # zone is the zone's priority order) and invert to per-zone
        # positions in one pass.
        zo = self._zone[order]
        self._zorders = [
            order[zo == z] for z in range(self.num_zones)
        ]
        self._pos = np.empty(n, np.int32)
        for zorder in self._zorders:
            self._pos[zorder] = np.arange(len(zorder), dtype=np.int32)
        self.rebuilds += 1

    def update_rows(
        self, avail: np.ndarray, name_rank: np.ndarray, dirty: np.ndarray,
        zone_id: np.ndarray | None = None,
    ) -> None:
        """Re-key `dirty` rows against the new availability (and zone, when
        a statics row-delta moved one) and merge them back into their
        zones' resident orders. Cost: O(changed + affected-zone memcpy)."""
        if (
            self._zorders is None
            or self._mem.shape[0] != avail.shape[0]
        ):
            raise RuntimeError("update_rows on an invalid index")
        d = np.unique(np.asarray(dirty))
        if d.size == 0:
            return
        new_zone = (
            self._zone[d]
            if zone_id is None
            else np.asarray(zone_id)[d].astype(np.int32)
        )
        old_zone = self._zone[d]
        touched = np.unique(np.concatenate([old_zone, new_zone]))
        # Remove the dirty rows from their OLD zones' orders.
        for z in touched:
            zorder = self._zorders[z]
            rm = d[old_zone == z]
            if rm.size:
                keep = np.ones(len(zorder), bool)
                keep[self._pos[rm]] = False
                self._zorders[z] = zorder[keep]
        # Re-key.
        self._mem[d] = avail[d, 1]
        self._cpu[d] = avail[d, 0]
        # Re-key the name component too: a statics row-delta (node ADD
        # under the gapped-rank scheme) changes the dirty rows' name
        # ranks without a roster rebuild — unchanged rows re-assign
        # their existing value (a no-op).
        self._name[d] = np.asarray(name_rank)[d]
        self._zone[d] = new_zone
        # Merge into the NEW zones' orders and re-number their positions.
        for z in touched:
            ins = d[new_zone == z]
            clean = self._zorders[z]
            if ins.size:
                ds = ins[np.lexsort(
                    (ins, self._name[ins], self._cpu[ins], self._mem[ins])
                )]
                pos = self._bisect(clean, ds)
                clean = np.insert(clean, pos, ds)
                self._zorders[z] = clean
            self._pos[clean] = np.arange(len(clean), dtype=np.int32)
        self.incremental_updates += 1

    def _bisect(self, clean: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """Vectorized lexicographic bisect: for each row, the count of
        clean-order entries with a strictly smaller (mem, cpu, name, row)
        key. Keys are totally ordered (row index tiebreak), so this is an
        exact insertion position."""
        mem, cpu, name = self._mem, self._cpu, self._name
        rm, rc, rn = mem[rows], cpu[rows], name[rows]
        n = clean.shape[0]
        if n == 0:
            return np.zeros(rows.shape[0], np.int64)
        lo = np.zeros(rows.shape[0], np.int64)
        hi = np.full(rows.shape[0], n, np.int64)
        # Classic lower-bound bisection, all lanes in lockstep; log2(n)+1
        # rounds always converge (lo == hi for every lane).
        for _ in range(max(1, int(np.ceil(np.log2(n + 1))) + 1)):
            active = lo < hi
            mid = (lo + hi) // 2
            m = clean[np.minimum(mid, max(n - 1, 0))]
            less = (mem[m] < rm) | (
                (mem[m] == rm)
                & (
                    (cpu[m] < rc)
                    | (
                        (cpu[m] == rc)
                        & ((name[m] < rn) | ((name[m] == rn) & (m < rows)))
                    )
                )
            )
            lo = np.where(active & less, mid + 1, lo)
            hi = np.where(active & ~less, mid, hi)
        return lo

    def zone_order(self, z: int) -> np.ndarray:
        """Zone z's rows in priority order (treat as read-only)."""
        return self._zorders[z]

    def order(self) -> np.ndarray:
        """The GLOBAL priority order, merged from the zone orders — an
        O(N log N) reconstruction for oracles/tests; the serving planner
        only ever walks zone orders."""
        parts = [z for z in self._zorders if len(z)]
        if not parts:
            return np.empty(0, np.int32)
        rows = np.concatenate(parts)
        return rows[np.lexsort(
            (rows, self._name[rows], self._cpu[rows], self._mem[rows])
        )].astype(np.int32)

    def stats(self) -> dict:
        return {
            "rebuilds": self.rebuilds,
            "incremental_updates": self.incremental_updates,
            "rows": self.rows,
            "zones": 0 if not self.valid else sum(
                1 for z in self._zorders if len(z)
            ),
        }


class HostFeatureStore:
    def __init__(self, backend, registry, overhead_computer, reservation_manager):
        self._backend = backend
        self._registry = registry
        self._overhead = overhead_computer
        self._rrm = reservation_manager
        self._lock = threading.Lock()
        self._nodes: tuple = ()
        self._by_name: dict[str, Any] = {}
        self._node_pos: dict[str, int] = {}  # name -> position in _nodes
        self._roster_topo: Optional[int] = None
        self._roster_dirty = True
        # Racy/unknown-name events force the full O(nodes) rebuild;
        # update, add AND delete bursts ride the patch paths below
        # (deletes since ISSUE 12: swap-remove + live-mask clear +
        # registry-row tombstone instead of the full re-list).
        self._dirty_full = True
        self._dirty_updates: dict[str, Any] = {}  # name -> newest Node
        self._dirty_adds: dict[str, Any] = {}  # name -> added Node
        self._dirty_deletes: dict[str, Any] = {}  # name -> deleted Node
        # Deleted-but-still-interned registry rows (the solver recycles
        # them through its tombstone release once their usage drains);
        # past the ratio threshold ONE full rebuild re-compacts the
        # roster structures.
        self._tombstones = 0
        self._roster_rows: Optional[np.ndarray] = None
        self._dirty_hint: Optional[tuple] = None
        self._statics_epoch = 0
        self._epoch = 0
        self._usage: Optional[np.ndarray] = None
        self._usage_version: Optional[int] = None
        self._overhead_arr = np.zeros((1, NUM_DIMS), np.int64)
        self._overhead_arr.flags.writeable = False
        self._overhead_version: Optional[int] = None
        # Live-roster row mask over the registry index space: the overhead
        # copy zeroes non-live rows so the dense view equals the legacy
        # get_overhead(all_nodes) dict exactly (a deleted node whose pods
        # still exist keeps aggregate rows that the dict never surfaced).
        self._roster_mask: Optional[np.ndarray] = None
        # Instrumentation — the O(changed) claim as counters, consumed by
        # the tier-1 budget test and the featurize telemetry gauges.
        self.snapshots = 0
        self.roster_rebuilds = 0
        self.roster_patches = 0
        self.roster_add_patches = 0
        self.roster_delete_patches = 0
        self.usage_refreshes = 0
        self.overhead_refreshes = 0
        overhead_computer.attach_registry(registry)
        # Node events only mark the roster dirty (O(1)); the next snapshot
        # pays ONE refresh for the whole burst — a patch (O(changed) dict
        # update + tuple rebuild) when the burst was updates of known
        # nodes, the full O(nodes) re-list otherwise.
        backend.subscribe(
            "nodes",
            on_add=self._on_node_add,
            on_update=self._on_node_update,
            on_delete=self._on_node_delete,
        )

    # -- events ---------------------------------------------------------------

    def _on_node_delete(self, node=None, *_args) -> None:
        """Node DELETEs ride the patch path too (ISSUE 12 satellite: a
        single deleted node used to trigger the full re-list + re-intern
        + arena walk): the deleted Node is captured here, and the next
        snapshot swap-removes it from the roster structures and clears
        its live-mask row in O(changed) — the registry row tombstones
        (the solver recycles it via the delta-statics journal once its
        usage drains). Unknown names are racy: full rebuild."""
        with self._lock:
            self._roster_dirty = True
            if self._dirty_full:
                return
            name = getattr(node, "name", None)
            if name is None:
                self._dirty_full = True
            elif name in self._dirty_adds:
                # Added then deleted within one burst: net no-op.
                del self._dirty_adds[name]
            elif name in self._dirty_deletes:
                pass  # duplicate delivery of a pending delete: no-op
            elif name in self._node_pos:
                self._dirty_updates.pop(name, None)
                self._dirty_deletes[name] = node
            else:
                self._dirty_full = True

    def _on_node_add(self, new) -> None:
        """Node ADDs ride their own patch path (ISSUE 11 satellite: a
        single added node used to trigger the full re-list + re-intern):
        the added Node object is captured here, and the next snapshot
        APPENDS it — roster tuple, name maps, registry row, live mask —
        in O(changed), never re-walking the existing roster. A name we
        already track arriving as an "add" is a racy replay: full rebuild."""
        with self._lock:
            self._roster_dirty = True
            if not self._dirty_full:
                if new.name in self._node_pos or new.name in self._dirty_adds:
                    self._dirty_full = True
                else:
                    self._dirty_adds[new.name] = new

    def _on_node_update(self, _old, new) -> None:
        with self._lock:
            self._roster_dirty = True
            if not self._dirty_full:
                if new.name in self._dirty_deletes:
                    # Deleted then touched again within one burst: racy
                    # replay — rebuild.
                    self._dirty_full = True
                elif new.name in self._dirty_adds:
                    # Added then updated within one burst: the add entry
                    # carries the newest object.
                    self._dirty_adds[new.name] = new
                elif new.name in self._node_pos:
                    self._dirty_updates[new.name] = new
                else:
                    self._dirty_full = True  # unknown name: racy — rebuild

    # -- snapshot -------------------------------------------------------------

    def snapshot(self) -> FeatureSnapshot:
        with self._lock:
            self.snapshots += 1
            self._refresh_roster()
            usage = self._refresh_usage()
            self._refresh_overhead()
            hint = self._dirty_hint
            self._dirty_hint = None  # one consumer, one hand-off
            return FeatureSnapshot(
                epoch=self._epoch,
                statics_epoch=self._statics_epoch,
                nodes_version=self._roster_topo,
                nodes=self._nodes,
                by_name=self._by_name,
                usage=usage,
                overhead=self._overhead_arr,
                roster_rows=self._roster_rows,
                dirty_hint=hint,
            )

    def _refresh_roster(self) -> None:
        """Refresh the roster only when a node event (or an unobserved
        backend version move) says it drifted.

        UPDATE-ONLY bursts (the common node event: heartbeat flips,
        capacity drift) take the PATCH path: the changed Node objects were
        captured by the event subscription, so the roster tuple and
        name->Node map are copied and patched in O(nodes) memcpy +
        O(changed) dict writes — no backend re-list, no re-intern, and the
        registry-row array / live-row mask carry over unchanged (the name
        set is identical). The solver gets the changed objects as
        `dirty_hint` so its native-arena sync upserts just those rows.

        Adds, deletes, unknown names, or a racing version take the full
        rebuild: version captured BEFORE the list and re-checked after — a
        concurrent mutation can only make the roster look stale (one extra
        walk next snapshot), never fresh over an unsynced list. This is
        the single owner of that dance."""
        topo = getattr(self._backend, "nodes_version", None)
        if not (
            self._roster_dirty or topo is None or topo != self._roster_topo
        ):
            return
        if self._dirty_deletes and self._tombstones >= max(
            64, len(self._nodes) // 8
        ):
            # Tombstone-ratio threshold: too many deleted-but-interned
            # rows accumulated — pay ONE full rebuild to re-compact the
            # roster structures instead of patching forever.
            self._dirty_full = True
            self._tombstones = 0
        can_patch = (
            not self._dirty_full
            and (
                self._dirty_updates
                or self._dirty_adds
                or self._dirty_deletes
            )
            and topo is not None
            and self._roster_topo is not None
        )
        if can_patch:
            prev = self._roster_topo
            updates = self._dirty_updates
            adds = self._dirty_adds
            deletes = self._dirty_deletes
            self._dirty_updates = {}
            self._dirty_adds = {}
            self._dirty_deletes = {}
            nodes = list(self._nodes)
            by_name = dict(self._by_name)
            pos = self._node_pos
            for name, node in updates.items():
                nodes[pos[name]] = node
                by_name[name] = node
            if deletes:
                # DELETE patch (ISSUE 12, O(changed)): swap-remove each
                # deleted node (the last roster entry fills its hole, so
                # only ONE position shifts per delete), clear its
                # live-mask row (the overhead copy re-masks on its next
                # refresh), and drop its registry row from roster_rows —
                # the row itself stays interned as a TOMBSTONE until the
                # solver recycles it. The existing roster is never
                # re-listed or re-interned.
                rows_arr = np.array(self._roster_rows)
                mask = self._roster_mask
                for name in deletes:
                    i = pos.pop(name)
                    by_name.pop(name, None)
                    last = len(nodes) - 1
                    row = rows_arr[i]
                    if i != last:
                        nodes[i] = nodes[last]
                        rows_arr[i] = rows_arr[last]
                        pos[nodes[i].name] = i
                    nodes.pop()
                    rows_arr = rows_arr[:last]
                    if mask is not None and 0 <= row < mask.shape[0]:
                        mask[row] = False
                rows_arr = rows_arr.copy()
                rows_arr.flags.writeable = False
                self._roster_rows = rows_arr
                self._overhead_version = None  # re-mask on next refresh
                self._tombstones += len(deletes)
                self.roster_delete_patches += 1
            if adds:
                # APPEND path (node-ADD, O(changed)): new names intern in
                # one bulk call, the registry-row array and live-row mask
                # extend in place, and the overhead copy re-masks against
                # the grown mask on its next refresh. The existing roster
                # is never re-listed or re-interned.
                for name, node in adds.items():
                    pos[name] = len(nodes)
                    nodes.append(node)
                    by_name[name] = node
                new_rows = self._registry.intern_many(list(adds))
                rows = np.concatenate(
                    [self._roster_rows, new_rows.astype(np.int32)]
                )
                rows.flags.writeable = False
                self._roster_rows = rows
                cap = max(self._registry.capacity, 1)
                mask = self._roster_mask
                if mask is None or mask.shape[0] < cap:
                    grown = np.zeros(cap, dtype=bool)
                    if mask is not None:
                        grown[: mask.shape[0]] = mask
                    mask = grown
                mask[new_rows] = True
                self._roster_mask = mask
                self._overhead_version = None  # re-mask on next refresh
                self.roster_add_patches += 1
            self._nodes = tuple(nodes)
            self._by_name = by_name
            self._roster_topo = topo
            self._roster_dirty = False
            # 3-tuple since ISSUE 12: (base version, changed Nodes,
            # deleted names) — consumers that predate deletes index [0]
            # and [1] unchanged.
            self._dirty_hint = (
                prev,
                tuple(updates.values()) + tuple(adds.values()),
                tuple(deletes),
            )
            self._statics_epoch += 1
            self._epoch += 1
            self.roster_patches += 1
            return
        nodes = self._backend.list_nodes()
        topo_after = getattr(self._backend, "nodes_version", None)
        self._nodes = tuple(nodes)
        self._by_name = {n.name: n for n in nodes}
        self._node_pos = {n.name: i for i, n in enumerate(nodes)}
        raced = topo is None or topo != topo_after
        self._roster_topo = None if raced else topo
        self._roster_dirty = raced
        self._dirty_full = raced
        self._dirty_updates = {}
        self._dirty_adds = {}
        self._dirty_deletes = {}
        self._tombstones = 0
        self._dirty_hint = None
        # Rebuild the live-row mask (we are already on the O(nodes) path)
        # and force the overhead copy to re-mask against it. One bulk
        # intern instead of a lock acquire per name.
        rows = self._registry.intern_many([n.name for n in nodes])
        rows.flags.writeable = False
        self._roster_rows = rows
        mask = np.zeros(max(self._registry.capacity, 1), dtype=bool)
        mask[rows] = True
        self._roster_mask = mask
        self._overhead_version = None
        self._statics_epoch += 1
        self._epoch += 1
        self.roster_rebuilds += 1

    def _refresh_usage(self):
        tracker = self._rrm.usage_tracker
        if tracker is None:
            # No tracker attached (legacy wiring): the map fallback has no
            # version to key on, so every snapshot is a fresh walk.
            self._epoch += 1
            return self._rrm.reserved_usage()
        version = tracker.version
        if self._usage is None or version != self._usage_version:
            arr = tracker.array()
            arr.flags.writeable = False
            self._usage = arr
            self._usage_version = version
            self._epoch += 1
            self.usage_refreshes += 1
        return self._usage

    def _refresh_overhead(self) -> None:
        version, arr = self._overhead.overhead_snapshot(self._overhead_version)
        if arr is not None:  # None = unchanged since our cached copy
            mask = self._roster_mask
            if mask is not None:
                rows = min(arr.shape[0], mask.shape[0])
                arr[:rows][~mask[:rows]] = 0
                arr[rows:] = 0  # interned-after-roster rows are not live
            arr.flags.writeable = False
            self._overhead_arr = arr
            self._overhead_version = version
            self._epoch += 1
            # Overhead feeds `schedulable = allocatable - overhead`, a
            # STATIC field of the cluster tensors: an overhead change must
            # invalidate the solver's statics-epoch skip (back to the
            # array compare, which sees the schedulable drift and forces
            # the full re-upload) or the device would score efficiencies
            # against a stale schedulable tensor.
            self._statics_epoch += 1
            self.overhead_refreshes += 1

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "snapshots": self.snapshots,
                "roster_rebuilds": self.roster_rebuilds,
                "roster_patches": self.roster_patches,
                "roster_add_patches": self.roster_add_patches,
                "roster_delete_patches": self.roster_delete_patches,
                "tombstones": self._tombstones,
                "usage_refreshes": self.usage_refreshes,
                "overhead_refreshes": self.overhead_refreshes,
                "nodes": len(self._nodes),
                "statics_epoch": self._statics_epoch,
            }
