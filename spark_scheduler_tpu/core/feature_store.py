"""HostFeatureStore — the event-sourced host side of per-window featurize.

Before this store, every serving window re-derived its host features from
scratch: a full `backend.list_nodes()` snapshot, a fresh `{name: node}`
dict, an `OverheadComputer.get_overhead` dict walk with a copy per node,
and a `reserved_usage()` array copy — O(nodes) Python per decision window
even when nothing changed between windows. That is the per-request
state-rebuild anti-pattern the shared-state schedulers (Omega, Firmament)
warn against: scheduler state should stay resident and absorb deltas.

The store keeps every host feature RESIDENT and epoch-versioned:

  nodes / by_name   the node roster (tuple + name->Node map), refreshed
                    from the backend only when the backend's node-mutation
                    counter moved (the capture-before-list versioning dance
                    lives HERE now, its single owner);
  usage             dense int64 [cap, 3] reservation usage over the
                    solver's NodeRegistry index space, re-copied from the
                    ReservedUsageTracker only when its version moved;
  overhead          dense int64 [cap, 3] schedulable overhead, maintained
                    incrementally by OverheadComputer's dense mirror and
                    re-copied only when its version moved.

`snapshot()` is the serving window's single featurize read: when nothing
changed since the previous window it returns the SAME immutable arrays
(zero work, zero copies); when k rows changed it costs k row patches into
the RESIDENT masters (ISSUE 13 — the per-refresh full [cap, 3] copies are
gone: the tracker/overhead mirrors name their dirty rows and the store
scatters just those); only a node add/update/delete pays the O(changed)
roster patch — i.e. per-window featurize is O(window + dirty rows), never
O(nodes).

`statics_epoch` bumps exactly when the roster was re-walked; the solver's
pipelined builder keys its static-field equality check on it, skipping the
eight per-window O(nodes) array compares when no node event occurred.

`avail_epoch` / `avail_journal` (ISSUE 13): the store names EXACTLY which
registry rows' availability inputs (usage / overhead / node statics)
changed in each refresh epoch — the solver's resident tensor build and its
pipelined device mirror sync by scattering those rows instead of running a
dense [N]-wide compare per window. A refresh that cannot name its rows
(from-scratch tracker rebuild, roster re-list) BREAKS the journal: the
epoch bumps with no entry, and the solver falls back to the dense compare
for that one build.

Capacity growth is AMORTIZED (ISSUE 13): the usage/overhead masters, the
live-row mask and the roster-row buffer are allocated at the power-of-two
bucket of the registry capacity, so a node-ADD burst appends in place —
`array_grows` counts the reallocations (CI pins zero across a burst).

Thread-safety: all mutation happens inside `snapshot()` under the store
lock, and the serving paths take their snapshot and consume it within the
request on the predicate batcher's single dispatcher thread. Handed-out
arrays are read-only VIEWS of the resident masters: a consumer that parks
a snapshot across later refreshes observes newer row values (resident-
state semantics) — every decision path in this repo reads its snapshot
immediately after taking it.
"""

from __future__ import annotations

import threading
from typing import Any, Mapping, NamedTuple, Optional, Sequence

import numpy as np

from spark_scheduler_tpu.models.resources import NUM_DIMS


from spark_scheduler_tpu.models.cluster import (  # noqa: E402
    pad_bucket as _bucket,
)


class FeatureSnapshot(NamedTuple):
    """One window's host-feature view. Arrays are read-only views of the
    store's resident masters, shared across snapshots until the underlying
    rows change — treat everything here as read-only and consume it within
    the taking request (see the module docstring's residency contract)."""

    epoch: int  # bumps on ANY tracked change
    statics_epoch: int  # bumps only on roster (node) changes
    nodes_version: Optional[int]  # backend nodes_version; None if racing
    nodes: Sequence[Any]  # full node roster (store-owned; read-only)
    by_name: Mapping[str, Any]  # name -> Node over the same roster
    usage: Any  # dense int64 [cap,3] (or {node: Resources} w/o tracker)
    overhead: np.ndarray  # dense int64 [cap,3]
    # Registry row of each node in `nodes` order (int32 read-only view of
    # the preallocated roster buffer) — lets the solver scatter its
    # request mask instead of walking 100k name->index lookups per cold
    # build. None only when the registry was churning under the rebuild.
    roster_rows: Optional[np.ndarray] = None
    # (previous nodes_version, changed Node objects) when this snapshot's
    # roster differs from the last one by UPDATES AND/OR ADDS only — the
    # solver upserts just those into its native arena (interning the new
    # names and inserting their name ranks incrementally) instead of the
    # O(nodes) identity walk. None = no hint (full walk on version
    # mismatch; deletes always rebuild).
    dirty_hint: Optional[tuple] = None
    # Availability-input change journal (ISSUE 13): `avail_epoch` is the
    # store's refresh epoch for availability inputs, and `avail_journal`
    # maps each epoch to (usage_rows, overhead_rows, node_rows) — the
    # EXACT registry rows whose usage / overhead / node-static inputs
    # changed in that epoch (split so the solver copies-on-write only the
    # static fields a class of change can touch). The solver's
    # resident tensor build recomputes just those rows and its pipelined
    # mirror syncs by scattering them; a missing epoch (journal break or
    # eviction) sends it to the dense-compare fallback for one build.
    avail_epoch: Optional[int] = None
    avail_journal: Optional[Mapping[int, tuple]] = None


class RankIndex:
    """Incrementally-maintained PER-ZONE node priority ordering for the
    candidate prefilter (core/prune.py — the two-tier solve's tier 1).

    Keeps every row of the registry index space sorted by the solver's
    within-zone placement key — (available memory asc, cpu asc, name rank,
    row index) — exactly the per-node components of ops/sorting.
    priority_order. Since ISSUE 12 the resident structure is one order PER
    ZONE (zone_id is a static field): the planner's head-walk takes a
    zone's top-K fitting rows straight off that zone's order head, and a
    churn-dirty zone re-scans only its own rows instead of re-ranking all
    N per window. Per-group (per-domain) orderings are served by filtering
    a zone order through the group's row mask — subsetting preserves
    relative order.

    Maintenance is O(changed) key math like the rest of the store: a
    window's availability deltas touch a handful of rows, which are
    removed from their zone's order, re-keyed, binary-searched (vectorized
    lexicographic bisect) and merged back in linear memcpys over that
    zone's rows — versus a full O(N log N) re-sort per window. Only a
    roster/statics change (full upload) pays a rebuild.
    """

    __slots__ = (
        "_zorders", "_zrows", "_pos", "_zone", "_mem", "_cpu", "_name",
        "num_zones", "rebuilds", "incremental_updates", "zone_sorts",
    )

    def __init__(self):
        self._zorders: list | None = None  # [Zb] of [n_z] int32 row arrays
        self._zrows: list | None = None  # [Zb] unsorted rows of LAZY zones
        self._pos: np.ndarray | None = None  # [N] int32 pos within zone order
        self._zone: np.ndarray | None = None  # [N] int32
        self._mem: np.ndarray | None = None  # [N] int64 key snapshots
        self._cpu: np.ndarray | None = None
        self._name: np.ndarray | None = None
        self.num_zones = 0
        self.rebuilds = 0
        self.incremental_updates = 0
        self.zone_sorts = 0  # deferred per-zone lexsorts actually paid

    def invalidate(self) -> None:
        self._zorders = None

    @property
    def valid(self) -> bool:
        return self._zorders is not None

    @property
    def rows(self) -> int:
        return 0 if self._mem is None or not self.valid else int(
            self._mem.shape[0]
        )

    def rebuild(
        self,
        avail: np.ndarray,
        name_rank: np.ndarray,
        zone_id: np.ndarray,
        num_zones: int,
    ) -> None:
        n = avail.shape[0]
        self._mem = avail[:, 1].astype(np.int64)  # MEM_DIM
        self._cpu = avail[:, 0].astype(np.int64)  # CPU_DIM
        self._name = np.asarray(name_rank).astype(np.int64)
        self._zone = np.asarray(zone_id).astype(np.int32)
        self.num_zones = int(num_zones)
        # LAZY per-zone cold build (ISSUE 13 tentpole (d)): the rebuild
        # pays only one stable zone-bucketing pass (radix argsort of the
        # int32 zone ids — no key comparisons); each zone's 4-key LEXSORT,
        # the expensive part of the old global cold build, is deferred to
        # the zone's first `zone_order` touch. A restart that re-plans one
        # zone pays one zone's sort, not the global one.
        order = np.argsort(self._zone, kind="stable").astype(np.int32)
        zo = self._zone[order]
        bounds = np.searchsorted(zo, np.arange(self.num_zones + 1))
        self._zrows = [
            order[bounds[z]:bounds[z + 1]] for z in range(self.num_zones)
        ]
        self._zorders = [None] * self.num_zones
        self._pos = np.empty(n, np.int32)
        self.rebuilds += 1

    def _materialize(self, z: int) -> np.ndarray:
        """Pay zone z's deferred lexsort and make its order resident."""
        rows = self._zrows[z]
        if rows.size:
            zorder = rows[np.lexsort(
                (rows, self._name[rows], self._cpu[rows], self._mem[rows])
            )].astype(np.int32)
        else:
            zorder = rows.astype(np.int32)
        self._zorders[z] = zorder
        self._pos[zorder] = np.arange(len(zorder), dtype=np.int32)
        self._zrows[z] = zorder  # keep slots aligned; no longer consulted
        self.zone_sorts += 1
        return zorder

    def update_rows(
        self, avail: np.ndarray, name_rank: np.ndarray, dirty: np.ndarray,
        zone_id: np.ndarray | None = None,
    ) -> None:
        """Re-key `dirty` rows against the new availability (and zone, when
        a statics row-delta moved one) and merge them back into their
        zones' resident orders. Cost: O(changed + affected-zone memcpy)."""
        if (
            self._zorders is None
            or self._mem.shape[0] != avail.shape[0]
        ):
            raise RuntimeError("update_rows on an invalid index")
        d = np.unique(np.asarray(dirty))
        if d.size == 0:
            return
        new_zone = (
            self._zone[d]
            if zone_id is None
            else np.asarray(zone_id)[d].astype(np.int32)
        )
        old_zone = self._zone[d]
        touched = np.unique(np.concatenate([old_zone, new_zone]))
        # A lazily-deferred zone must materialize before its order can be
        # merged into (its _pos entries are unset until then).
        for z in touched:
            if self._zorders[z] is None:
                self._materialize(int(z))
        # Remove the dirty rows from their OLD zones' orders.
        for z in touched:
            zorder = self._zorders[z]
            rm = d[old_zone == z]
            if rm.size:
                keep = np.ones(len(zorder), bool)
                keep[self._pos[rm]] = False
                self._zorders[z] = zorder[keep]
        # Re-key.
        self._mem[d] = avail[d, 1]
        self._cpu[d] = avail[d, 0]
        # Re-key the name component too: a statics row-delta (node ADD
        # under the gapped-rank scheme) changes the dirty rows' name
        # ranks without a roster rebuild — unchanged rows re-assign
        # their existing value (a no-op).
        self._name[d] = np.asarray(name_rank)[d]
        self._zone[d] = new_zone
        # Merge into the NEW zones' orders and re-number their positions.
        for z in touched:
            ins = d[new_zone == z]
            clean = self._zorders[z]
            if ins.size:
                ds = ins[np.lexsort(
                    (ins, self._name[ins], self._cpu[ins], self._mem[ins])
                )]
                pos = self._bisect(clean, ds)
                clean = np.insert(clean, pos, ds)
                self._zorders[z] = clean
            self._pos[clean] = np.arange(len(clean), dtype=np.int32)
        self.incremental_updates += 1

    def _bisect(self, clean: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """Vectorized lexicographic bisect: for each row, the count of
        clean-order entries with a strictly smaller (mem, cpu, name, row)
        key. Keys are totally ordered (row index tiebreak), so this is an
        exact insertion position."""
        mem, cpu, name = self._mem, self._cpu, self._name
        rm, rc, rn = mem[rows], cpu[rows], name[rows]
        n = clean.shape[0]
        if n == 0:
            return np.zeros(rows.shape[0], np.int64)
        lo = np.zeros(rows.shape[0], np.int64)
        hi = np.full(rows.shape[0], n, np.int64)
        # Classic lower-bound bisection, all lanes in lockstep; log2(n)+1
        # rounds always converge (lo == hi for every lane).
        for _ in range(max(1, int(np.ceil(np.log2(n + 1))) + 1)):
            active = lo < hi
            mid = (lo + hi) // 2
            m = clean[np.minimum(mid, max(n - 1, 0))]
            less = (mem[m] < rm) | (
                (mem[m] == rm)
                & (
                    (cpu[m] < rc)
                    | (
                        (cpu[m] == rc)
                        & ((name[m] < rn) | ((name[m] == rn) & (m < rows)))
                    )
                )
            )
            lo = np.where(active & less, mid + 1, lo)
            hi = np.where(active & ~less, mid, hi)
        return lo

    def zone_order(self, z: int) -> np.ndarray:
        """Zone z's rows in priority order (treat as read-only); pays the
        zone's deferred cold lexsort on first touch."""
        zo = self._zorders[z]
        return zo if zo is not None else self._materialize(z)

    def order(self) -> np.ndarray:
        """The GLOBAL priority order, merged from the zone orders — an
        O(N log N) reconstruction for oracles/tests; the serving planner
        only ever walks zone orders."""
        parts = [
            self.zone_order(z)
            for z in range(self.num_zones)
        ]
        parts = [z for z in parts if len(z)]
        if not parts:
            return np.empty(0, np.int32)
        rows = np.concatenate(parts)
        return rows[np.lexsort(
            (rows, self._name[rows], self._cpu[rows], self._mem[rows])
        )].astype(np.int32)

    def stats(self) -> dict:
        return {
            "rebuilds": self.rebuilds,
            "incremental_updates": self.incremental_updates,
            "zone_sorts": self.zone_sorts,
            "rows": self.rows,
            "zones": 0 if not self.valid else sum(
                1
                for z in range(self.num_zones)
                if len(
                    self._zorders[z]
                    if self._zorders[z] is not None
                    else self._zrows[z]
                )
            ),
            "lazy_zones": 0 if not self.valid else sum(
                1 for z in self._zorders if z is None
            ),
        }


class HostFeatureStore:
    def __init__(self, backend, registry, overhead_computer, reservation_manager):
        self._backend = backend
        self._registry = registry
        self._overhead = overhead_computer
        self._rrm = reservation_manager
        self._lock = threading.Lock()
        # Roster structures are store-OWNED and mutated in place (adds
        # append, updates assign; a delete burst copies once — see
        # _refresh_roster). Snapshots expose them directly.
        self._nodes: list = []
        self._by_name: dict[str, Any] = {}
        self._node_pos: dict[str, int] = {}  # name -> position in _nodes
        self._roster_topo: Optional[int] = None
        self._roster_dirty = True
        # Racy/unknown-name events force the full O(nodes) rebuild;
        # update, add AND delete bursts ride the patch paths below
        # (deletes since ISSUE 12: swap-remove + live-mask clear +
        # registry-row tombstone instead of the full re-list).
        self._dirty_full = True
        self._dirty_updates: dict[str, Any] = {}  # name -> newest Node
        self._dirty_adds: dict[str, Any] = {}  # name -> added Node
        self._dirty_deletes: dict[str, Any] = {}  # name -> deleted Node
        # Deleted-but-still-interned registry rows (the solver recycles
        # them through its tombstone release once their usage drains);
        # past the ratio threshold ONE full rebuild re-compacts the
        # roster structures.
        self._tombstones = 0
        # Preallocated roster-row buffer (ISSUE 13 amortized growth):
        # `_roster_buf[:len(nodes)]` is the registry row of each roster
        # position; snapshots hand out a read-only VIEW. Adds append in
        # place; a delete burst pays ONE copy-on-write (stale snapshots
        # keep positional integrity) and then swap-removes on the owned
        # copy — the per-delete np.array(...) copy is gone.
        self._roster_buf: np.ndarray = np.empty(8, np.int32)
        self._roster_view: Optional[np.ndarray] = None
        self._dirty_hint: Optional[tuple] = None
        self._statics_epoch = 0
        self._epoch = 0
        # Resident masters (ISSUE 13): writable int64 [bucket(cap), 3]
        # aggregates patched O(changed) from the tracker/overhead dirty
        # feeds; snapshots hand out read-only views. Sized at the
        # power-of-two bucket of the registry capacity — the same bucket
        # the solver pads to, so `_dense_or_scatter` stays zero-copy.
        self._usage_master: Optional[np.ndarray] = None
        self._usage: Optional[np.ndarray] = None
        self._usage_version: Optional[int] = None
        self._overhead_master: Optional[np.ndarray] = None
        self._overhead_arr = np.zeros((1, NUM_DIMS), np.int64)
        self._overhead_arr.flags.writeable = False
        self._overhead_version: Optional[int] = None
        self._overhead_full = True  # force first full overhead resync
        # Live-roster row mask over the registry index space: the overhead
        # master zeroes non-live rows so the dense view equals the legacy
        # get_overhead(all_nodes) dict exactly (a deleted node whose pods
        # still exist keeps aggregate rows that the dict never surfaced).
        self._roster_mask: Optional[np.ndarray] = None
        # Rows whose live-mask bit flipped since the last overhead refresh
        # (adds + deletes) — the overhead master re-masks just those.
        self._mask_flips: list = []
        # Availability-input journal (ISSUE 13): epoch -> (usage rows,
        # static rows) changed in that refresh. `_avail_break` bumps the
        # epoch WITHOUT an entry — the solver detects the gap and runs its
        # dense-compare fallback once. `journal_enabled=False` (tests)
        # withholds the journal so the dense oracle path serves every
        # window.
        self._avail_epoch = 0
        self._avail_journal: dict[int, tuple] = {}
        self._pending_arows: list = []  # usage rows (available only)
        self._pending_orows: list = []  # overhead rows (avail+schedulable)
        self._pending_nrows: list = []  # node/roster rows (all statics)
        self.journal_enabled = True
        # Instrumentation — the O(changed) claim as counters, consumed by
        # the tier-1 budget test, the CI scale smoke and the featurize
        # telemetry gauges. `array_grows` counts capacity reallocations of
        # the resident buffers (amortized growth: zero across an ADD
        # burst that stays inside the bucket).
        self.snapshots = 0
        self.roster_rebuilds = 0
        self.roster_patches = 0
        self.roster_add_patches = 0
        self.roster_delete_patches = 0
        self.usage_refreshes = 0
        self.usage_patches = 0
        self.overhead_refreshes = 0
        self.overhead_patches = 0
        self.array_grows = 0
        overhead_computer.attach_registry(registry)
        # Node events only mark the roster dirty (O(1)); the next snapshot
        # pays ONE refresh for the whole burst — a patch (O(changed) dict
        # update + tuple rebuild) when the burst was updates of known
        # nodes, the full O(nodes) re-list otherwise.
        backend.subscribe(
            "nodes",
            on_add=self._on_node_add,
            on_update=self._on_node_update,
            on_delete=self._on_node_delete,
        )

    # -- events ---------------------------------------------------------------

    def _on_node_delete(self, node=None, *_args) -> None:
        """Node DELETEs ride the patch path too (ISSUE 12 satellite: a
        single deleted node used to trigger the full re-list + re-intern
        + arena walk): the deleted Node is captured here, and the next
        snapshot swap-removes it from the roster structures and clears
        its live-mask row in O(changed) — the registry row tombstones
        (the solver recycles it via the delta-statics journal once its
        usage drains). Unknown names are racy: full rebuild."""
        with self._lock:
            self._roster_dirty = True
            if self._dirty_full:
                return
            name = getattr(node, "name", None)
            if name is None:
                self._dirty_full = True
            elif name in self._dirty_adds:
                # Added then deleted within one burst: net no-op.
                del self._dirty_adds[name]
            elif name in self._dirty_deletes:
                pass  # duplicate delivery of a pending delete: no-op
            elif name in self._node_pos:
                self._dirty_updates.pop(name, None)
                self._dirty_deletes[name] = node
            else:
                self._dirty_full = True

    def _on_node_add(self, new) -> None:
        """Node ADDs ride their own patch path (ISSUE 11 satellite: a
        single added node used to trigger the full re-list + re-intern):
        the added Node object is captured here, and the next snapshot
        APPENDS it — roster tuple, name maps, registry row, live mask —
        in O(changed), never re-walking the existing roster. A name we
        already track arriving as an "add" is a racy replay: full rebuild."""
        with self._lock:
            self._roster_dirty = True
            if not self._dirty_full:
                if new.name in self._node_pos or new.name in self._dirty_adds:
                    self._dirty_full = True
                else:
                    self._dirty_adds[new.name] = new

    def _on_node_update(self, _old, new) -> None:
        with self._lock:
            self._roster_dirty = True
            if not self._dirty_full:
                if new.name in self._dirty_deletes:
                    # Deleted then touched again within one burst: racy
                    # replay — rebuild.
                    self._dirty_full = True
                elif new.name in self._dirty_adds:
                    # Added then updated within one burst: the add entry
                    # carries the newest object.
                    self._dirty_adds[new.name] = new
                elif new.name in self._node_pos:
                    self._dirty_updates[new.name] = new
                else:
                    self._dirty_full = True  # unknown name: racy — rebuild

    # -- snapshot -------------------------------------------------------------

    def snapshot(self) -> FeatureSnapshot:
        with self._lock:
            self.snapshots += 1
            self._refresh_roster()
            usage = self._refresh_usage()
            self._refresh_overhead()
            self._avail_commit()
            hint = self._dirty_hint
            self._dirty_hint = None  # one consumer, one hand-off
            return FeatureSnapshot(
                epoch=self._epoch,
                statics_epoch=self._statics_epoch,
                nodes_version=self._roster_topo,
                nodes=self._nodes,
                by_name=self._by_name,
                usage=usage,
                overhead=self._overhead_arr,
                roster_rows=self._roster_rows_view(),
                dirty_hint=hint,
                avail_epoch=(
                    self._avail_epoch if self.journal_enabled else None
                ),
                avail_journal=(
                    self._avail_journal if self.journal_enabled else None
                ),
            )

    # -- availability-input journal (ISSUE 13) --------------------------------

    def _avail_break(self) -> None:
        """A refresh could not name its changed rows: bump the epoch with
        NO journal entry — the solver's next resident build detects the
        gap and runs its dense-compare fallback once."""
        self._avail_epoch += 1
        self._avail_journal.clear()
        self._pending_arows = []
        self._pending_orows = []
        self._pending_nrows = []

    def _avail_commit(self) -> None:
        """Fold this snapshot's named row changes into one journal epoch."""
        if not (
            self._pending_arows or self._pending_orows or self._pending_nrows
        ):
            return

        def _fold(parts):
            return (
                np.unique(np.concatenate(parts))
                if parts
                else np.empty(0, np.int64)
            )

        arows = _fold(self._pending_arows)
        orows = _fold(self._pending_orows)
        nrows = _fold(self._pending_nrows)
        self._pending_arows = []
        self._pending_orows = []
        self._pending_nrows = []
        self._avail_epoch += 1
        self._avail_journal[self._avail_epoch] = (arows, orows, nrows)
        while len(self._avail_journal) > 64:
            self._avail_journal.pop(next(iter(self._avail_journal)))

    # -- resident-buffer sizing (ISSUE 13 amortized growth) -------------------

    def _master_len(self) -> int:
        return _bucket(max(self._registry.capacity, 1), 8)

    def _new_roster_buf(self, n: int) -> np.ndarray:
        return np.empty(_bucket(max(n, 8), 8), np.int32)

    def _roster_rows_view(self) -> Optional[np.ndarray]:
        n = len(self._nodes)
        v = self._roster_view
        if v is None or v.shape[0] != n or v.base is not self._roster_buf:
            v = self._roster_buf[:n].view()
            v.flags.writeable = False
            self._roster_view = v
        return v

    def _ensure_mask(self) -> np.ndarray:
        need = self._master_len()
        mask = self._roster_mask
        if mask is None or mask.shape[0] < need:
            grown = np.zeros(need, dtype=bool)
            if mask is not None:
                grown[: mask.shape[0]] = mask
                self.array_grows += 1
            self._roster_mask = mask = grown
        return mask

    def _refresh_roster(self) -> None:
        """Refresh the roster only when a node event (or an unobserved
        backend version move) says it drifted.

        UPDATE-ONLY bursts (the common node event: heartbeat flips,
        capacity drift) take the PATCH path: the changed Node objects were
        captured by the event subscription, so the roster tuple and
        name->Node map are copied and patched in O(nodes) memcpy +
        O(changed) dict writes — no backend re-list, no re-intern, and the
        registry-row array / live-row mask carry over unchanged (the name
        set is identical). The solver gets the changed objects as
        `dirty_hint` so its native-arena sync upserts just those rows.

        Adds, deletes, unknown names, or a racing version take the full
        rebuild: version captured BEFORE the list and re-checked after — a
        concurrent mutation can only make the roster look stale (one extra
        walk next snapshot), never fresh over an unsynced list. This is
        the single owner of that dance."""
        topo = getattr(self._backend, "nodes_version", None)
        if not (
            self._roster_dirty or topo is None or topo != self._roster_topo
        ):
            return
        if self._dirty_deletes and self._tombstones >= max(
            64, len(self._nodes) // 8
        ):
            # Tombstone-ratio threshold: too many deleted-but-interned
            # rows accumulated — pay ONE full rebuild to re-compact the
            # roster structures instead of patching forever.
            self._dirty_full = True
            self._tombstones = 0
        can_patch = (
            not self._dirty_full
            and (
                self._dirty_updates
                or self._dirty_adds
                or self._dirty_deletes
            )
            and topo is not None
            and self._roster_topo is not None
        )
        if can_patch:
            prev = self._roster_topo
            updates = self._dirty_updates
            adds = self._dirty_adds
            deletes = self._dirty_deletes
            self._dirty_updates = {}
            self._dirty_adds = {}
            self._dirty_deletes = {}
            # Store-owned roster structures, patched IN PLACE (ISSUE 13
            # amortized growth): an update assigns its position, an add
            # appends — no O(nodes) list/dict copy per event. Only a
            # delete burst pays one copy-on-write of the list + row
            # buffer (stale snapshots keep positional integrity) before
            # swap-removing on the owned copies.
            nodes = self._nodes
            by_name = self._by_name
            pos = self._node_pos
            if updates:
                upd_rows = np.asarray(
                    [self._roster_buf[pos[name]] for name in updates],
                    np.int64,
                )
                for name, node in updates.items():
                    nodes[pos[name]] = node
                    by_name[name] = node
                self._pending_nrows.append(upd_rows)
            if deletes:
                # DELETE patch (ISSUE 12/13, O(changed) + one COW):
                # swap-remove each deleted node (the last roster entry
                # fills its hole, so only ONE position shifts per
                # delete), clear its live-mask row (the overhead master
                # re-masks just the flipped rows), and drop its registry
                # row from the roster buffer — the row itself stays
                # interned as a TOMBSTONE until the solver recycles it.
                # The existing roster is never re-listed or re-interned,
                # and the old per-delete np.array(...) full copy is gone.
                # The list, row buffer AND by-name map all copy-on-write
                # ONCE per burst: an in-flight window's ticket parks the
                # old snapshot's structures across its dispatch->complete
                # gap and indexes by_name with dispatch-time names — an
                # in-place pop would KeyError its completion.
                nodes = self._nodes = list(nodes)
                by_name = self._by_name = dict(by_name)
                n = len(nodes)
                buf = self._new_roster_buf(n)
                buf[:n] = self._roster_buf[:n]
                self._roster_buf = buf
                mask = self._ensure_mask()
                del_rows: list[int] = []
                for name in deletes:
                    i = pos.pop(name)
                    by_name.pop(name, None)
                    last = len(nodes) - 1
                    row = int(buf[i])
                    if i != last:
                        nodes[i] = nodes[last]
                        buf[i] = buf[last]
                        pos[nodes[i].name] = i
                    nodes.pop()
                    if 0 <= row < mask.shape[0]:
                        mask[row] = False
                    del_rows.append(row)
                flips = np.asarray(del_rows, np.int64)
                self._mask_flips.append(flips)
                self._pending_nrows.append(flips)
                self._tombstones += len(deletes)
                self.roster_delete_patches += 1
            if adds:
                # APPEND path (node-ADD, O(changed) amortized): new names
                # intern in one bulk call and append into the
                # preallocated roster buffer / live mask — growth is
                # bucketed doubling, so a burst reallocates nothing
                # (array_grows counts the exceptions).
                start = len(nodes)
                for name, node in adds.items():
                    pos[name] = len(nodes)
                    nodes.append(node)
                    by_name[name] = node
                new_rows = self._registry.intern_many(list(adds))
                n = len(nodes)
                if n > self._roster_buf.shape[0]:
                    buf = self._new_roster_buf(n)
                    buf[:start] = self._roster_buf[:start]
                    self._roster_buf = buf
                    self.array_grows += 1
                self._roster_buf[start:n] = new_rows
                mask = self._ensure_mask()
                mask[new_rows] = True
                flips = new_rows.astype(np.int64)
                self._mask_flips.append(flips)
                self._pending_nrows.append(flips)
                self.roster_add_patches += 1
            self._roster_view = None  # length moved: re-slice on demand
            self._roster_topo = topo
            self._roster_dirty = False
            # 3-tuple since ISSUE 12: (base version, changed Nodes,
            # deleted names) — consumers that predate deletes index [0]
            # and [1] unchanged.
            self._dirty_hint = (
                prev,
                tuple(updates.values()) + tuple(adds.values()),
                tuple(deletes),
            )
            self._statics_epoch += 1
            self._epoch += 1
            self.roster_patches += 1
            return
        nodes = self._backend.list_nodes()
        topo_after = getattr(self._backend, "nodes_version", None)
        self._nodes = list(nodes)
        self._by_name = {n.name: n for n in nodes}
        self._node_pos = {n.name: i for i, n in enumerate(nodes)}
        raced = topo is None or topo != topo_after
        self._roster_topo = None if raced else topo
        self._roster_dirty = raced
        self._dirty_full = raced
        self._dirty_updates = {}
        self._dirty_adds = {}
        self._dirty_deletes = {}
        self._tombstones = 0
        self._dirty_hint = None
        # Rebuild the live-row mask (we are already on the O(nodes) path)
        # and force the overhead master's full resync against it. One bulk
        # intern instead of a lock acquire per name. The journal breaks:
        # a re-list cannot name which rows drifted.
        rows = self._registry.intern_many([n.name for n in nodes])
        n = len(nodes)
        buf = self._new_roster_buf(n)
        buf[:n] = rows
        self._roster_buf = buf
        self._roster_view = None
        mask = np.zeros(self._master_len(), dtype=bool)
        mask[rows] = True
        self._roster_mask = mask
        self._mask_flips = []
        self._overhead_full = True
        self._avail_break()
        self._statics_epoch += 1
        self._epoch += 1
        self.roster_rebuilds += 1

    def _refresh_usage(self):
        tracker = self._rrm.usage_tracker
        if tracker is None:
            # No tracker attached (legacy wiring): the map fallback has no
            # version to key on, so every snapshot is a fresh walk — and
            # the journal cannot name rows.
            self._epoch += 1
            self._avail_break()
            return self._rrm.reserved_usage()
        need = self._master_len()
        master = self._usage_master
        if (
            master is not None
            and master.shape[0] == need
            and tracker.version == self._usage_version
        ):
            return self._usage
        version, rows, vals = tracker.collect_delta()
        if master is None or rows is None or master.shape[0] != need:
            # Full resync: cold start, a tracker rebuild, or capacity
            # growth past the master's bucket (counted as a realloc).
            arr = tracker.array(min_rows=need)
            if arr.shape[0] != need:
                arr = np.ascontiguousarray(arr[:need])
            if master is not None and master.shape[0] != need:
                self.array_grows += 1
            self._usage_master = arr
            view = arr.view()
            view.flags.writeable = False
            self._usage = view
            self._avail_break()
            self.usage_refreshes += 1
        elif rows.size:
            # O(changed): scatter the tracker's named dirty rows into the
            # resident master and journal them for the solver's build.
            inside = rows < need
            rows = rows[inside]
            master[rows] = vals[inside]
            self._pending_arows.append(rows)
            self.usage_patches += 1
        self._usage_version = version
        self._epoch += 1
        return self._usage

    def _refresh_overhead(self) -> None:
        need = self._master_len()
        master = self._overhead_master
        if (
            master is not None
            and master.shape[0] == need
            and not self._overhead_full
            and not self._mask_flips
            and self._overhead.overhead_version == self._overhead_version
        ):
            return
        version, rows, vals = self._overhead.collect_delta()
        mask = self._ensure_mask()
        if (
            master is None
            or rows is None
            or master.shape[0] != need
            or self._overhead_full
        ):
            # Full resync: cold start, an overhead-mirror rebuild, a
            # roster re-list, or capacity growth past the bucket.
            _, arr = self._overhead.overhead_snapshot()
            full = np.zeros((need, NUM_DIMS), np.int64)
            r = min(arr.shape[0], need)
            full[:r] = arr[:r]
            full[~mask[:need]] = 0
            if master is not None and master.shape[0] != need:
                self.array_grows += 1
            self._overhead_master = full
            view = full.view()
            view.flags.writeable = False
            self._overhead_arr = view
            self._mask_flips = []
            self._overhead_full = False
            self._avail_break()
            self.overhead_refreshes += 1
        else:
            # O(changed): the mirror's named dirty rows plus any live-mask
            # flips (node add/delete) re-mask and scatter in place.
            flips = self._mask_flips
            self._mask_flips = []
            parts = ([rows] if rows.size else []) + flips
            if not parts:
                if version == self._overhead_version:
                    return
                rows_all = np.empty(0, np.int64)
            elif not flips:
                # Common case: mirror dirt only — the values were already
                # copied under the mirror's lock by collect_delta.
                rows_all = rows[rows < need]
                vals = vals[rows < need]
            else:
                rows_all = np.unique(np.concatenate(parts))
                rows_all = rows_all[rows_all < need]
                vals = self._overhead.dense_values(rows_all)
            if rows_all.size:
                vals[~mask[rows_all]] = 0
                master[rows_all] = vals
                self._pending_orows.append(rows_all)
                self.overhead_patches += 1
        self._overhead_version = version
        self._epoch += 1
        # Overhead feeds `schedulable = allocatable - overhead`, a
        # STATIC field of the cluster tensors: an overhead change must
        # invalidate the solver's statics-epoch skip (back to the
        # array compare / static row-delta, which sees the schedulable
        # drift) or the device would score efficiencies against a stale
        # schedulable tensor.
        self._statics_epoch += 1

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "snapshots": self.snapshots,
                "roster_rebuilds": self.roster_rebuilds,
                "roster_patches": self.roster_patches,
                "roster_add_patches": self.roster_add_patches,
                "roster_delete_patches": self.roster_delete_patches,
                "tombstones": self._tombstones,
                "usage_refreshes": self.usage_refreshes,
                "usage_patches": self.usage_patches,
                "overhead_refreshes": self.overhead_refreshes,
                "overhead_patches": self.overhead_patches,
                "array_grows": self.array_grows,
                "avail_epoch": self._avail_epoch,
                "nodes": len(self._nodes),
                "statics_epoch": self._statics_epoch,
            }
