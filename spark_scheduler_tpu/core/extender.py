"""SparkSchedulerExtender — the gang-admission predicate.

Rebuilds internal/extender/resource.go:59-639. The Predicate contract is the
kube-scheduler extender protocol: given a pod + candidate node names, return
the one node the pod should land on, or a per-node failure map. Driver
requests perform gang admission (FIFO-aware fit of the whole application
through the placement kernels, durable reservation creation on success);
executor requests walk the binding ladder (already-bound / unbound /
reschedule / soft reservation).

Outcome strings match the reference exactly (resource.go:43-57) so
dashboards keyed on them carry over.
"""

from __future__ import annotations

import dataclasses
import time
from typing import NamedTuple, Optional, Sequence

from spark_scheduler_tpu.models.kube import Pod
from spark_scheduler_tpu.core.binpacker import Binpacker
from spark_scheduler_tpu.core.demands import DemandManager
from spark_scheduler_tpu.core.feature_store import HostFeatureStore
from spark_scheduler_tpu.core.lru import LRUCache
from spark_scheduler_tpu.core.overhead import OverheadComputer
from spark_scheduler_tpu.core.reservation_manager import (
    ReservationError,
    ResourceReservationManager,
)
from spark_scheduler_tpu.core.solver import PlacementSolver, WindowRequest
from spark_scheduler_tpu.core.sparkpods import (
    DRIVER_RESERVATION,
    ROLE_DRIVER,
    ROLE_EXECUTOR,
    SPARK_APP_ID_LABEL,
    SPARK_ROLE_LABEL,
    SparkPodError,
    SparkPodLister,
    find_instance_group,
    pod_matches_node,
    spark_resources,
)

# Outcomes (resource.go:43-57)
FAILURE_UNBOUND = "failure-unbound"
FAILURE_INTERNAL = "failure-internal"
FAILURE_FIT = "failure-fit"
FAILURE_EARLIER_DRIVER = "failure-earlier-driver"
FAILURE_NON_SPARK_POD = "failure-non-spark-pod"
SUCCESS = "success"
SUCCESS_RESCHEDULED = "success-rescheduled"
SUCCESS_ALREADY_BOUND = "success-already-bound"
SUCCESS_SCHEDULED_EXTRA_EXECUTOR = "success-scheduled-extra-executor"

SUCCESS_OUTCOMES = frozenset(
    {SUCCESS, SUCCESS_RESCHEDULED, SUCCESS_ALREADY_BOUND, SUCCESS_SCHEDULED_EXTRA_EXECUTOR}
)

LEADER_ELECTION_INTERVAL_S = 15.0  # resource.go:54-57

# `DRIVER_RESERVATION` lives in models.reservations; re-exported through
# sparkpods for core-layer convenience.


class _DomainNames(list):
    """A memoized affinity-domain name list with an O(1) identity digest —
    the in-process analog of server/ingest.NativeNodeNames. The domain
    cache reuses ONE object per (selector signature, topology version), so
    keying the solver's candidate-mask LRU and the window dispatch's
    domain memo on `names_digest` makes every steady-state lookup O(1)
    where tuple-keying hashed (and first built a tuple of) every name —
    a measured per-window O(N) host cost at the million-node tier.

    `patch_base`/`patch_added`/`patch_removed` (ISSUE 13) record this
    ticket's LINEAGE when the domain cache patched membership through a
    node-event hint: the solver's candidate-mask patch follows the chain
    and applies the exact deltas instead of re-walking every name — the
    O(N) mask rebuild per node ADD that dominated the 1M add budget.
    The solver bounds the chain walk and clears the back-reference once
    it re-bases, so chains stay one-or-two links in practice."""

    __hash__ = object.__hash__

    patch_base = None
    patch_added: tuple = ()
    patch_removed: frozenset = frozenset()

    def __eq__(self, other):
        return self is other

    def __ne__(self, other):
        return self is not other

    @property
    def names_digest(self) -> int:
        return id(self)


class ExtenderArgs(NamedTuple):
    """schedulerapi.ExtenderArgs: the pod + kube-scheduler's candidates."""

    pod: Pod
    node_names: list[str]


class ExtenderFilterResult(NamedTuple):
    """schedulerapi.ExtenderFilterResult."""

    node_names: list[str]
    failed_nodes: dict[str, str]
    outcome: str

    @property
    def ok(self) -> bool:
        return bool(self.node_names)


@dataclasses.dataclass
class FifoConfig:
    """config.FifoConfig (config/config.go:57-64): age gate before an
    unschedulable earlier driver BLOCKS later drivers."""

    enforce_after_pod_age_s: float = 0.0
    enforce_after_pod_age_by_instance_group: dict[str, float] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class ExtenderConfig:
    fifo: bool = False
    fifo_config: FifoConfig = dataclasses.field(default_factory=FifoConfig)
    instance_group_label: str = "instance-group"
    schedule_dynamically_allocated_executors_in_same_az: bool = False
    # One batched device solve per driver request (FIFO prefix + current
    # app, solver.pack_window) instead of a pack per earlier driver. All six
    # binpack strategies batch (solver.BATCHABLE_STRATEGIES). The batched
    # path sorts node orders ONCE per request like the reference
    # (resource.go:299); the sequential fallback (False) re-sorts after
    # each earlier driver's hypothetical placement, so the two paths can
    # pick different (both valid) nodes when FIFO subtractions reorder ties.
    batched_admission: bool = True
    # Request-gap resync threshold (`extender.resync-gap-seconds`): a gap
    # longer than this means the leader probably changed, so durable state
    # is resynced from observed pods before serving (resource.go:191-202).
    # Redundant — and skipped — while a real HA lease is held (see
    # SparkSchedulerExtender.ha_lease); float("inf") disables it outright
    # (sharded-group members, where the lease holder owns reconciliation).
    resync_gap_seconds: float = LEADER_ELECTION_INTERVAL_S


class WindowTicket:
    """A serving window between its dispatch and complete phases
    (predicate_window_dispatch / predicate_window_complete)."""

    __slots__ = (
        "args_list", "results", "roles", "timer_start", "window", "handle",
        "all_nodes", "by_name", "domains", "inflight_keys", "sync", "done",
        "epoch", "featurize_ms", "featurize_phases", "solve_started",
        "trace_wid",
    )

    def __init__(self, args_list):
        self.args_list = args_list
        self.results = None
        self.roles = None
        self.timer_start = 0.0
        self.window = []  # (arg index, pod, app_resources, args)
        self.handle = None  # solver WindowHandle when a window was dispatched
        self.all_nodes = []
        self.by_name = {}
        self.domains = {}
        self.inflight_keys = []
        self.sync = False  # single request: serve via the solo predicate()
        self.done = False  # results already final (e.g. reconcile failure)
        # Extender capacity epoch at dispatch: if a solo-path admission
        # changed capacity while this window was in flight, its device
        # decisions are stale and the complete phase re-solves serially.
        self.epoch = -1
        # Flight-recorder phase anchors: host featurize cost of the window
        # dispatch (with its sub-phase breakdown: snapshot / tensors /
        # domains / fifo), and the wall time the device solve started (the
        # complete phase's fetch closes the solve interval).
        self.featurize_ms = 0.0
        self.featurize_phases: dict[str, float] = {}
        self.solve_started = 0.0
        # Trace journal window id (replay/trace.TraceWriter): set when a
        # trace sink journaled this ticket's dispatch; the complete phase
        # journals its results under the same id. None = not journaled
        # (no sink, or a sync ticket — the solo path self-journals).
        self.trace_wid = None


class SparkSchedulerExtender:
    def __init__(
        self,
        backend,
        pod_lister: SparkPodLister,
        reservation_manager: ResourceReservationManager,
        demand_manager: DemandManager,
        overhead_computer: OverheadComputer,
        binpacker: Binpacker,
        solver: PlacementSolver,
        config: ExtenderConfig,
        reconciler=None,
        metrics=None,
        events=None,
        waste=None,
        recorder=None,
        clock=time.time,
        policy=None,
    ):
        self._backend = backend
        self._pod_lister = pod_lister
        self._rrm = reservation_manager
        self._demands = demand_manager
        self._overhead = overhead_computer
        self.binpacker = binpacker
        self._solver = solver
        self._config = config
        self._reconciler = reconciler
        self._metrics = metrics
        self._events = events
        self._waste = waste
        # Scheduling flight recorder (observability/recorder.py): every
        # decision below appends one explainable DecisionRecord.
        self._recorder = recorder
        # Policy engine (policy/engine.py) — None keeps every hook below on
        # the exact pre-policy branch (the FIFO byte-identity contract).
        self._policy = policy
        self._clock = clock
        self._last_request: float = 0.0
        # HA lease handle (ha/lease.LeaseManager), set by the replica
        # runtime: while the lease is HELD, the >gap "leader probably
        # changed" heuristic below is redundant (no silent leader change
        # can have happened — a takeover revokes the lease) and skipped.
        self.ha_lease = None
        # Apps whose gang admission is DISPATCHED but not yet applied (a
        # pipelined window in flight). A later window must not re-admit
        # them; their requests fall through to the solo loop of their own
        # window's complete phase, which runs after the prior window
        # applied — the idempotent-retry branch then returns the reserved
        # node (resource.go:273-286).
        self._inflight_apps: set[tuple[str, str]] = set()
        # Affinity-domain memo across windows: (selector/affinity
        # signature) -> (backend nodes_version, matching node names). The
        # O(nodes) pod_matches_node walk was a measured per-window hotspot
        # at 10k nodes even though serving workloads reuse a handful of
        # selector shapes; invalidated by the node-mutation counter, and
        # LRU-evicting so a 65th live signature keeps the 64 hottest
        # instead of wiping them all.
        self._domain_cache: LRUCache = LRUCache(64)
        # Event-sourced host feature store: the single featurize read of
        # every serving path (roster + by-name map + dense usage/overhead,
        # all epoch-versioned, O(changed) per window). Owns the
        # capture-before-list node versioning dance.
        self.features = HostFeatureStore(
            backend, solver.registry, overhead_computer, reservation_manager
        )
        # Bumped by every SOLO-path admission that changes capacity (a solo
        # driver's reservations, an executor reschedule / soft
        # reservation). Windows dispatched before such a change re-solve at
        # complete time instead of applying their stale device decisions —
        # pipelined serving stays decision-equivalent to a serialized
        # order.
        self._capacity_epoch = 0


    # ------------------------------------------------------------------ API
    #
    # Trace capture (ISSUE 17): each public serving entry point is a thin
    # wrapper journaling the request inputs + final results to the
    # recorder's trace sink (replay/trace.TraceWriter). Sink-off cost is
    # one attribute check per call. Window dispatches journal AFTER the
    # dispatch succeeds — PipelineDrainRequired propagates un-journaled,
    # so the caller's drain-and-retry appears in the trace exactly as the
    # serialization the replay engine re-drives (drained results first,
    # then the retried dispatch).

    def _trace_sink(self):
        rec = self._recorder
        return getattr(rec, "sink", None) if rec is not None else None

    def predicate(self, args: ExtenderArgs) -> ExtenderFilterResult:
        tw = self._trace_sink()
        if tw is None:
            return self._predicate_solo(args)
        wid = tw.on_predicate([args], mode="solo")
        res = self._predicate_solo(args)
        tw.on_results(wid, [res])
        return res

    def predicate_window_dispatch(
        self, args_list: Sequence[ExtenderArgs]
    ) -> "WindowTicket":
        t = self._window_dispatch(args_list)
        tw = self._trace_sink()
        if tw is not None and not t.sync and t.trace_wid is None:
            t.trace_wid = tw.on_predicate(t.args_list, mode="window")
        return t

    def predicate_window_complete(
        self, t: "WindowTicket"
    ) -> list[ExtenderFilterResult]:
        results = self._window_complete(t)
        # Sync tickets route through self.predicate() inside
        # _window_complete and self-journal there.
        if t.trace_wid is not None:
            tw = self._trace_sink()
            if tw is not None:
                tw.on_results(t.trace_wid, results)
        return results

    def predicate_windows_dispatch(
        self, args_lists: Sequence[Sequence[ExtenderArgs]]
    ) -> "list[WindowTicket]":
        tickets = self._windows_dispatch(args_lists)
        tw = self._trace_sink()
        if tw is not None:
            # Each fused sub-window journals as its own window dispatch,
            # in claim order — replaying them as sequential pipelined
            # dispatches is decision-equivalent by the fused==sequential
            # pin. The len==1 path delegated to the public
            # predicate_window_dispatch and already journaled.
            for t in tickets:
                if not t.sync and t.trace_wid is None:
                    t.trace_wid = tw.on_predicate(t.args_list, mode="window")
        return tickets

    def _predicate_solo(self, args: ExtenderArgs) -> ExtenderFilterResult:
        from spark_scheduler_tpu.tracing import tracer

        pod = args.pod
        role = pod.labels.get(SPARK_ROLE_LABEL, "")
        timer_start = self._clock()

        try:
            self._reconcile_if_needed()
        except Exception as exc:  # failure to rebuild state is internal
            msg = f"failed to reconcile: {exc}"
            self._record_decision(
                pod, role, FAILURE_INTERNAL, None, args.node_names, msg
            )
            return self._fail(args, FAILURE_INTERNAL, msg)
        self._rrm.compact_dynamic_allocation_applications()

        ctx: dict = {}
        with tracer().span(
            "select-node", role=role or "unknown", pod=f"{pod.namespace}/{pod.name}"
        ) as sp:
            node, outcome, message = self._select_node(
                role, pod, args.node_names, ctx=ctx
            )
            sp.tag("outcome", outcome)

        if self._metrics is not None:
            self._metrics.mark_schedule_outcome(
                pod, role, outcome, self._clock() - timer_start
            )
        self._record_decision(
            pod, role, outcome, node, args.node_names, message, ctx=ctx
        )
        if node is None:
            return self._fail(args, outcome, message or outcome)
        return ExtenderFilterResult(node_names=[node], failed_nodes={}, outcome=outcome)

    def predicate_batch(
        self, args_list: Sequence[ExtenderArgs]
    ) -> list[ExtenderFilterResult]:
        """Serve a WINDOW of coalesced predicate calls (VERDICT r2 #1).

        The window is serialized as: driver gang admissions first (one
        `pack_window` device program, each request a segment with exact
        solo-solve semantics — decisions identical to serving those drivers
        one at a time in list order), then executor/non-spark requests in
        list order against the reservations the window just created. All
        window requests arrived concurrently, so this driver-first order is
        one valid linearization (and the friendliest: an executor whose
        driver is in the same window finds its reservation). Reconciliation
        and soft-reservation compaction run once per window — the window IS
        the serialization point (SURVEY.md §7 "Mutable-state races").

        Synchronous form of the two-phase API: the PIPELINED serving loop
        (server/http.py PredicateBatcher) instead dispatches window k+1
        (predicate_window_dispatch) before completing window k
        (predicate_window_complete), overlapping the next window's host
        build + device dispatch with the previous window's blocking
        decision pull."""
        return self.predicate_window_complete(
            self.predicate_window_dispatch(args_list)
        )

    def _window_dispatch(
        self, args_list: Sequence[ExtenderArgs]
    ) -> "WindowTicket":
        """Phase 1: reconcile/compact, select the driver window, build the
        segmented requests, and DISPATCH the device solve (no blocking
        fetch). May raise solver.PipelineDrainRequired — the caller must
        complete the pending window and retry."""
        t = WindowTicket(args_list)
        if len(args_list) == 1 and (
            args_list[0].pod.labels.get(SPARK_ROLE_LABEL, "") != ROLE_DRIVER
            or not self._config.batched_admission
            or not self._solver.can_batch(self.binpacker.name)
        ):
            # Lone NON-driver request: the solo ladder (host-only, no device
            # solve to overlap). A lone DRIVER stays on the window path
            # below: the solo driver path would bump the capacity epoch
            # (forcing every in-flight window to re-solve) and its ticket
            # would drain the pipeline — one straggler client could
            # serialize the whole serving loop.
            t.sync = True
            return t
        t.timer_start = self._clock()
        try:
            self._reconcile_if_needed()
        except Exception as exc:
            msg = f"failed to reconcile: {exc}"
            for a in args_list:
                self._record_decision(
                    a.pod,
                    a.pod.labels.get(SPARK_ROLE_LABEL, ""),
                    FAILURE_INTERNAL, None, a.node_names, msg,
                )
            t.results = [
                self._fail(a, FAILURE_INTERNAL, msg) for a in args_list
            ]
            t.done = True
            return t
        self._rrm.compact_dynamic_allocation_applications()
        t.results = [None] * len(args_list)
        t.roles = [a.pod.labels.get(SPARK_ROLE_LABEL, "") for a in args_list]
        driver_ids = [i for i, r in enumerate(t.roles) if r == ROLE_DRIVER]
        if (
            driver_ids
            and self._config.batched_admission
            and self._solver.can_batch(self.binpacker.name)
        ):
            self._dispatch_driver_window(t, driver_ids)
        return t

    def _window_complete(
        self, t: "WindowTicket"
    ) -> list[ExtenderFilterResult]:
        """Phase 2: fetch + apply the window decisions (reservations,
        demands, events), then serve everything not window-served
        (executors, non-spark pods, deferred in-flight duplicates, drivers
        when batching is off) on the solo path in arrival order."""
        from spark_scheduler_tpu.tracing import tracer

        if t.sync:
            return [self.predicate(t.args_list[0])]
        if t.done:
            return t.results
        if t.handle is not None and t.epoch != self._capacity_epoch:
            # A solo-path admission changed capacity while this window was
            # in flight: its device decisions could double-book. Discard
            # them and re-solve NOW — every earlier window has applied by
            # this point (completions are FIFO), so a fresh serialized
            # solve sees the full truth. The pipelined device state is
            # dropped with the stale decisions; later in-flight windows
            # detect the same epoch change and re-solve too.
            self._inflight_apps.difference_update(t.inflight_keys)
            self._solver.discard_pipeline()
            # The discard/re-solve is itself a capacity change: the re-solve
            # below may place this window's gangs on different nodes than the
            # (discarded) device decisions a LATER in-flight window's base
            # threads. Bump the epoch so every window dispatched before this
            # discard also re-solves from host truth instead of applying
            # decisions computed against the dropped placements.
            self._capacity_epoch += 1
            redo_ids = [
                i
                for i, r in enumerate(t.roles)
                if r == ROLE_DRIVER and t.results[i] is None
            ]
            t.window = []
            t.handle = None
            t.inflight_keys = []
            t.domains = {}
            if redo_ids:
                # Even a SINGLE invalidated driver redoes on the window
                # path: the solo ladder would bump the epoch again on
                # success, cascading re-solves through every other
                # in-flight window.
                self._dispatch_driver_window(t, redo_ids)
        # One write-back drain for the whole window instead of one per
        # mutation: every result below is only released to its client after
        # this context exits, so durability-before-response is unchanged.
        with self._rrm.rr_cache.deferred_sync(), \
                self._demands.deferred_sync():
            if t.handle is not None:
                self._complete_driver_window(t)
            args_list, results, roles = t.args_list, t.results, t.roles
            # Consecutive executor requests are served as ONE grouped ladder
            # pass + one grouped reschedule solve (_serve_executor_window);
            # a non-executor request between them flushes the run so the
            # arrival-order serialization is preserved.
            run: list[int] = []
            for i, args in enumerate(args_list):
                if results[i] is not None:
                    continue
                if roles[i] == ROLE_EXECUTOR:
                    run.append(i)
                    continue
                if run:
                    self._serve_executor_window(t, run)
                    run = []
                pod = args.pod
                ctx: dict = {}
                with tracer().span(
                    "select-node", role=roles[i] or "unknown",
                    pod=f"{pod.namespace}/{pod.name}",
                ) as sp:
                    node, outcome, message = self._select_node(
                        roles[i], pod, args.node_names, ctx=ctx
                    )
                    sp.tag("outcome", outcome)
                self._mark_outcome(pod, roles[i], outcome, t.timer_start)
                self._record_decision(
                    pod, roles[i], outcome, node, args.node_names, message,
                    ctx=ctx,
                )
                if node is None:
                    results[i] = self._fail(args, outcome, message or outcome)
                else:
                    results[i] = ExtenderFilterResult(
                        node_names=[node], failed_nodes={}, outcome=outcome
                    )
            if run:
                self._serve_executor_window(t, run)
        return results

    def _windows_dispatch(
        self, args_lists: Sequence[Sequence[ExtenderArgs]]
    ) -> "list[WindowTicket]":
        """Phase 1 of a FUSED K-window serve (the PredicateBatcher's
        fused claim, `solver.fuse-windows` > 1): reconcile/compact ONCE,
        take ONE feature-store snapshot + pipelined tensor build, stage
        every sub-window's driver requests, and dispatch them all in ONE
        fused device program (solver.pack_windows_dispatch) whose
        committed base carries on-device between the sub-windows — one
        h2d + one dispatch + one d2h where K sequential windows pay K
        round trips. Returns one ticket per sub-window; complete each IN
        ORDER via predicate_window_complete (the first completion pays
        the single decision pull, the rest are free).

        Decision-equivalent to dispatching the K windows sequentially
        back-to-back: the sub-windows were claimed at one instant, so no
        external state lands between them in either serialization, the
        in-flight app dedup threads across sub-windows exactly as
        _inflight_apps does across pipelined dispatches, and the shared
        FIFO pending scan sees the same backend state each sequential
        dispatch would. May raise PipelineDrainRequired BEFORE any ticket
        state is committed — the caller completes pending windows and
        retries the whole claim."""
        if len(args_lists) == 1:
            return [self.predicate_window_dispatch(args_lists[0])]
        tickets = [WindowTicket(a) for a in args_lists]
        can_window = (
            self._config.batched_admission
            and self._solver.can_batch(self.binpacker.name)
        )
        for t in tickets:
            if len(t.args_list) == 1 and (
                t.args_list[0].pod.labels.get(SPARK_ROLE_LABEL, "")
                != ROLE_DRIVER
                or not can_window
            ):
                # Same shortcut as predicate_window_dispatch: a lone
                # NON-driver sub-window serves on the solo ladder.
                t.sync = True
        live = [t for t in tickets if not t.sync]
        if not live:
            return tickets
        timer_start = self._clock()
        try:
            self._reconcile_if_needed()
        except Exception as exc:
            msg = f"failed to reconcile: {exc}"
            for t in live:
                for a in t.args_list:
                    self._record_decision(
                        a.pod,
                        a.pod.labels.get(SPARK_ROLE_LABEL, ""),
                        FAILURE_INTERNAL, None, a.node_names, msg,
                    )
                t.results = [
                    self._fail(a, FAILURE_INTERNAL, msg) for a in t.args_list
                ]
                t.done = True
            return tickets
        self._rrm.compact_dynamic_allocation_applications()
        for t in live:
            t.timer_start = timer_start
            t.results = [None] * len(t.args_list)
            t.roles = [
                a.pod.labels.get(SPARK_ROLE_LABEL, "") for a in t.args_list
            ]
        if not can_window:
            return tickets
        driver_ids_of = {
            id(t): [i for i, r in enumerate(t.roles) if r == ROLE_DRIVER]
            for t in live
        }
        if not any(driver_ids_of.values()):
            # No driver anywhere in the claim (executor-heavy burst):
            # nothing will dispatch, so skip the shared featurize — the
            # sequential path gates the same way on driver_ids, and a
            # spurious PipelineDrainRequired here would drain the whole
            # pipeline for a claim that needed no device work.
            return tickets
        # Shared featurize: ONE snapshot + ONE pipelined build (the only
        # raise site — PipelineDrainRequired propagates before any ticket
        # commits state) + ONE FIFO pending-driver scan for the whole
        # fused claim. The shared phase costs are attributed to the
        # sub-windows in equal shares — amortization is the point.
        featurize_start = self._clock()
        snap = self.features.snapshot()
        t_snap = self._clock()
        snapshot_ms = (t_snap - featurize_start) * 1e3
        tensors = self._solver.build_tensors_pipelined(
            snap.nodes, snap.usage, snap.overhead,
            topo_version=snap.nodes_version,
            statics_version=snap.statics_epoch,
            roster_rows=snap.roster_rows,
            dirty_hint=snap.dirty_hint,
            avail_epoch=snap.avail_epoch,
            avail_journal=snap.avail_journal,
        )
        t_tensors = self._clock()
        tensors_ms = (t_tensors - t_snap) * 1e3
        pending_supplier = self._pending_driver_supplier()
        share = max(1, len(live))
        seen_apps: set[tuple[str, str]] = set(self._inflight_apps)
        staged: list[tuple[WindowTicket, list[WindowRequest]]] = []
        for t in live:
            t.featurize_phases["featurize_snapshot_ms"] = snapshot_ms / share
            t.featurize_phases["featurize_tensors_ms"] = tensors_ms / share
            driver_ids = driver_ids_of[id(t)]
            if not driver_ids:
                continue
            requests = self._stage_driver_window(
                t, driver_ids, snap, seen_apps, pending_supplier
            )
            if requests:
                staged.append((t, requests))
        if staged:
            solve_started = self._clock()
            views = self._solver.pack_windows_dispatch(
                self.binpacker.name, tensors, [r for _, r in staged]
            )
            for (t, _), view in zip(staged, views):
                t.solve_started = solve_started
                t.handle = view
                self._mark_window_inflight(t)
        return tickets

    def _parse_pending_drivers(self) -> list[tuple]:
        """FIFO predecessor scan: one backend list + one annotation parse
        per pending driver, shared by every request of a window (and by
        every sub-window of a fused claim — each request then filters the
        shared snapshot, sparkpods.go:51-77 semantics unchanged)."""
        out: list[tuple] = []
        ig_label = self._pod_lister.instance_group_label
        for ed in self._pod_lister.list_pending_drivers():
            try:
                ed_res = spark_resources(ed)
            except SparkPodError:
                continue  # unparseable driver skipped (resource.go:228-233)
            out.append(
                (
                    ed,
                    find_instance_group(ed, ig_label),
                    ed_res,
                    self._should_skip_driver_fifo(ed),
                )
            )
        return out

    def _pending_driver_supplier(self):
        """LAZY, memoized form of _parse_pending_drivers for window
        staging: the O(pending-drivers) scan runs at most once per
        dispatch (shared across a fused claim's sub-windows) and ONLY when
        some sub-window actually stages a driver request — a window whose
        members all dedup away (in-flight duplicates, idempotent retries)
        costs nothing, as before the fused refactor. FIFO-off returns []
        for free."""
        memo: dict = {}

        def supply() -> list[tuple]:
            if "rows" not in memo:
                memo["rows"] = (
                    self._parse_pending_drivers() if self._config.fifo else []
                )
            return memo["rows"]

        return supply

    def _mark_window_inflight(self, t: WindowTicket) -> None:
        t.epoch = self._capacity_epoch
        t.inflight_keys = [
            (pod.namespace, pod.labels.get(SPARK_APP_ID_LABEL, ""))
            for _, pod, _, _ in t.window
        ]
        self._inflight_apps.update(t.inflight_keys)

    def _dispatch_driver_window(self, t: WindowTicket, driver_ids) -> None:
        """Gang-admit every driver request of the window in ONE device solve
        (solver.pack_window_dispatch; fetched in _complete_driver_window).
        Mirrors _select_driver_node's flow per request: idempotent retry,
        FIFO earlier-driver rows, demand lifecycle, reservation creation,
        metrics/events."""
        # Build the device tensors FIRST: build_tensors_pipelined is the
        # only raise site (PipelineDrainRequired), and raising before any
        # outcome is marked lets the serving loop retry the whole dispatch
        # without double-counting metrics or waste attempts.
        # ONE feature-store snapshot replaces the per-window list_nodes +
        # name->node dict + overhead dict + usage copy of the old path:
        # steady state it returns the resident epoch-versioned arrays
        # (O(changed), usually O(1)); the capture-before-list versioning
        # dance lives inside the store.
        featurize_start = self._clock()
        snap = self.features.snapshot()
        phases = t.featurize_phases
        t_snap = self._clock()
        phases["featurize_snapshot_ms"] = (t_snap - featurize_start) * 1e3
        # Device-resident state threaded ACROSS windows: the previous
        # window's committed base (still on device) plus additive external
        # deltas — what makes dispatch-before-fetch pipelining exact
        # (solver.build_tensors_pipelined). The statics epoch lets the
        # builder skip its per-window static-field array compares.
        tensors = self._solver.build_tensors_pipelined(
            snap.nodes, snap.usage, snap.overhead,
            topo_version=snap.nodes_version,
            statics_version=snap.statics_epoch,
            roster_rows=snap.roster_rows,
            dirty_hint=snap.dirty_hint,
            avail_epoch=snap.avail_epoch,
            avail_journal=snap.avail_journal,
        )
        phases["featurize_tensors_ms"] = (self._clock() - t_snap) * 1e3
        requests = self._stage_driver_window(
            t, driver_ids, snap, set(self._inflight_apps),
            self._pending_driver_supplier(),
        )
        if not requests:
            return
        t.solve_started = self._clock()
        t.handle = self._solver.pack_window_dispatch(
            self.binpacker.name, tensors, requests
        )
        self._mark_window_inflight(t)

    def _stage_driver_window(
        self, t: WindowTicket, driver_ids, snap, seen_apps, pending_supplier
    ) -> "list[WindowRequest]":
        """Select the window's members (idempotent retry, in-flight dedup,
        resource parse), match affinity domains, and build the segmented
        WindowRequests — everything of a driver-window dispatch EXCEPT the
        tensor build and the device dispatch, so the fused path can stage
        K sub-windows against one shared snapshot/tensor build.
        `seen_apps` is MUTATED (the fused claim threads one set across its
        sub-windows, exactly as _inflight_apps threads across pipelined
        dispatches); `pending_supplier` is the lazy shared FIFO pending
        scan (_pending_driver_supplier), invoked only once a window is
        known non-empty — its cost lands inside this ticket's fifo
        featurize phase."""
        all_nodes, topo = snap.nodes, snap.nodes_version
        t.all_nodes = all_nodes
        by_name = t.by_name = snap.by_name
        args_list, results, timer_start = t.args_list, t.results, t.timer_start
        phases = t.featurize_phases
        t_stage = self._clock()
        window = t.window
        for i in driver_ids:
            args = args_list[i]
            pod = args.pod
            app_id = pod.labels.get(SPARK_APP_ID_LABEL, "")
            if (pod.namespace, app_id) in seen_apps:
                # Duplicate submission of the same app in one window (client
                # retry) OR an app whose admission is still in flight in a
                # previous pipelined window: leave it for the post-window
                # solo loop — it runs after every prior window applied, so
                # the idempotent-retry branch returns the node the first
                # submission reserved (resource.go:273-286).
                continue
            rr = self._rrm.get_resource_reservation(app_id, pod.namespace)
            if rr is not None:
                # Idempotent retry (resource.go:273-286).
                node = rr.spec.reservations[DRIVER_RESERVATION].node
                self._mark_outcome(pod, ROLE_DRIVER, SUCCESS, timer_start)
                self._record_decision(
                    pod, ROLE_DRIVER, SUCCESS, node, args.node_names
                )
                results[i] = ExtenderFilterResult(
                    node_names=[node], failed_nodes={}, outcome=SUCCESS
                )
                continue
            try:
                res = spark_resources(pod)
            except SparkPodError as exc:
                msg = f"failed to get spark resources: {exc}"
                self._mark_outcome(pod, ROLE_DRIVER, FAILURE_INTERNAL, timer_start)
                self._record_decision(
                    pod, ROLE_DRIVER, FAILURE_INTERNAL, None,
                    args.node_names, msg,
                )
                results[i] = self._fail(args, FAILURE_INTERNAL, msg)
                continue
            seen_apps.add((pod.namespace, app_id))
            window.append((i, pod, res, args))
        if not window:
            return []

        # Domain (node-affinity) matching, deduplicated by affinity
        # signature: requests without selector/affinity — the overwhelmingly
        # common case — share the all-nodes domain (None => pack_window uses
        # every valid node), and identical selectors run the O(nodes)
        # matcher walk once per window instead of once per request. A node
        # event no longer invalidates the cache wholesale (ISSUE 11): an
        # update/add burst PATCHES the cached membership through the
        # snapshot's dirty hint — O(changed) matcher calls — and when
        # membership is unchanged (the common event: capacity drift,
        # cordons; labels untouched) the SAME domain object survives, so
        # the solver's digest-keyed candidate-mask memo keeps hitting.
        domains = t.domains
        hint = snap.dirty_hint
        domain_by_sig: dict[tuple, list[str] | None] = {}
        for i, pod, res, args in window:
            sig = (
                tuple(sorted(pod.node_selector.items())),
                tuple(sorted(
                    (k, tuple(v)) for k, v in pod.node_affinity.items()
                )),
            )
            if sig not in domain_by_sig:
                if not pod.node_selector and not pod.node_affinity:
                    domain_by_sig[sig] = None  # all valid nodes
                else:
                    cached = (
                        self._domain_cache.get(sig)
                        if topo is not None
                        else None
                    )
                    if cached is not None and cached[0] == topo:
                        domain_by_sig[sig] = cached[1]
                    elif (
                        cached is not None
                        and hint is not None
                        and cached[0] == hint[0]
                    ):
                        # Version chain verified: the cache was current as
                        # of the hint's base version, and the hint carries
                        # exactly the nodes changed since.
                        names, name_set = cached[1], cached[2]
                        added = [
                            n.name
                            for n in hint[1]
                            if n.name not in name_set
                            and pod_matches_node(pod, n)
                        ]
                        removed = {
                            n.name
                            for n in hint[1]
                            if n.name in name_set
                            and not pod_matches_node(pod, n)
                        }
                        # Deleted nodes (hint[2], ISSUE 12): drop them
                        # from the cached membership — a delete no longer
                        # rebuilds the domain cache wholesale.
                        removed |= {
                            nm
                            for nm in (
                                hint[2] if len(hint) > 2 else ()
                            )
                            if nm in name_set
                        }
                        if added or removed:
                            prev_names = names
                            if removed:
                                names = _DomainNames(
                                    nm for nm in names if nm not in removed
                                )
                                names.extend(added)
                                name_set = (name_set - removed) | set(added)
                            else:
                                # Adds-only (the node-ADD burst case): one
                                # pointer copy of the name list, and the
                                # member set grows IN PLACE — rebuilding a
                                # million-entry set per event was the
                                # dominant 1M ADD cost (ISSUE 15). The set
                                # is owned by this cache entry alone, and
                                # the ticket object must still be NEW (its
                                # digest keys the solver's mask memo).
                                names = _DomainNames(names)
                                names.extend(added)
                                name_set.update(added)
                            # Lineage for the solver's candidate-mask
                            # patch (ISSUE 13): the new ticket names its
                            # exact membership deltas so the mask updates
                            # O(changed) instead of re-walking N names.
                            # The solver clears the back-reference once it
                            # re-bases its mask on this ticket, so chains
                            # stay one-or-two links in practice.
                            names.patch_base = prev_names
                            names.patch_added = tuple(added)
                            names.patch_removed = frozenset(removed)
                        domain_by_sig[sig] = names
                        self._domain_cache.put(sig, (topo, names, name_set))
                    else:
                        names = _DomainNames(
                            n.name
                            for n in all_nodes
                            if pod_matches_node(pod, n)
                        )
                        domain_by_sig[sig] = names
                        if topo is not None:
                            self._domain_cache.put(
                                sig, (topo, names, set(names))
                            )
            domains[i] = domain_by_sig[sig]
        t_domains = self._clock()
        phases["featurize_domains_ms"] = (t_domains - t_stage) * 1e3
        # First non-empty window of the dispatch pays the (memoized)
        # pending-driver scan here, inside its fifo phase interval.
        parsed_pending = pending_supplier()

        requests: list[WindowRequest] = []
        kept: list[tuple] = []
        now_policy = self._clock()
        for i, pod, res, args in window:
            rows: list[tuple] = []
            if self._config.fifo:
                group = find_instance_group(
                    pod, self._pod_lister.instance_group_label
                )
                if self._policy is not None:
                    # Policy window ordering (policy/ordering.py): blocker
                    # rows by the configured strategy; a DRF cross-group
                    # yield denies without consuming a solve (disjoint
                    # domains — capacity rows cannot express it).
                    blockers, hard = self._policy.ordering.blockers(
                        pod, group, parsed_pending, now_policy
                    )
                    if hard:
                        msg = (
                            "yielding to instance group with smaller "
                            "dominant share"
                        )
                        self._demands.create_demand_for_application(pod, res)
                        self._mark_outcome(
                            pod, ROLE_DRIVER, FAILURE_EARLIER_DRIVER,
                            timer_start,
                        )
                        self._record_decision(
                            pod, ROLE_DRIVER, FAILURE_EARLIER_DRIVER, None,
                            args.node_names, msg,
                        )
                        results[i] = self._fail(
                            args, FAILURE_EARLIER_DRIVER, msg
                        )
                        continue
                    for _ed, _ed_group, ed_res, ed_skip in blockers:
                        rows.append(
                            (
                                ed_res.driver_resources,
                                ed_res.executor_resources,
                                ed_res.min_executor_count,
                                ed_skip,
                            )
                        )
                else:
                    for ed, ed_group, ed_res, ed_skip in parsed_pending:
                        if not SparkPodLister.is_earlier_driver(
                            ed, ed_group, pod, group
                        ):
                            continue
                        rows.append(
                            (
                                ed_res.driver_resources,
                                ed_res.executor_resources,
                                ed_res.min_executor_count,
                                ed_skip,
                            )
                        )
            rows.append(
                (
                    res.driver_resources,
                    res.executor_resources,
                    res.min_executor_count,
                    False,
                )
            )
            kept.append((i, pod, res, args))
            requests.append(
                WindowRequest(
                    rows=rows,
                    driver_candidate_names=args.node_names,
                    domain_node_names=domains[i],
                )
            )
        if len(kept) != len(window):
            window[:] = kept  # t.window stays aligned with `requests`

        now = self._clock()
        phases["featurize_fifo_ms"] = (now - t_domains) * 1e3
        # The window's featurize cost is the sum of its contiguous phases
        # (shared snapshot/tensor costs arrive as the fused claim's equal
        # shares, so fused sub-windows report their amortized featurize).
        t.featurize_ms = sum(phases.values())
        tel = self._solver.telemetry
        if tel is not None:
            tel.on_featurize(phases, self.features)
        return requests

    def _complete_driver_window(self, t: WindowTicket) -> None:
        """Fetch the dispatched window's decisions and apply them:
        reservations, demand lifecycle, events, metrics."""
        from spark_scheduler_tpu.tracing import tracer

        try:
            decisions = self._solver.pack_window_fetch(t.handle)
        finally:
            self._inflight_apps.difference_update(t.inflight_keys)
        # Solve interval for the recorder: device dispatch -> decisions on
        # host. On the pipelined path the blocking pull overlapped other
        # windows' host work, so this is the wall time the WINDOW waited,
        # not pure device time.
        solve_ms = (self._clock() - t.solve_started) * 1e3
        dispatch_info = t.handle.info
        requests = t.handle.requests
        window, results, timer_start = t.window, t.results, t.timer_start
        all_nodes, by_name, domains = t.all_nodes, t.by_name, t.domains
        commit_t0 = self._clock()

        def record(k, pod, args, outcome, node, msg="", extra=None):
            self._record_decision(
                pod, ROLE_DRIVER, outcome, node, args.node_names, msg,
                ctx={
                    **(extra or {}),
                    "featurize_ms": t.featurize_ms,
                    **t.featurize_phases,
                    "solve_ms": solve_ms,
                    # The window-coalesced commit: classification + ONE
                    # batched reservation write-back, measured from the
                    # decisions landing on host to this record.
                    "commit_ms": (self._clock() - commit_t0) * 1e3,
                    # None when FIFO is off (rows then carries only
                    # the request's own app — 0 would misread as
                    # "first in queue").
                    "queue_position": (
                        len(requests[k].rows) - 1
                        if self._config.fifo
                        else None
                    ),
                    "solve_info": dispatch_info,
                    # Multi-device engine: the pool slot whose
                    # partition solved THIS request (None on the
                    # single-device path).
                    "device_id": (
                        t.handle.request_device[k]
                        if t.handle.request_device is not None
                        else None
                    ),
                },
            )

        # Pass 1 — classify: denials finalize immediately (demand +
        # record + failure response); admitted gangs queue for ONE
        # coalesced reservation write-back below instead of a cache
        # write + listener fan-out per decision.
        admitted: list[tuple] = []  # (k, i, pod, res, args, packing)
        for k, (i, pod, res, args) in enumerate(window):
            d = decisions[k]
            if d.admitted:
                admitted.append((k, i, pod, res, args, d.packing))
                continue
            # Per-request trace span over the decision apply, same
            # name/tags as the solo path's — dashboards keyed on
            # select-node cover windowed serving too.
            with tracer().span(
                "select-node", role=ROLE_DRIVER,
                pod=f"{pod.namespace}/{pod.name}",
            ) as sp:
                self._demands.create_demand_for_application(pod, res)
                extra = None
                if d.earlier_blocked:
                    outcome, msg = (
                        FAILURE_EARLIER_DRIVER,
                        "earlier drivers do not fit to the cluster",
                    )
                else:
                    outcome, msg = (
                        FAILURE_FIT,
                        "application does not fit to the cluster",
                    )
                    pre = self._try_preempt_for(
                        pod, res, args.node_names, domains[i]
                    )
                    if pre is not None:
                        # Evictions freed capacity; this round still denies
                        # and the pod's retry admits against the freed
                        # cluster (the solo path re-solves inline instead).
                        msg = (
                            "application does not fit; preempted "
                            f"{len(pre['evicted'])} lower-priority gang(s)"
                        )
                        extra = {"preemption": pre}
                sp.tag("outcome", outcome)
                self._mark_outcome(pod, ROLE_DRIVER, outcome, timer_start)
                record(k, pod, args, outcome, None, msg, extra)
                results[i] = self._fail(args, outcome, msg)

        # One batched reservation write-back for the whole window: one
        # write-mutex hold, one batched usage-tracker/overhead delta
        # application, one (deferred) queue drain — instead of the full
        # chain per admitted gang. Per-entry failures surface exactly as
        # the serial create's ReservationError did.
        errors = self._rrm.create_reservations_batch(
            [
                (pod, res, packing.driver_node, packing.executor_nodes)
                for _k, _i, pod, res, _args, packing in admitted
            ]
        )

        # Pass 2 — finalize admitted gangs against the batch outcome.
        for (k, i, pod, res, args, packing), err in zip(admitted, errors):
            with tracer().span(
                "select-node", role=ROLE_DRIVER,
                pod=f"{pod.namespace}/{pod.name}",
            ) as sp:
                if self._metrics is not None:
                    self._metrics.report_packing_efficiency(
                        self.binpacker.name, packing
                    )
                    self._metrics.report_cross_zone(
                        packing.driver_node,
                        packing.executor_nodes,
                        all_nodes
                        if domains[i] is None
                        else [by_name[nm] for nm in domains[i]],
                    )
                self._demands.delete_demand_if_exists(pod)
                if err is not None:
                    # No rollback of the window's committed base: later
                    # window decisions stand even though this app holds
                    # nothing. That is the reference's own durability
                    # stance — reservation writes are fire-and-forget and
                    # "some writes will be lost on leader change"
                    # (failover.go:35-41); the failed app retries, and
                    # failover reconciliation repairs drift.
                    sp.tag("outcome", FAILURE_INTERNAL)
                    self._mark_outcome(
                        pod, ROLE_DRIVER, FAILURE_INTERNAL, timer_start
                    )
                    record(k, pod, args, FAILURE_INTERNAL, None, str(err))
                    results[i] = self._fail(args, FAILURE_INTERNAL, str(err))
                    continue
                if self._events is not None:
                    self._events.emit_application_scheduled(pod, res)
                sp.tag("outcome", SUCCESS)
                self._mark_outcome(pod, ROLE_DRIVER, SUCCESS, timer_start)
                record(k, pod, args, SUCCESS, packing.driver_node)
                results[i] = ExtenderFilterResult(
                    node_names=[packing.driver_node],
                    failed_nodes={},
                    outcome=SUCCESS,
                )

    def _build_serving_tensors(self, snap):
        """Device tensors for the SOLO serving paths from a feature-store
        snapshot, shared with the pipelined window cache: one
        device-resident copy of cluster state, and solo solves see the
        gangs of still-in-flight windows (the threaded base) instead of a
        stale host-only view. If topology changed while windows are in
        flight, fall back to an uncached host-truth build for this one
        solve."""
        from spark_scheduler_tpu.core.solver import PipelineDrainRequired

        try:
            return self._solver.build_tensors_pipelined(
                snap.nodes, snap.usage, snap.overhead,
                topo_version=snap.nodes_version,
                statics_version=snap.statics_epoch,
                roster_rows=snap.roster_rows,
                dirty_hint=snap.dirty_hint,
                avail_epoch=snap.avail_epoch,
                avail_journal=snap.avail_journal,
            )
        except PipelineDrainRequired:
            return self._solver.build_tensors(
                snap.nodes, snap.usage, snap.overhead,
                full_node_list=True, topo_version=snap.nodes_version,
                roster_rows=snap.roster_rows,
                avail_epoch=snap.avail_epoch,
                avail_journal=snap.avail_journal,
            )

    def _mark_outcome(self, pod, role, outcome, timer_start) -> None:
        if self._metrics is not None:
            self._metrics.mark_schedule_outcome(
                pod, role, outcome, self._clock() - timer_start
            )

    def _try_preempt_for(
        self, pod, res, candidate_names, domain_names
    ) -> Optional[dict]:
        """Vectorized preemption on a fit denial (policy subsystem): ONE
        batched masked-fit pass over candidate eviction sets, then evict
        the minimal feasible set through the normal teardown path and bump
        the capacity epoch. Best-effort — any failure leaves the denial as
        is. Returns the recorder payload (eviction set + costs) or None."""
        if self._policy is None or self._policy.preemption is None:
            return None
        try:
            snap = self.features.snapshot()
            tensors = self._build_serving_tensors(snap)
            domain_mask = (
                self._solver.candidate_mask(tensors, list(domain_names))
                if domain_names is not None
                else None
            )
            result = self._policy.try_preempt(
                self._solver,
                self.binpacker.name,
                tensors,
                pod,
                res,
                candidate_names,
                set(domain_names) if domain_names is not None else None,
                domain_mask=domain_mask,
            )
        except Exception as exc:
            from spark_scheduler_tpu.tracing import svc1log

            svc1log().warn(
                "preemption search failed; keeping fit denial",
                pod=f"{pod.namespace}/{pod.name}",
                error=repr(exc),
            )
            return None
        if result is None:
            return None
        self._capacity_epoch += 1
        return dataclasses.asdict(result)

    def _record_decision(
        self, pod, role, outcome, node, node_names, message="", ctx=None,
    ) -> None:
        """Append one flight-recorder DecisionRecord. `ctx` is the per-
        decision scratch dict the select paths fill: phase wall times
        ("featurize_ms"/"solve_ms"/"commit_ms"), "queue_position" (earlier
        FIFO drivers re-packed), and "solve_info" (the solver's dispatch
        bucket + compile-cache verdict)."""
        rec = self._recorder
        if rec is None:
            return
        ctx = ctx or {}
        # Capped at the recorder's per-record bound up front: on a
        # 10k-node denial the reason is one identical message, and
        # materializing the full map just for the recorder to truncate it
        # would be an O(nodes) allocation per denial. (The wire response's
        # full FailedNodes map is built by _fail as before.)
        failed_nodes = (
            rec.build_failure_map(node_names, message or outcome)
            if node is None
            else {}
        )
        solve_info = ctx.get("solve_info")
        rec.record(
            namespace=pod.namespace,
            pod_name=pod.name,
            app_id=pod.labels.get(SPARK_APP_ID_LABEL, ""),
            instance_group=(
                find_instance_group(pod, self._config.instance_group_label)
                or ""
            ),
            role=role or "unknown",
            verdict=outcome,
            node=node,
            message=message,
            failed_nodes=failed_nodes,
            queue_position=ctx.get("queue_position"),
            phases={
                k: v
                for k, v in ctx.items()
                if k in ("featurize_ms", "solve_ms", "commit_ms")
                or k.startswith("featurize_")
            },
            solve=solve_info,
            device_id=ctx.get("device_id"),
            state_upload=(
                solve_info.get("state_upload")
                if isinstance(solve_info, dict)
                else None
            ),
            fused_k=(
                solve_info.get("fused_k")
                if isinstance(solve_info, dict)
                else None
            ),
            dispatch_id=(
                solve_info.get("dispatch_id")
                if isinstance(solve_info, dict)
                else None
            ),
            degraded=(
                solve_info.get("degraded")
                if isinstance(solve_info, dict)
                else None
            ),
            redispatches=(
                solve_info.get("redispatches")
                if isinstance(solve_info, dict)
                else None
            ),
            preemption=ctx.get("preemption"),
        )

    # ------------------------------------------------------------- plumbing

    def _fail(self, args: ExtenderArgs, outcome: str, message: str) -> ExtenderFilterResult:
        if self._metrics is not None:
            self._metrics.mark_failed_scheduling_attempt(args.pod, outcome)
        if self._waste is not None:
            self._waste.mark_failed_scheduling_attempt(args.pod, outcome)
        return ExtenderFilterResult(
            node_names=[],
            failed_nodes={name: message for name in args.node_names},
            outcome=outcome,
        )

    def _reconcile_if_needed(self) -> None:
        """Request gap > `extender.resync-gap-seconds` => leader probably
        changed => resync durable state from observed pods
        (resource.go:191-202). Under a HELD HA lease the gap can prove
        nothing (leadership is affirmed every heartbeat, and losing it
        already forces a promotion-time reconcile on the successor), so
        the heuristic is skipped entirely."""
        now = self._clock()
        lease = self.ha_lease
        if lease is not None and lease.is_held():
            self._last_request = now
            return
        if now > self._last_request + self._config.resync_gap_seconds:
            if self._reconciler is not None:
                from spark_scheduler_tpu.tracing import tracer

                with tracer().span("reconcile", reason="leader-election-gap"):
                    self._reconciler.sync_resource_reservations_and_demands()
        self._last_request = now

    def _select_node(
        self, role: str, pod: Pod, node_names: list[str], ctx=None
    ) -> tuple[Optional[str], str, str]:
        if role == ROLE_DRIVER:
            return self._select_driver_node(pod, node_names, ctx=ctx)
        if role == ROLE_EXECUTOR:
            node, outcome, msg = self._select_executor_node(pod, node_names)
            if outcome in SUCCESS_OUTCOMES:
                self._demands.delete_demand_if_exists(pod)
            return node, outcome, msg
        return None, FAILURE_NON_SPARK_POD, "can not schedule non spark pod"

    # --------------------------------------------------------------- driver

    def _select_driver_node(
        self, driver: Pod, node_names: list[str], ctx=None
    ) -> tuple[Optional[str], str, str]:
        if ctx is None:
            ctx = {}
        t0 = self._clock()
        app_id = driver.labels.get(SPARK_APP_ID_LABEL, "")
        rr = self._rrm.get_resource_reservation(app_id, driver.namespace)
        if rr is not None:
            # Idempotent retry: return the previously reserved node even if
            # absent from the candidate list (resource.go:273-286).
            return rr.spec.reservations[DRIVER_RESERVATION].node, SUCCESS, ""

        snap = self.features.snapshot()
        all_nodes = snap.nodes
        available_nodes = [n for n in all_nodes if pod_matches_node(driver, n)]

        try:
            app_resources = spark_resources(driver)
        except SparkPodError as exc:
            return None, FAILURE_INTERNAL, f"failed to get spark resources: {exc}"

        earlier: Sequence[Pod] = ()
        if self._config.fifo:
            if self._policy is not None:
                group = find_instance_group(
                    driver, self._config.instance_group_label
                )
                blockers, hard = self._policy.ordering.blockers(
                    driver, group, self._parse_pending_drivers(), self._clock()
                )
                if hard:
                    self._demands.create_demand_for_application(
                        driver, app_resources
                    )
                    return (
                        None,
                        FAILURE_EARLIER_DRIVER,
                        "yielding to instance group with smaller dominant share",
                    )
                earlier = [row[0] for row in blockers]
            else:
                earlier = self._pod_lister.list_earlier_drivers(driver)
            # None (not 0) when FIFO is off: the record must distinguish
            # "first in queue" from "queue never consulted".
            ctx["queue_position"] = len(earlier)

        if self._config.batched_admission and self._solver.can_batch(
            self.binpacker.name
        ):
            # ONE device program admits the whole FIFO prefix + this driver
            # (SURVEY.md §2d row 1) — replaces fitEarlierDrivers' per-driver
            # re-pack loop (resource.go:221-258) AND the final pack with a
            # single batched solve, sorting once per request like the
            # reference (resource.go:299; see ExtenderConfig.batched_admission
            # for how this can differ from the sequential fallback). Cluster
            # state is device-resident: full node list + delta upload,
            # affinity filtering via the domain mask (VERDICT r2 #3).
            tensors = self._build_serving_tensors(snap)
            domain = self._solver.candidate_mask(
                tensors, [n.name for n in available_nodes]
            )
            s0 = self._clock()
            ctx["featurize_ms"] = (s0 - t0) * 1e3
            packing, outcome, message = self._admit_driver_batched(
                driver, app_resources, earlier, tensors, node_names, domain
            )
            ctx["solve_ms"] = (self._clock() - s0) * 1e3
            ctx["solve_info"] = self._solver.last_solve_info
            if packing is None:
                if outcome == FAILURE_FIT and not ctx.get("preempted"):
                    pre = self._try_preempt_for(
                        driver,
                        app_resources,
                        node_names,
                        [n.name for n in available_nodes],
                    )
                    if pre is not None:
                        # Inline one-shot retry against the freed cluster
                        # (the windowed path instead denies and lets the
                        # pod's retry admit — see _complete_driver_window).
                        ctx["preempted"] = True
                        ctx["preemption"] = pre
                        return self._select_driver_node(
                            driver, node_names, ctx=ctx
                        )
                self._demands.create_demand_for_application(driver, app_resources)
                return None, outcome, message
        else:
            # Sequential fallback (batching disabled by config).
            overhead = self._overhead.get_overhead(available_nodes)
            tensors = self._solver.build_tensors(
                available_nodes, snap.usage, overhead
            )
            s0 = self._clock()
            ctx["featurize_ms"] = (s0 - t0) * 1e3
            if earlier:
                tensors, ok = self._fit_earlier_drivers(earlier, tensors, node_names)
                if not ok:
                    ctx["solve_ms"] = (self._clock() - s0) * 1e3
                    self._demands.create_demand_for_application(driver, app_resources)
                    return None, FAILURE_EARLIER_DRIVER, "earlier drivers do not fit to the cluster"

            packing = self._solver.pack(
                self.binpacker.name,
                tensors,
                app_resources.driver_resources,
                app_resources.executor_resources,
                app_resources.min_executor_count,
                node_names,
            )
            ctx["solve_ms"] = (self._clock() - s0) * 1e3
            ctx["solve_info"] = self._solver.last_solve_info
            if not packing.has_capacity:
                if not ctx.get("preempted"):
                    pre = self._try_preempt_for(
                        driver,
                        app_resources,
                        node_names,
                        [n.name for n in available_nodes],
                    )
                    if pre is not None:
                        ctx["preempted"] = True
                        ctx["preemption"] = pre
                        return self._select_driver_node(
                            driver, node_names, ctx=ctx
                        )
                self._demands.create_demand_for_application(driver, app_resources)
                return None, FAILURE_FIT, "application does not fit to the cluster"

        c0 = self._clock()
        if self._metrics is not None:
            self._metrics.report_packing_efficiency(self.binpacker.name, packing)
            self._metrics.report_cross_zone(
                packing.driver_node, packing.executor_nodes, available_nodes
            )
        self._demands.delete_demand_if_exists(driver)
        try:
            self._rrm.create_reservations(
                driver,
                app_resources,
                packing.driver_node,
                packing.executor_nodes,
            )
        except ReservationError as exc:
            ctx["commit_ms"] = (self._clock() - c0) * 1e3
            return None, FAILURE_INTERNAL, str(exc)
        # Solo-path capacity change: stale in-flight windows must re-solve.
        self._capacity_epoch += 1
        if self._events is not None:
            # Only on fresh admission — the idempotent-retry branch above
            # must not double-emit application_scheduled (events.go:27-50).
            self._events.emit_application_scheduled(driver, app_resources)
        ctx["commit_ms"] = (self._clock() - c0) * 1e3
        return packing.driver_node, SUCCESS, ""

    def _admit_driver_batched(
        self,
        driver: Pod,
        app_resources,
        earlier: Sequence[Pod],
        tensors,
        node_names: list[str],
        domain_mask=None,
    ):
        """Batched FIFO admission: earlier drivers + the current driver as
        one single-segment `pack_window` solve — the same device program the
        coalesced serving window runs. Returns (packing|None, outcome,
        message); None packing means the caller creates a demand and fails
        the request (resource.go:241-249 / :342-345 outcome split)."""
        rows = []
        for ed in earlier:
            try:
                res = spark_resources(ed)
            except SparkPodError:
                continue  # unparseable driver is skipped (resource.go:228-233)
            rows.append(
                (
                    res.driver_resources,
                    res.executor_resources,
                    res.min_executor_count,
                    self._should_skip_driver_fifo(ed),
                )
            )
        rows.append(
            (
                app_resources.driver_resources,
                app_resources.executor_resources,
                app_resources.min_executor_count,
                False,
            )
        )
        # ONE single-segment pack_window: the same program the coalesced
        # serving window runs, so solo and windowed serving share semantics
        # exactly — including sorting ONCE per request (resource.go:299).
        decision = self._solver.pack_window(
            self.binpacker.name,
            tensors,
            [
                WindowRequest(
                    rows=rows,
                    driver_candidate_names=node_names,
                    domain_mask=domain_mask,
                )
            ],
        )[0]
        if decision.admitted:
            return decision.packing, SUCCESS, ""
        if decision.earlier_blocked:
            return None, FAILURE_EARLIER_DRIVER, "earlier drivers do not fit to the cluster"
        return None, FAILURE_FIT, "application does not fit to the cluster"

    def _fit_earlier_drivers(
        self, drivers: Sequence[Pod], tensors, node_names: list[str]
    ):
        """FIFO prefix admission (resource.go:221-258): every earlier driver
        must hypothetically fit (or be young enough to skip); each fit
        subtracts its placements from availability.

        Deviation from the reference, deliberate: the reference's
        `sparkResourceUsage` (sparkpods.go:141-149) OVERWRITES per-node usage
        (one executor's worth per distinct node, driver slot clobbered by
        executors on the same node), under-reserving for earlier drivers. We
        scatter-ADD the true usage of every placement.
        """
        for driver in drivers:
            try:
                app_resources = spark_resources(driver)
            except SparkPodError:
                continue  # unparseable driver is skipped (resource.go:228-233)
            packing = self._solver.pack(
                self.binpacker.name,
                tensors,
                app_resources.driver_resources,
                app_resources.executor_resources,
                app_resources.min_executor_count,
                node_names,
            )
            if not packing.has_capacity:
                if self._should_skip_driver_fifo(driver):
                    continue
                return tensors, False
            usage: dict = {}
            from spark_scheduler_tpu.models.resources import Resources as _R

            usage[packing.driver_node] = app_resources.driver_resources.copy()
            for node in packing.executor_nodes:
                usage.setdefault(node, _R.zero()).add(app_resources.executor_resources)
            tensors = self._solver.subtract_usage(tensors, usage)
        return tensors, True

    def _should_skip_driver_fifo(self, pod: Pod) -> bool:
        """Age-gated FIFO enforcement (resource.go:260-270)."""
        from spark_scheduler_tpu.core.sparkpods import find_instance_group

        group = find_instance_group(pod, self._config.instance_group_label) or ""
        age_gate = self._config.fifo_config.enforce_after_pod_age_by_instance_group.get(
            group, self._config.fifo_config.enforce_after_pod_age_s
        )
        return pod.creation_timestamp + age_gate > self._clock()

    # ------------------------------------------------------------- executor

    def _serve_executor_window(self, t: WindowTicket, ids: list[int]) -> None:
        """Serve a run of consecutive executor requests of a window with
        grouped passes instead of one full ladder per request:

        1. Per app: ONE pass over the reservation/soft stores resolves
           already-bound / unbound / needs-spot for the whole batch
           (rrm.executor_ladder_batch — one fetch, one active-pod listing,
           one cache write per app per window).
        2. ONE grouped device solve places all reschedule stragglers
           (pack_window, one 1-executor segment per straggler; each segment
           commits into the threaded base, so later stragglers see earlier
           placements — replacing one `pack` device round trip per
           straggler with one for the whole window).

        Decisions match serving the run serially through
        _select_executor_node, with two documented conservative deviations:
        a straggler's slot-move frees its OLD node only after this window
        (a later straggler in the same window does not see that freed
        capacity), and when a straggler's solve fails, later same-app
        executors that were classified no-spots fail failure-fit (the
        outcome the serial re-attempt would reach) without re-solving.
        Anchor: resource.go:376-428."""
        from spark_scheduler_tpu.tracing import tracer

        args_list, results = t.args_list, t.results

        def finish(i, node, outcome, message=""):
            pod = args_list[i].pod
            with tracer().span(
                "select-node", role=ROLE_EXECUTOR,
                pod=f"{pod.namespace}/{pod.name}",
            ) as sp:
                sp.tag("outcome", outcome)
            self._mark_outcome(pod, ROLE_EXECUTOR, outcome, t.timer_start)
            self._record_decision(
                pod, ROLE_EXECUTOR, outcome, node,
                args_list[i].node_names, message,
            )
            if node is None:
                results[i] = self._fail(args_list[i], outcome, message or outcome)
            else:
                self._demands.delete_demand_if_exists(pod)
                results[i] = ExtenderFilterResult(
                    node_names=[node], failed_nodes={}, outcome=outcome
                )

        by_app: dict[tuple[str, str], list[int]] = {}
        for i in ids:
            pod = args_list[i].pod
            key = (pod.namespace, pod.labels.get(SPARK_APP_ID_LABEL, ""))
            by_app.setdefault(key, []).append(i)

        stragglers: list[dict] = []
        straggler_by_pod: dict[tuple[str, str], dict] = {}
        dup_waiters: dict[tuple[str, str], list[int]] = {}
        deferred_no_spots: dict[tuple[str, str], list[int]] = {}
        app_ctx: dict[tuple[str, str], tuple] = {}
        for key, app_ids in by_app.items():
            namespace, app_id = key
            try:
                rungs = self._rrm.executor_ladder_batch(
                    app_id, namespace,
                    [(args_list[i].pod, args_list[i].node_names) for i in app_ids],
                )
            except ReservationError as exc:
                for i in app_ids:
                    finish(
                        i, None, FAILURE_INTERNAL,
                        f"error when looking for already bound reservations: {exc}",
                    )
                continue
            for i, (kind, val) in zip(app_ids, rungs):
                pod = args_list[i].pod
                if kind == "already":
                    finish(i, val, SUCCESS_ALREADY_BOUND)
                elif kind == "bound":
                    finish(i, val, SUCCESS)
                elif kind == "no-spots":
                    deferred_no_spots.setdefault(key, []).append(i)
                elif kind == "dup-reschedule":
                    # Same pod submitted twice in one window; resolved from
                    # the first occurrence's result after the solve.
                    dup_waiters.setdefault(
                        (pod.namespace, pod.name), []
                    ).append(i)
                else:  # reschedule
                    ctx = app_ctx.get(key)
                    if ctx is None:
                        ctx = app_ctx[key] = self._reschedule_context(pod)
                    pod_key = (pod.namespace, pod.name)
                    if ctx[0] is None:
                        finish(i, None, FAILURE_INTERNAL, ctx[2])
                        straggler_by_pod[pod_key] = {
                            "result": ("internal", ctx[2])
                        }
                        continue
                    exec_res, zone, _ = ctx
                    names = [
                        n.name
                        for name in args_list[i].node_names
                        if (n := self._backend.get_node(name)) is not None
                        and (zone is None or n.zone == zone)
                    ]
                    entry = {
                        "i": i, "key": key, "exec_res": exec_res,
                        "zone": zone, "names": names, "is_extra": not val,
                        "result": None,
                    }
                    stragglers.append(entry)
                    straggler_by_pod[pod_key] = entry
        # Solve stragglers in ARRIVAL order: pack_window commits segment
        # placements sequentially, so under capacity contention the earlier
        # request must win the spot exactly as serial serving would.
        stragglers.sort(key=lambda s: s["i"])

        app_failed: set[tuple[str, str]] = set()
        app_internal: dict[tuple[str, str], str] = {}
        if stragglers:
            from spark_scheduler_tpu.models.resources import Resources as _R

            tensors = self._build_serving_tensors(self.features.snapshot())
            decisions = self._solver.pack_window(
                "tightly-pack",
                tensors,
                [
                    WindowRequest(
                        rows=[(_R.zero(), s["exec_res"], 1, False)],
                        driver_candidate_names=s["names"],
                        domain_node_names=s["names"],
                    )
                    for s in stragglers
                ],
            )
            rescheduled = False
            for s, d in zip(stragglers, decisions):
                i = s["i"]
                pod = args_list[i].pod
                if d.admitted and d.packing.executor_nodes:
                    node = d.packing.executor_nodes[0]
                    try:
                        self._rrm.reserve_for_executor_on_rescheduled_node(
                            pod, node
                        )
                    except ReservationError as exc:
                        msg = f"failed to reserve node for rescheduled executor: {exc}"
                        finish(i, None, FAILURE_INTERNAL, msg)
                        s["result"] = ("internal", msg)
                        # NOT app_failed: capacity exists (the solve
                        # admitted); a serial re-attempt by a later same-app
                        # executor would hit the same write failure, so
                        # those fail internal below, not failure-fit.
                        app_internal[s["key"]] = msg
                        continue
                    rescheduled = True
                    s["result"] = ("ok", node)
                    finish(
                        i, node,
                        SUCCESS_SCHEDULED_EXTRA_EXECUTOR
                        if s["is_extra"]
                        else SUCCESS_RESCHEDULED,
                    )
                else:
                    self._demands.create_demand_for_executor(
                        pod, s["exec_res"], zone=s["zone"]
                    )
                    s["result"] = ("fit", None)
                    finish(
                        i, None, FAILURE_FIT,
                        "not enough capacity to reschedule the executor",
                    )
                    app_failed.add(s["key"])
            if rescheduled:
                # New usage on nodes the reservations did not cover: stale
                # in-flight windows must re-solve (one bump covers the run).
                self._capacity_epoch += 1

        # Duplicate submissions resolve from their first occurrence: success
        # means the bind has applied, so the serial rung 1 would now return
        # already-bound (only for an OFFERED node — rung 1 checks the
        # request's own candidates; a non-offered node fails unbound, a
        # conservative stand-in for the serial path's rebind-on-new-spot,
        # and the client's next retry walks the full ladder); a failed
        # first occurrence means the retry would re-attempt the identical
        # reschedule and fail the identical way.
        for pod_key, idxs in dup_waiters.items():
            first = straggler_by_pod.get(pod_key)
            result = first.get("result") if first is not None else None
            for i in idxs:
                if result is not None and result[0] == "ok":
                    if result[1] in args_list[i].node_names:
                        finish(i, result[1], SUCCESS_ALREADY_BOUND)
                    else:
                        finish(
                            i, None, FAILURE_UNBOUND,
                            "application has no free executor spots to schedule this one",
                        )
                elif result is not None and result[0] == "internal":
                    finish(i, None, FAILURE_INTERNAL, result[1])
                else:
                    finish(
                        i, None, FAILURE_FIT,
                        "not enough capacity to reschedule the executor",
                    )

        for key, idxs in deferred_no_spots.items():
            ctx = app_ctx.get(key)
            if ctx is not None and ctx[0] is None:
                # Serial equivalence: the spot was only pre-consumed by an
                # executor whose reschedule context failed (spot never
                # actually used), so these would have re-attempted and hit
                # the same internal error.
                for i in idxs:
                    finish(i, None, FAILURE_INTERNAL, ctx[2])
            elif key in app_internal:
                # The spot was freed by a reservation-write failure, not a
                # capacity shortage — a serial re-attempt hits the same
                # write failure.
                for i in idxs:
                    finish(i, None, FAILURE_INTERNAL, app_internal[key])
            elif key in app_failed:
                # Serial equivalence: the failed straggler left its spot
                # unconsumed, so these executors would have re-attempted the
                # identical reschedule and failed the identical way.
                for i in idxs:
                    pod = args_list[i].pod
                    if ctx is not None and ctx[0] is not None:
                        exec_res, zone, _ = ctx
                        self._demands.create_demand_for_executor(
                            pod, exec_res, zone=zone
                        )
                    finish(
                        i, None, FAILURE_FIT,
                        "not enough capacity to reschedule the executor",
                    )
            else:
                for i in idxs:
                    finish(
                        i, None, FAILURE_UNBOUND,
                        "application has no free executor spots to schedule this one",
                    )

    def _reschedule_context(
        self, executor: Pod
    ) -> tuple[Optional["Resources"], Optional[str], Optional[str]]:
        """Per-app context for reschedule stragglers:
        (exec_resources, single-az zone restriction | None, None) on
        success, (None, None, error message) on failure — the error rides
        its own slot so no caller can mistake it for a zone name."""
        driver = self._pod_lister.get_driver_for_executor(executor)
        if driver is None:
            return None, None, "failed to get driver pod for executor"
        try:
            app_resources = spark_resources(driver)
        except SparkPodError as exc:
            return None, None, str(exc)
        zone = None
        if (
            self.binpacker.is_single_az
            and self._config.schedule_dynamically_allocated_executors_in_same_az
        ):
            try:
                z, all_same_az = self._common_zone_for_app(executor)
            except ReservationError as exc:
                return None, None, str(exc)
            if all_same_az:
                zone = z
        return app_resources.executor_resources, zone, None

    def _select_executor_node(
        self, executor: Pod, node_names: list[str]
    ) -> tuple[Optional[str], str, str]:
        try:
            bound_node, found = self._rrm.find_already_bound_reservation_node(executor)
        except ReservationError as exc:
            return None, FAILURE_INTERNAL, f"error when looking for already bound reservations: {exc}"
        if found:
            if bound_node in node_names:
                return bound_node, SUCCESS_ALREADY_BOUND, ""
            # bound node not offered; fall through (resource.go:377-388)

        try:
            chosen, unbound_count = self._rrm.reserve_executor_on_unbound(
                executor, node_names
            )
        except ReservationError as exc:
            return None, FAILURE_INTERNAL, f"error when looking for unbound reservations: {exc}"
        if chosen is not None:
            return chosen, SUCCESS, ""
        found_unbound = unbound_count > 0

        try:
            free_spots = self._rrm.get_remaining_allowed_executor_count(
                executor.labels.get(SPARK_APP_ID_LABEL, ""), executor.namespace,
                unbound_count=unbound_count,
            )
        except ReservationError as exc:
            return None, FAILURE_INTERNAL, f"error when checking for remaining allowed executor count: {exc}"
        if free_spots > 0:
            is_extra = not found_unbound
            node, outcome, msg = self._reschedule_executor(executor, node_names, is_extra)
            if node is None:
                return None, outcome, msg
            try:
                self._rrm.reserve_for_executor_on_rescheduled_node(executor, node)
            except ReservationError as exc:
                return None, FAILURE_INTERNAL, f"failed to reserve node for rescheduled executor: {exc}"
            # New usage on a node the reservation did not already cover:
            # stale in-flight windows must re-solve.
            self._capacity_epoch += 1
            return node, outcome, msg

        return None, FAILURE_UNBOUND, "application has no free executor spots to schedule this one"

    def _reschedule_executor(
        self, executor: Pod, node_names: list[str], is_extra: bool
    ) -> tuple[Optional[str], str, str]:
        """First executor-priority-ordered node with room (resource.go:565-639),
        optionally restricted to the app's common AZ for single-AZ dynamic
        allocation. Context derivation (driver lookup, resources, single-AZ
        zone — incl. the reference's error-the-request semantics,
        resource.go:583-586) is shared with the windowed path via
        _reschedule_context so the two ladders cannot drift."""
        exec_res, single_az_zone, ctx_error = self._reschedule_context(
            executor
        )
        if exec_res is None:
            return None, FAILURE_INTERNAL, ctx_error

        nodes = [
            n
            for name in node_names
            if (n := self._backend.get_node(name)) is not None
        ]
        if single_az_zone is not None:
            nodes = [n for n in nodes if n.zone == single_az_zone]

        tensors = self._build_serving_tensors(self.features.snapshot())
        domain = self._solver.candidate_mask(tensors, [n.name for n in nodes])
        # A 1-executor gang with no driver = "first sorted node with room".
        packing = self._solver.pack(
            "tightly-pack",
            tensors,
            type(exec_res).zero(),
            exec_res,
            1,
            [n.name for n in nodes],
            domain_mask=domain,
        )
        if packing.has_capacity and packing.executor_nodes:
            outcome = SUCCESS_SCHEDULED_EXTRA_EXECUTOR if is_extra else SUCCESS_RESCHEDULED
            return packing.executor_nodes[0], outcome, ""

        self._demands.create_demand_for_executor(
            executor, exec_res, zone=single_az_zone
        )
        return None, FAILURE_FIT, "not enough capacity to reschedule the executor"

    def _common_zone_for_app(self, executor: Pod) -> tuple[Optional[str], bool]:
        """(zone, running pods all in one AZ?) (resource.go:472-506). Raises
        ReservationError for the reference's error cases: no app-id label, no
        running pods, or an unresolvable node — callers must fail the request
        rather than fall back to any-AZ scheduling."""
        app_id = executor.labels.get(SPARK_APP_ID_LABEL)
        if app_id is None:
            raise ReservationError(
                "executor does not have a Spark app id label, could not create label selector"
            )
        pods = self._pod_lister.list_app_pods(app_id, executor.namespace)
        zones = set()
        for pod in pods:
            if pod.phase != "Running" or not pod.node_name:
                continue
            node = self._backend.get_node(pod.node_name)
            if node is None:
                raise ReservationError(
                    f"could not read zone label from node {pod.node_name}"
                )
            zones.add(node.zone)
        if len(zones) > 1:
            return None, False
        if not zones:
            raise ReservationError(
                "application has no scheduled pods, can't make scheduling decisions based on AZ"
            )
        return next(iter(zones)), True
