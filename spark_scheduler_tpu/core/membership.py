"""StableMembership — the live-membership/remap core shared by the
instance-group ShardMap (ha/shard.py) and the fleet ClusterMap (fleet/).

One rule, two layers: ownership is a pure function of (key, slot count)
— stable CRC32 over the ORIGINAL slot space — with a live-list fallback
for dead slots. Removing a member moves only ITS keys onto survivors; a
surviving member's keys never change owner, so an in-flight window on a
survivor cannot silently lose ownership mid-commit. Every participant
computes the same map from the same membership with no coordination
beyond agreeing on who is live.
"""

from __future__ import annotations

import zlib


class StableMembership:
    """Live membership over a fixed original slot space [0, n_slots)."""

    __slots__ = ("n_slots", "_live")

    def __init__(self, n_slots: int):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        self.n_slots = n_slots
        self._live = list(range(n_slots))

    def live(self) -> list[int]:
        return list(self._live)

    def is_live(self, index: int) -> bool:
        return index in self._live

    def remove(self, index: int) -> None:
        if len(self._live) <= 1:
            raise ValueError("cannot remove the last live member")
        if index in self._live:
            self._live.remove(index)

    def rejoin(self, index: int) -> None:
        if not 0 <= index < self.n_slots:
            raise ValueError(f"index {index} outside slot space")
        if index not in self._live:
            self._live.append(index)
            self._live.sort()

    def owner(self, key: str) -> int:
        """Owning slot for a key — stable across processes and runs
        (CRC32, not Python's salted hash). Assignment is over the
        ORIGINAL slot space: only a dead slot's keys fall through to the
        live-list modulo, so survivors' keys are never remapped."""
        h = zlib.crc32(key.encode("utf-8"))
        idx = h % self.n_slots
        live = self._live  # never empty: remove() refuses the last member
        if idx in live:
            return idx
        return live[h % len(live)]

    def owned_by(self, index: int, keys) -> list[str]:
        return [k for k in keys if self.owner(k) == index]

    def describe(self, keys=()) -> dict:
        return {
            "slots": self.n_slots,
            "live": list(self._live),
            "assignments": {k: self.owner(k) for k in keys},
        }
