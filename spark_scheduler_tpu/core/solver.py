"""PlacementSolver — the host <-> device boundary of the scheduler.

Everything above this module speaks names and Resources; everything below it
(ops/) speaks int32 tensors over a stable node-index space. The solver:

  - interns nodes into the NodeRegistry and builds ClusterTensors with
    padded (bucketed) shapes so XLA compile caches stay warm across node
    count / executor count jitter (SURVEY.md §7 "Dynamic shapes");
  - dispatches to the jitted packing kernels;
  - maps Packing index results back to node names.

This replaces the reference's per-request map-building + sort + greedy loops
(resource.go:287-323) with one device program per request.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from spark_scheduler_tpu.models.cluster import (
    NodeRegistry,
    build_cluster_tensors,
)
from spark_scheduler_tpu.models.kube import Node
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.ops import BINPACK_FUNCTIONS
from spark_scheduler_tpu.ops.efficiency import avg_packing_efficiency


def _bucket(n: int, minimum: int) -> int:
    out = minimum
    while out < n:
        out *= 2
    return out


class HostPacking(NamedTuple):
    driver_node: Optional[str]
    executor_nodes: list[str]
    has_capacity: bool
    efficiency_max: float
    efficiency_cpu: float
    efficiency_memory: float
    efficiency_gpu: float


class PlacementSolver:
    def __init__(
        self,
        driver_label_priority: tuple[str, list[str]] | None = None,
        executor_label_priority: tuple[str, list[str]] | None = None,
    ):
        self.registry = NodeRegistry()
        self._driver_label_priority = driver_label_priority
        self._executor_label_priority = executor_label_priority

    def build_tensors(
        self,
        nodes: Sequence[Node],
        usage: dict[str, Resources],
        overhead: dict[str, Resources],
    ):
        for n in nodes:
            self.registry.intern(n.name)
        pad = _bucket(self.registry.capacity, 8)
        return build_cluster_tensors(
            list(nodes),
            usage,
            overhead,
            self.registry,
            driver_label_priority=self._driver_label_priority,
            executor_label_priority=self._executor_label_priority,
            pad_to=pad,
        )

    def candidate_mask(self, tensors, node_names: Sequence[str]) -> np.ndarray:
        n = tensors.available.shape[0]
        mask = np.zeros(n, dtype=bool)
        for name in node_names:
            idx = self.registry.index_of(name)
            if idx is not None and idx < n:
                mask[idx] = True
        return mask

    def _num_zones_bucket(self) -> int:
        return _bucket(max(len(self.registry._zone_names), 1), 2)

    def pack(
        self,
        strategy: str,
        tensors,
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_candidate_names: Sequence[str],
        domain_mask: np.ndarray | None = None,
    ) -> HostPacking:
        fn = BINPACK_FUNCTIONS[strategy]
        n = tensors.available.shape[0]
        driver_mask = self.candidate_mask(tensors, driver_candidate_names)
        if domain_mask is None:
            domain_mask = np.asarray(tensors.valid)
        emax = _bucket(max(executor_count, 1), 8)
        packing = fn(
            tensors,
            jnp.asarray(driver_resources.as_array()),
            jnp.asarray(executor_resources.as_array()),
            jnp.int32(executor_count),
            jnp.asarray(driver_mask),
            jnp.asarray(domain_mask),
            emax=emax,
            num_zones=self._num_zones_bucket(),
        )
        eff = avg_packing_efficiency(
            tensors,
            packing.driver_node,
            packing.executor_nodes,
            jnp.asarray(driver_resources.as_array()),
            jnp.asarray(executor_resources.as_array()),
        )
        has_cap = bool(packing.has_capacity)
        driver_idx = int(packing.driver_node)
        exec_idx = [int(x) for x in np.asarray(packing.executor_nodes) if int(x) >= 0]
        return HostPacking(
            driver_node=self.registry.name_of(driver_idx) if driver_idx >= 0 else None,
            executor_nodes=[self.registry.name_of(i) for i in exec_idx],
            has_capacity=has_cap,
            efficiency_max=float(eff.max),
            efficiency_cpu=float(eff.cpu),
            efficiency_memory=float(eff.memory),
            efficiency_gpu=float(eff.gpu),
        )

    def subtract_usage(self, tensors, usage: dict[str, Resources]):
        """Subtract per-node usage from availability in-place-equivalent
        (NodeGroupSchedulingMetadata.SubtractUsageIfExists,
        resources.go:128-135); returns new tensors."""
        avail = np.array(tensors.available)
        for name, res in usage.items():
            idx = self.registry.index_of(name)
            if idx is not None and idx < avail.shape[0]:
                avail[idx] = avail[idx] - res.as_array()
        import dataclasses as _dc

        return _dc.replace(tensors, available=avail)
