"""PlacementSolver — the host <-> device boundary of the scheduler.

Everything above this module speaks names and Resources; everything below it
(ops/) speaks int32 tensors over a stable node-index space. The solver:

  - interns nodes into the NodeRegistry and builds ClusterTensors with
    padded (bucketed) shapes so XLA compile caches stay warm across node
    count / executor count jitter (SURVEY.md §7 "Dynamic shapes");
  - dispatches to the jitted packing kernels;
  - maps Packing index results back to node names.

This replaces the reference's per-request map-building + sort + greedy loops
(resource.go:287-323) with one device program per request.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from spark_scheduler_tpu import native
from spark_scheduler_tpu.models.cluster import (
    ClusterTensors,
    NodeRegistry,
    build_cluster_tensors,
)
from spark_scheduler_tpu.models.kube import Node
from spark_scheduler_tpu.models.resources import INT32_INF, NUM_DIMS, Resources
from spark_scheduler_tpu.ops import BINPACK_FUNCTIONS
from spark_scheduler_tpu.ops.efficiency import avg_packing_efficiency


def _bucket(n: int, minimum: int) -> int:
    out = minimum
    while out < n:
        out *= 2
    return out


class HostPacking(NamedTuple):
    driver_node: Optional[str]
    executor_nodes: list[str]
    has_capacity: bool
    efficiency_max: float
    efficiency_cpu: float
    efficiency_memory: float
    efficiency_gpu: float


class PlacementSolver:
    def __init__(
        self,
        driver_label_priority: tuple[str, list[str]] | None = None,
        executor_label_priority: tuple[str, list[str]] | None = None,
        use_native: bool = True,
    ):
        self.registry = NodeRegistry()
        self._driver_label_priority = driver_label_priority
        self._executor_label_priority = executor_label_priority
        # Native C++ arena (native/runtime.cpp): per-node state is upserted
        # only when a node object actually changes, and the dense tensor
        # inputs are materialized in one C call per request instead of a
        # Python walk over every node.
        self._arena = None
        self._node_seen: dict[str, Node] = {}
        self._rank_epoch = -1
        if use_native and native.available():
            self._arena = native.ClusterArena()

    @property
    def uses_native_arena(self) -> bool:
        return self._arena is not None

    def build_tensors(
        self,
        nodes: Sequence[Node],
        usage: dict[str, Resources],
        overhead: dict[str, Resources],
    ):
        for n in nodes:
            self.registry.intern(n.name)
        pad = _bucket(self.registry.capacity, 8)
        if self._arena is not None:
            return self._build_tensors_native(list(nodes), usage, overhead, pad)
        return build_cluster_tensors(
            list(nodes),
            usage,
            overhead,
            self.registry,
            driver_label_priority=self._driver_label_priority,
            executor_label_priority=self._executor_label_priority,
            pad_to=pad,
        )

    def _label_rank(self, node: Node, prio) -> int:
        if prio is None:
            return INT32_INF
        label, values = prio
        val = node.labels.get(label)
        if val is not None and val in values:
            return values.index(val)
        return INT32_INF

    def _build_tensors_native(
        self,
        nodes: list[Node],
        usage: dict[str, Resources],
        overhead: dict[str, Resources],
        pad: int,
    ) -> ClusterTensors:
        """Arena-backed ClusterTensors. Deviation from the Python builder,
        deliberate: name ranks are GLOBAL over all known nodes rather than
        recomputed over the request's filtered subset — the rank values
        differ but their relative order (all the sort kernels consume) is
        identical for any subset."""
        arena = self._arena
        seen = self._node_seen
        changed_names = False
        for node in nodes:
            if seen.get(node.name) is node:
                continue
            if node.name not in seen:
                changed_names = True
            seen[node.name] = node
            idx = self.registry.intern(node.name)
            arena.upsert(
                idx,
                node.allocatable.as_array(),
                self.registry.zone_id(node.zone),
                node.unschedulable,
                node.ready,
                self._label_rank(node, self._driver_label_priority),
                self._label_rank(node, self._executor_label_priority),
            )
        if changed_names or self._rank_epoch < 0:
            ordered = sorted(seen)
            arena.set_name_ranks(
                [self.registry.index_of(name) for name in ordered]
            )
            self._rank_epoch += 1

        usage_t = np.zeros((pad, NUM_DIMS), dtype=np.int64)
        overhead_t = np.zeros((pad, NUM_DIMS), dtype=np.int64)
        for target, mapping in ((usage_t, usage), (overhead_t, overhead)):
            for name, res in mapping.items():
                idx = self.registry.index_of(name)
                if idx is not None and idx < pad:
                    target[idx] += res.as_array()

        fields = arena.snapshot(pad, usage_t, overhead_t)
        tensors = ClusterTensors(*fields)
        # The arena knows every node ever seen; this request's candidate set
        # is the (selector-filtered) `nodes` list — mask the rest out.
        request_mask = np.zeros(pad, dtype=bool)
        idxs = [self.registry.index_of(n.name) for n in nodes]
        request_mask[[i for i in idxs if i is not None and i < pad]] = True
        tensors.valid &= request_mask
        return tensors

    def candidate_mask(self, tensors, node_names: Sequence[str]) -> np.ndarray:
        n = tensors.available.shape[0]
        mask = np.zeros(n, dtype=bool)
        for name in node_names:
            idx = self.registry.index_of(name)
            if idx is not None and idx < n:
                mask[idx] = True
        return mask

    def _num_zones_bucket(self) -> int:
        return _bucket(max(len(self.registry._zone_names), 1), 2)

    def pack(
        self,
        strategy: str,
        tensors,
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_candidate_names: Sequence[str],
        domain_mask: np.ndarray | None = None,
    ) -> HostPacking:
        fn = BINPACK_FUNCTIONS[strategy]
        n = tensors.available.shape[0]
        driver_mask = self.candidate_mask(tensors, driver_candidate_names)
        if domain_mask is None:
            domain_mask = np.asarray(tensors.valid)
        emax = _bucket(max(executor_count, 1), 8)
        packing = fn(
            tensors,
            jnp.asarray(driver_resources.as_array()),
            jnp.asarray(executor_resources.as_array()),
            jnp.int32(executor_count),
            jnp.asarray(driver_mask),
            jnp.asarray(domain_mask),
            emax=emax,
            num_zones=self._num_zones_bucket(),
        )
        eff = avg_packing_efficiency(
            tensors,
            packing.driver_node,
            packing.executor_nodes,
            jnp.asarray(driver_resources.as_array()),
            jnp.asarray(executor_resources.as_array()),
        )
        has_cap = bool(packing.has_capacity)
        driver_idx = int(packing.driver_node)
        exec_idx = [int(x) for x in np.asarray(packing.executor_nodes) if int(x) >= 0]
        return HostPacking(
            driver_node=self.registry.name_of(driver_idx) if driver_idx >= 0 else None,
            executor_nodes=[self.registry.name_of(i) for i in exec_idx],
            has_capacity=has_cap,
            efficiency_max=float(eff.max),
            efficiency_cpu=float(eff.cpu),
            efficiency_memory=float(eff.memory),
            efficiency_gpu=float(eff.gpu),
        )

    def subtract_usage(self, tensors, usage: dict[str, Resources]):
        """Subtract per-node usage from availability in-place-equivalent
        (NodeGroupSchedulingMetadata.SubtractUsageIfExists,
        resources.go:128-135); returns new tensors."""
        avail = np.array(tensors.available)
        for name, res in usage.items():
            idx = self.registry.index_of(name)
            if idx is not None and idx < avail.shape[0]:
                avail[idx] = avail[idx] - res.as_array()
        import dataclasses as _dc

        return _dc.replace(tensors, available=avail)
