"""PlacementSolver — the host <-> device boundary of the scheduler.

Everything above this module speaks names and Resources; everything below it
(ops/) speaks int32 tensors over a stable node-index space. The solver:

  - interns nodes into the NodeRegistry and builds ClusterTensors with
    padded (bucketed) shapes so XLA compile caches stay warm across node
    count / executor count jitter (SURVEY.md §7 "Dynamic shapes");
  - dispatches to the jitted packing kernels;
  - maps Packing index results back to node names.

This replaces the reference's per-request map-building + sort + greedy loops
(resource.go:287-323) with one device program per request.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import NamedTuple, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from spark_scheduler_tpu import native
from spark_scheduler_tpu.models.cluster import (
    ClusterTensors,
    NodeRegistry,
    build_cluster_tensors,
)
from spark_scheduler_tpu.models.kube import Node
from spark_scheduler_tpu.models.resources import INT32_INF, NUM_DIMS, Resources
from spark_scheduler_tpu.ops import BINPACK_FUNCTIONS
from spark_scheduler_tpu.ops.batched import batched_fifo_pack, make_app_batch
from spark_scheduler_tpu.ops.efficiency import avg_packing_efficiency_np

# Every strategy batches: the plain fills run as the scan's executor fill,
# and the single-AZ wrappers run their per-zone pack + efficiency-scored
# zone selection inside the scan step (ops/batched.py _SINGLE_AZ_INNER,
# VERDICT r2 #2). Derived, not enumerated — a new strategy registered in
# BINPACK_FUNCTIONS must also be taught to the batched scan.
BATCHABLE_STRATEGIES = frozenset(BINPACK_FUNCTIONS)

def _build_segmented_window(
    requests, drv_arr, exc_arr, counts, skip_arr, cand_per_req, dom_per_req
):
    """Segment-major [S, R] arrays for the Pallas window path
    (ops/pallas_window.make_segmented_window), with S and R BUCKETED
    coarsely: every (s_pad, r_pad) pair is a separate scan-over-segments
    compile, and padding segments are skipped at runtime (lax.cond on
    row_count) so coarse S padding costs no device time. Returns
    (SegmentedWindow, seg_idx, row_idx) — host numpy index arrays mapping
    each flat row to its [S, R] position (used by pack_window_fetch to
    flatten the fetched blob)."""
    from spark_scheduler_tpu.ops.pallas_window import (
        segmented_window_from_flat,
    )

    s = len(requests)
    rc = np.asarray([len(req.rows) for req in requests], np.int32)
    s_pad = 4
    while s_pad < s:
        s_pad *= 8
    r_pad = 16
    while r_pad < int(rc.max()):
        r_pad *= 4
    win, seg_idx, row_idx = segmented_window_from_flat(
        drv_arr, exc_arr, counts, skip_arr, rc, cand_per_req, dom_per_req,
        pad_segments=s_pad, pad_rows=r_pad,
    )
    return win, seg_idx, row_idx, s_pad, r_pad


def _bucket(n: int, minimum: int) -> int:
    out = minimum
    while out < n:
        out *= 2
    return out


def _host_view(tensors) -> ClusterTensors:
    """Host-resident numpy view of cluster tensors. Device-cached tensors
    (build_tensors_cached) carry their numpy source as `.host`; using it for
    host-side math (efficiency, masks, reconstruction) avoids pulling full
    arrays back over a tunneled device link."""
    return getattr(tensors, "host", tensors)


def _tensors_nbytes(host) -> int:
    """Total byte size of a host ClusterTensors — what a full device upload
    ships (telemetry's h2d accounting)."""
    total = 0
    for f in dataclasses.fields(host):
        arr = getattr(host, f.name, None)
        total += getattr(arr, "nbytes", 0)
    return total


# Fields that force a full re-upload when they change (node topology /
# attribute changes — rare next to availability churn).
_STATIC_FIELDS = (
    "schedulable",
    "zone_id",
    "name_rank",
    "label_rank_driver",
    "label_rank_executor",
    "unschedulable",
    "ready",
    "valid",
)


@jax.jit
def _scatter_rows(avail, idx, rows):
    """Jitted row update for the device-resident availability tensor.
    Duplicate indices carry identical rows (bucketing pads by repeating a
    dirty row), so .set is deterministic."""
    return avail.at[idx].set(rows)


class _DaemonFetchPool:
    """Minimal fetch pool with DAEMON workers: a device transfer stuck on a
    dead tunnel must never block interpreter exit, which
    ThreadPoolExecutor's non-daemon workers (joined by its atexit hook)
    would. Futures are concurrent.futures.Future — result()/done()
    compatible with the executor API the handles expose.

    ONE pool is shared by every solver in the process
    (_shared_fetch_pool): the workers run stateless jax.device_get calls,
    so there is nothing per-solver about them, and a pool per solver
    accumulates leaked daemon threads wherever solvers are created without
    a paired close() (each test harness, every rebuilt app). A full test
    run leaked 100+ such threads and died with a native-thread segfault;
    the shared pool bounds the cost at `workers` threads per process."""

    def __init__(self, workers: int = 4, name: str = "window-blob-fetch"):
        import queue as _queue

        self._q: "_queue.Queue" = _queue.Queue()
        self._threads = []
        for i in range(workers):
            t = threading.Thread(
                target=self._run, daemon=True, name=f"{name}-{i}"
            )
            t.start()
            self._threads.append(t)

    def _run(self) -> None:
        while True:
            fut, fn = self._q.get()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as exc:  # delivered via future.result()
                fut.set_exception(exc)

    def submit(self, fn, *args):
        from concurrent.futures import Future

        fut: Future = Future()
        self._q.put((fut, lambda: fn(*args)))
        return fut


_shared_pool: _DaemonFetchPool | None = None
_shared_pool_lock = threading.Lock()


def _shared_fetch_pool() -> _DaemonFetchPool:
    """The process-wide blob-fetch pool, created on first use. Never shut
    down: the workers are daemon threads idling on a queue, so they cost
    nothing and cannot block interpreter exit. Solver.close() fail-fasts
    new submits at the solver level instead of tearing the pool down under
    other solvers."""
    global _shared_pool
    with _shared_pool_lock:
        if _shared_pool is None:
            # Several workers: over the tunnel, concurrent device_get RPCs
            # overlap almost perfectly (4 fetches take ~1 RTT), so a
            # depth-N serving pipeline divides the round trip.
            _shared_pool = _DaemonFetchPool(workers=4)
        return _shared_pool


@jax.jit
def _add_rows(avail, idx, delta_rows):
    """Jitted ADDITIVE row update for the pipelined device availability:
    ships host-side deltas without clobbering gang subtractions the device
    threaded from still-in-flight windows. Padding rows carry zero deltas,
    so duplicate padded indices are harmless."""
    return avail.at[idx].add(delta_rows)


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def _window_blob(cluster, apps, *, fill, emax, num_zones):
    """batched_fifo_pack with every per-row output packed into ONE int32
    array [B, 3+Emax]: (driver, admitted, packed, exec slots...). On a
    tunneled device each fetched array is its own RPC round trip, so the
    serving path pulls a single blob instead of four arrays. Also returns
    the threaded committed-base availability so a PIPELINED caller can
    dispatch the next window from it without fetching this one."""
    out = batched_fifo_pack(
        cluster, apps, fill=fill, emax=emax, num_zones=num_zones
    )
    blob = jnp.concatenate(
        [
            out.driver_node[:, None],
            out.admitted[:, None].astype(jnp.int32),
            out.packed[:, None].astype(jnp.int32),
            out.executor_nodes,
        ],
        axis=1,
    )
    return blob, out.available_after


@_partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def _window_blob_pallas(cluster, win, *, fill, emax, num_zones):
    """Segmented-window solve on the Pallas path (ops/pallas_window). The
    blob stays [S, R, 3+emax] — pack_window_fetch flattens the real rows
    host-side via the handle's seg_map, so the device program's shape
    depends ONLY on the (segments, rows) buckets, never on the window's
    flat row count (a third shape dimension would cross-multiply the
    compile cache)."""
    from spark_scheduler_tpu.ops.pallas_window import window_pack_pallas

    meta, execs, base_after = window_pack_pallas(
        cluster, win, fill=fill, emax=emax, num_zones=num_zones
    )
    blob = jnp.concatenate([meta[:, :, :3], execs], axis=2)
    return blob, base_after


@_partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def _pack_blob(cluster, dreq, ereq, count, dmask, dom, *, fill, emax, num_zones):
    """Single-app pack with the Packing flattened to one int32 [2+Emax]
    array: (driver, has_capacity, exec slots...) — one device fetch."""
    p = BINPACK_FUNCTIONS[fill](
        cluster, dreq, ereq, count, dmask, dom, emax=emax, num_zones=num_zones
    )
    return jnp.concatenate(
        [p.driver_node[None], p.has_capacity.astype(jnp.int32)[None], p.executor_nodes]
    )


class HostPacking(NamedTuple):
    driver_node: Optional[str]
    executor_nodes: list[str]
    has_capacity: bool
    efficiency_max: float
    efficiency_cpu: float
    efficiency_memory: float
    efficiency_gpu: float


class WindowRequest(NamedTuple):
    """One serving request inside a coalesced /predicates window
    (see PlacementSolver.pack_window)."""

    # (driver_resources, executor_resources, executor_count, skippable) in
    # FIFO order; the LAST row is the request's own application, earlier
    # rows are its pending earlier drivers (fitEarlierDrivers semantics —
    # every unscheduled earlier driver re-packs hypothetically, even one
    # whose own admission this window just committed; the reference does
    # the same, resource.go:221-258 + sparkpods.go:60-77).
    rows: Sequence[tuple]
    driver_candidate_names: Sequence[str]
    domain_node_names: Sequence[str] | None = None  # None = all valid nodes
    domain_mask: "np.ndarray | None" = None  # precomputed [N] bool override


class WindowDecision(NamedTuple):
    """Outcome of one window request (see PlacementSolver.pack_window)."""

    packing: HostPacking
    admitted: bool
    # A non-skippable, still-pending earlier driver failed to fit => the
    # request fails FAILURE_EARLIER_DRIVER instead of FAILURE_FIT
    # (resource.go:241-249).
    earlier_blocked: bool


class PipelineDrainRequired(RuntimeError):
    """Raised by build_tensors_pipelined when node topology/attributes
    changed while a dispatched window is still un-fetched: the caller must
    fetch (complete) the pending window first, then retry — the fresh full
    upload would otherwise discard the in-flight window's threaded base."""


class WindowHandle:
    """A dispatched-but-not-yet-fetched window solve
    (PlacementSolver.pack_window_dispatch -> pack_window_fetch)."""

    __slots__ = (
        "strategy", "blob", "blob_future", "requests", "flat_rows",
        "host_avail", "host_schedulable", "priors", "placements", "n",
        "row_driver_req", "row_exec_req", "row_skippable", "seg_map",
        "info",
    )

    def __init__(self, *, strategy, blob, requests, flat_rows, host_avail,
                 host_schedulable, priors, n):
        self.strategy = strategy
        # Device blob, not yet transferred: flat [B, 3+emax] int32 on the
        # XLA path; [S, R, 3+emax] on the Pallas window path (seg_map set
        # — pack_window_fetch flattens the real rows after the pull).
        self.blob = blob
        # Device->host transfer started EAGERLY on a side thread at dispatch
        # (pipelined path): the ~RTT-bound pull elapses concurrently with
        # the dispatcher's host work instead of serializing after it.
        self.blob_future = None
        self.requests = requests
        self.flat_rows = flat_rows
        # Host availability view at dispatch (int64 [N,3]); the device base
        # additionally lacks the placements of `priors` (windows dispatched
        # earlier but un-fetched at this dispatch).
        self.host_avail = host_avail
        self.host_schedulable = host_schedulable
        self.priors = priors  # tuple[WindowHandle] — fetched before this one
        self.placements = None  # int64 [N,3], filled at fetch
        self.n = n
        self.row_driver_req = None  # int64 [B,3], set after dispatch
        self.row_exec_req = None
        self.row_skippable = None
        self.seg_map = None  # pallas window path: (seg_idx, row_idx)
        # Flight-recorder dispatch info: {"path", "nodes", "rows",
        # "row_bucket", "emax", "compile_cache_hit"} — set at dispatch.
        self.info = None


class PlacementSolver:
    def __init__(
        self,
        driver_label_priority: tuple[str, list[str]] | None = None,
        executor_label_priority: tuple[str, list[str]] | None = None,
        use_native: bool = True,
    ):
        self.registry = NodeRegistry()
        self._driver_label_priority = driver_label_priority
        self._executor_label_priority = executor_label_priority
        # Native C++ arena (native/runtime.cpp): per-node state is upserted
        # only when a node object actually changes, and the dense tensor
        # inputs are materialized in one C call per request instead of a
        # Python walk over every node.
        self._arena = None
        self._node_seen: dict[str, Node] = {}
        self._rank_epoch = -1
        if use_native and native.available():
            self._arena = native.ClusterArena()
        # Device-resident cluster state (VERDICT r2 #3): the last uploaded
        # tensors + their numpy source. build_tensors_cached diffs against
        # the mirror and ships only changed availability rows.
        self._dev: dict | None = None
        # Pipelined serving state (build_tensors_pipelined /
        # pack_window_dispatch / pack_window_fetch): the device availability
        # threaded ACROSS windows, an int64 mirror of it in host terms, and
        # the dispatched-but-unfetched handles. Single-threaded by contract
        # (the predicate batcher is the serialization point); the fetch pool
        # only runs stateless jax.device_get calls.
        self._pipe: dict | None = None
        self._closed = False
        # Candidate-mask memo: serving windows pass the same (usually
        # cluster-wide) candidate list once per request, and building the
        # [N] bool mask is a Python walk over every name. Keyed by the full
        # name tuple + registry epoch + padded size, so a stale mapping can
        # never serve (collision-safe: dict equality compares the tuple).
        self._cand_cache: dict[tuple, np.ndarray] = {}
        # Topology-version memo (see build_tensors' topo_version contract):
        # lets the native tensor build skip its O(nodes) sync walk between
        # requests when no node changed.
        self._topo_seen = None
        self._topo_request_mask = None  # ((version, pad, n), [pad] bool)
        self.device_state_stats = {
            "full_uploads": 0,
            "delta_uploads": 0,
            "delta_rows": 0,
            "reuse_hits": 0,
        }
        # Which device path served each dispatched window (pallas | xla).
        self.window_path_counts: dict[str, int] = {}
        # SolverTelemetry hook surface (observability/telemetry.py) — wired
        # by build_scheduler_app; None keeps every hot-path hook a single
        # attribute test.
        self.telemetry = None
        # Dispatch info of the most recent SOLO pack() ({"path", "nodes",
        # "emax", "compile_cache_hit"}) for the flight recorder.
        # Single-threaded by the same contract as the pipeline state.
        self.last_solve_info: dict | None = None

    @property
    def uses_native_arena(self) -> bool:
        return self._arena is not None


    def build_tensors(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        *,
        full_node_list: bool = False,
        topo_version: Optional[int] = None,
    ):
        """`usage` / `overhead` are either {node: Resources} maps (the
        reference's shape) or dense int64 [cap, 3] arrays indexed by this
        solver's registry (the incremental-tracker fast path — no
        per-reservation host walk).

        `full_node_list` asserts `nodes` is the backend's complete current
        node list (the serving contract of the cached/pipelined builders).
        `topo_version` is the backend's node-mutation counter
        (store/backend.py nodes_version) captured by the caller BEFORE
        listing `nodes` — capture-before-list means a concurrent mutation
        makes the version look stale (extra walk, safe) and never fresh
        (skipped walk over unsynced state, unsafe). Both together enable
        skipping the O(nodes) sync walk and memoizing the request mask."""
        if self._arena is not None:
            return self._build_tensors_native(
                list(nodes), usage, overhead,
                full_node_list=full_node_list, topo_version=topo_version,
            )
        for n in nodes:
            self.registry.intern(n.name)
        pad = _bucket(self.registry.capacity, 8)
        return build_cluster_tensors(
            list(nodes),
            usage,
            overhead,
            self.registry,
            driver_label_priority=self._driver_label_priority,
            executor_label_priority=self._executor_label_priority,
            pad_to=pad,
        )

    def build_tensors_cached(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        topo_version: Optional[int] = None,
    ) -> ClusterTensors:
        """Device-resident cluster state with delta updates (VERDICT r2 #3).

        Builds the host tensors exactly like `build_tensors`, then keeps the
        device copy ALIVE between requests: when only availability rows
        changed since the previous call (reservation deltas, overhead
        drift), a jitted row-scatter ships just those rows; unchanged state
        re-uses the resident arrays outright; topology/attribute changes
        (any non-availability field) trigger a full upload. The numpy source
        rides along as `.host` so host-side consumers (efficiency, masks)
        never pull arrays back off the device.

        Callers should pass the FULL current node list and express
        per-request affinity/candidate filtering through the kernels'
        domain/candidate masks — that keeps the cached topology stable
        across requests (SURVEY.md §7 "persistent device state + small
        delta updates")."""
        host = self.build_tensors(
            nodes, usage, overhead,
            full_node_list=True, topo_version=topo_version,
        )
        stats = self.device_state_stats
        dev = self._dev
        tensors = None
        if dev is not None and dev["host"].available.shape == host.available.shape:
            prev = dev["host"]
            if all(
                np.array_equal(getattr(prev, f), getattr(host, f))
                for f in _STATIC_FIELDS
            ):
                dirty = np.flatnonzero(
                    np.any(prev.available != host.available, axis=1)
                )
                k = len(dirty)
                if k == 0:
                    tensors = dev["tensors"]
                    stats["reuse_hits"] += 1
                elif k <= max(32, host.available.shape[0] // 8):
                    # Bucket the row count so the scatter program compiles
                    # once per bucket; padding repeats dirty rows (set with
                    # identical values — deterministic).
                    idx = np.resize(dirty, _bucket(k, 16))
                    rows = host.available[idx]
                    new_avail = _scatter_rows(
                        dev["tensors"].available,
                        jnp.asarray(idx.astype(np.int32)),
                        jnp.asarray(rows),
                    )
                    tensors = dataclasses.replace(
                        dev["tensors"], available=new_avail
                    )
                    stats["delta_uploads"] += 1
                    stats["delta_rows"] += k
                    if self.telemetry is not None:
                        self.telemetry.on_transfer(
                            "h2d", rows.nbytes + idx.nbytes
                        )
                else:
                    tensors = dataclasses.replace(
                        dev["tensors"], available=jax.device_put(host.available)
                    )
                    stats["full_uploads"] += 1
                    if self.telemetry is not None:
                        self.telemetry.on_transfer(
                            "h2d", host.available.nbytes
                        )
        if tensors is None:
            tensors = jax.device_put(host)
            stats["full_uploads"] += 1
            if self.telemetry is not None:
                self.telemetry.on_transfer("h2d", _tensors_nbytes(host))
        tensors.host = host
        self._dev = {"host": host, "tensors": tensors}
        return tensors

    def close(self) -> None:
        """Stop accepting new pipelined fetch submits (they would enqueue a
        Future whose result nobody will pull). The fetch pool itself is
        process-shared (_shared_fetch_pool) and stays up for other
        solvers; its workers are daemon threads, so a transfer stuck on a
        dead tunnel can never block interpreter exit."""
        self._closed = True

    def discard_pipeline(self) -> None:
        """Drop the pipelined device state: the next build_tensors_pipelined
        does a full upload from the host view. Used when in-flight window
        decisions are being discarded (capacity changed under them) — the
        host view is the durable truth once every surviving window has
        applied."""
        self._pipe = None
        if self.telemetry is not None:
            self.telemetry.on_pipeline_event("discard")

    def build_tensors_pipelined(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        topo_version: Optional[int] = None,
    ) -> ClusterTensors:
        """Device-resident availability threaded ACROSS serving windows.

        Unlike build_tensors_cached (which re-uploads the host availability
        rows verbatim), this keeps the device availability equal to
        `last window's committed base` + `external deltas`: the kernel's
        `available_after` from the previous dispatch is extended with the
        ADDITIVE difference between the current host view and an int64
        mirror of what the device already embodies. Gang placements of a
        window are debited from the mirror when the window is fetched
        (pack_window_fetch), so the host's own reservation bookkeeping for
        those gangs does not get shipped a second time — and a gang whose
        reservation the host then failed to create is automatically
        restored by the next delta. This is what makes it safe to DISPATCH
        window k+1 before FETCHING window k (the pipelined serving loop):
        k's admissions ride the device-side thread, not the host view.

        Raises PipelineDrainRequired when a non-availability field changed
        while a window is still in flight — fetch it first, then retry.
        Single-threaded by contract (the predicate batcher thread)."""
        host = self.build_tensors(
            nodes, usage, overhead,
            full_node_list=True, topo_version=topo_version,
        )
        stats = self.device_state_stats
        p = self._pipe
        if (
            p is not None
            and p["host"].available.shape == host.available.shape
            and all(
                np.array_equal(getattr(p["host"], f), getattr(host, f))
                for f in _STATIC_FIELDS
            )
        ):
            cur = host.available.astype(np.int64)
            delta = cur - p["mirror"]
            dirty = np.flatnonzero(delta.any(axis=1))
            avail = p["avail"]
            k = len(dirty)
            # An external availability swing too large for the int32 delta
            # rows falls through to a FULL re-upload instead of wrapping
            # silently and corrupting the device base (with windows in
            # flight that raises PipelineDrainRequired below — the standard
            # retry contract of this method).
            fits_i32 = k == 0 or (
                delta.min() >= np.iinfo(np.int32).min
                and delta.max() <= np.iinfo(np.int32).max
            )
            if not fits_i32 and p["unfetched"]:
                if self.telemetry is not None:
                    self.telemetry.on_pipeline_event("drain")
                raise PipelineDrainRequired(
                    "availability delta exceeds int32 with a window in flight"
                )
            if fits_i32:
                if k:
                    # Pad with a repeated index but ZERO delta rows: .add
                    # is cumulative, so padding must contribute nothing.
                    kb = _bucket(k, 16)
                    idx = np.full(kb, dirty[0], dtype=np.int32)
                    idx[:k] = dirty
                    rows = np.zeros((kb, host.available.shape[1]), np.int32)
                    rows[:k] = delta[dirty]
                    avail = _add_rows(avail, jnp.asarray(idx), jnp.asarray(rows))
                    stats["delta_uploads"] += 1
                    stats["delta_rows"] += k
                    if self.telemetry is not None:
                        self.telemetry.on_transfer(
                            "h2d", rows.nbytes + idx.nbytes
                        )
                else:
                    stats["reuse_hits"] += 1
                tensors = dataclasses.replace(p["tensors"], available=avail)
                tensors.host = host
                p.update(host=host, tensors=tensors, avail=avail, mirror=cur)
                return tensors
        if p is not None and p["unfetched"]:
            if self.telemetry is not None:
                self.telemetry.on_pipeline_event("drain")
            raise PipelineDrainRequired(
                "cluster topology changed with a window in flight"
            )
        tensors = jax.device_put(host)
        tensors.host = host
        stats["full_uploads"] += 1
        if self.telemetry is not None:
            self.telemetry.on_transfer("h2d", _tensors_nbytes(host))
        self._pipe = {
            "host": host,
            "tensors": tensors,
            "avail": tensors.available,
            "mirror": host.available.astype(np.int64),
            "unfetched": [],
        }
        return tensors

    def _label_rank(self, node: Node, prio) -> int:
        if prio is None:
            return INT32_INF
        label, values = prio
        val = node.labels.get(label)
        if val is not None and val in values:
            return values.index(val)
        return INT32_INF

    def _build_tensors_native(
        self,
        nodes: list[Node],
        usage,
        overhead,
        *,
        full_node_list: bool = False,
        topo_version: Optional[int] = None,
    ) -> ClusterTensors:
        """Arena-backed ClusterTensors. Deviation from the Python builder,
        deliberate: name ranks are GLOBAL over all known nodes rather than
        recomputed over the request's filtered subset — the rank values
        differ but their relative order (all the sort kernels consume) is
        identical for any subset."""
        arena = self._arena
        seen = self._node_seen
        # Topology-version fast path: when the backend exposes a node
        # version (store/backend.py nodes_version) and it hasn't moved
        # since the last build, the whole O(nodes) identity walk is
        # skipped — at 10k nodes this walk was a measured serving-window
        # hotspot despite doing no upserts.
        # Skipping is safe regardless of subset: an unchanged version means
        # no node was created/updated/deleted since the FULL-list build that
        # recorded it, so the walk would upsert nothing.
        topo = topo_version
        if not (topo is not None and topo == self._topo_seen):
            changed_names = False
            for node in nodes:
                if seen.get(node.name) is node:
                    continue
                if node.name not in seen:
                    changed_names = True
                seen[node.name] = node
                idx = self.registry.intern(node.name)
                arena.upsert(
                    idx,
                    node.allocatable.as_array(),
                    self.registry.zone_id(node.zone),
                    node.unschedulable,
                    node.ready,
                    self._label_rank(node, self._driver_label_priority),
                    self._label_rank(node, self._executor_label_priority),
                )
            if changed_names or self._rank_epoch < 0:
                ordered = sorted(seen)
                arena.set_name_ranks(
                    [self.registry.index_of(name) for name in ordered]
                )
                self._rank_epoch += 1
            if full_node_list and topo is not None:
                # Only a full-list walk proves the arena is synced for this
                # version; a filtered subset must not suppress future walks.
                self._topo_seen = topo
        pad = _bucket(self.registry.capacity, 8)

        usage_t = self._dense_or_scatter(usage, pad)
        overhead_t = self._dense_or_scatter(overhead, pad)

        fields = arena.snapshot(pad, usage_t, overhead_t)
        tensors = ClusterTensors(*fields)
        # The arena knows every node ever seen; this request's candidate set
        # is the (selector-filtered) `nodes` list — mask the rest out. The
        # O(nodes) index walk is memoized on the topology version (the
        # extender passes the full node list, so the mask only changes when
        # a node does).
        # Only a FULL node list is memoizable (caller-asserted): a filtered
        # subset of the same length would collide.
        cacheable = topo is not None and full_node_list
        cached = self._topo_request_mask
        if (
            cacheable
            and cached is not None
            and cached[0] == (topo, pad, len(nodes))
        ):
            request_mask = cached[1]
        else:
            request_mask = np.zeros(pad, dtype=bool)
            idxs = [self.registry.index_of(n.name) for n in nodes]
            request_mask[[i for i in idxs if i is not None and i < pad]] = True
            if cacheable:
                self._topo_request_mask = (
                    (topo, pad, len(nodes)), request_mask,
                )
        tensors.valid &= request_mask
        return tensors

    def _dense_or_scatter(self, mapping, pad: int) -> np.ndarray:
        """[pad, 3] int64: a dense array is padded/truncated in one vectorized
        op (rows past `pad` can only be registry-unused zeros); a map is
        scattered entry-by-entry (the fallback path)."""
        out = np.zeros((pad, NUM_DIMS), dtype=np.int64)
        if isinstance(mapping, np.ndarray):
            rows = min(pad, mapping.shape[0])
            out[:rows] = mapping[:rows]
            return out
        for name, res in mapping.items():
            idx = self.registry.index_of(name)
            if idx is not None and idx < pad:
                out[idx] += res.as_array()
        return out

    def candidate_mask(self, tensors, node_names: Sequence[str]) -> np.ndarray:
        n = tensors.available.shape[0]
        names = tuple(node_names)

        def _build() -> np.ndarray:
            mask = np.zeros(n, dtype=bool)
            index_of = self.registry.index_of
            for name in names:
                idx = index_of(name)
                if idx is not None and idx < n:
                    mask[idx] = True
            # Shared across callers — must be treated read-only (every
            # consumer either copies via `&`/stack or hands it straight to
            # the device).
            mask.flags.writeable = False
            return mask

        for _ in range(4):
            epoch = self.registry.epoch
            if epoch & 1:  # mutation in flight: the walk would be torn
                continue
            key = (n, epoch, names)
            mask = self._cand_cache.get(key)
            if mask is not None:
                return mask
            mask = _build()
            # Seqlock read: the walk is valid only if the epoch is unchanged
            # after it — otherwise the mask may mix old and new name->index
            # mappings; rebuild.
            if self.registry.epoch == epoch:
                if len(self._cand_cache) >= 64:
                    self._cand_cache.clear()
                self._cand_cache[key] = mask
                return mask
        # Registry churning continuously: one consistent build under the
        # registry's lock (uncached — the epoch is stale by construction).
        return self.registry.read_consistent(_build)

    def _num_zones_bucket(self) -> int:
        return _bucket(max(len(self.registry._zone_names), 1), 2)

    def pack(
        self,
        strategy: str,
        tensors,
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_candidate_names: Sequence[str],
        domain_mask: np.ndarray | None = None,
    ) -> HostPacking:
        from spark_scheduler_tpu.tracing import tracer

        n = tensors.available.shape[0]
        host = _host_view(tensors)
        driver_mask = self.candidate_mask(tensors, driver_candidate_names)
        if domain_mask is None:
            domain_mask = np.asarray(host.valid)
        emax = _bucket(max(executor_count, 1), 8)
        tel = self.telemetry
        compiles_before = tel.compile_count() if tel is not None else None
        # The span covers dispatch AND the device->host transfer — the
        # transfer is where the device work is actually awaited.
        with tracer().span(
            "solve", strategy=strategy, nodes=n, executors=executor_count
        ):
            # ONE device->host transfer (one flat int32 blob) for the whole
            # decision: on a tunneled TPU every fetched array is a full RPC
            # round-trip (SURVEY.md §7 latency budget). Efficiency reporting
            # runs as pure numpy on the host-resident cluster arrays — zero
            # extra pulls.
            blob = jax.device_get(
                _pack_blob(
                    tensors,
                    jnp.asarray(driver_resources.as_array()),
                    jnp.asarray(executor_resources.as_array()),
                    jnp.int32(executor_count),
                    jnp.asarray(driver_mask),
                    jnp.asarray(domain_mask),
                    fill=strategy,
                    emax=emax,
                    num_zones=self._num_zones_bucket(),
                )
            )
        self.last_solve_info = {
            "path": "xla",
            "nodes": n,
            "emax": emax,
            "compile_cache_hit": (
                tel.compile_count() == compiles_before
                if tel is not None
                else None
            ),
        }
        if tel is not None:
            tel.on_pack(nodes=n, emax=emax)
            tel.on_transfer("d2h", getattr(blob, "nbytes", 0))
        driver_idx = int(blob[0])
        has_cap = bool(blob[1])
        executor_nodes = blob[2:]
        eff = avg_packing_efficiency_np(
            np.asarray(host.schedulable),
            np.asarray(host.available),
            driver_idx,
            executor_nodes,
            driver_resources.as_array(),
            executor_resources.as_array(),
        )
        exec_idx = [int(x) for x in executor_nodes if int(x) >= 0]
        return HostPacking(
            driver_node=self.registry.name_of(driver_idx) if driver_idx >= 0 else None,
            executor_nodes=[self.registry.name_of(i) for i in exec_idx],
            has_capacity=has_cap,
            efficiency_max=float(eff.max),
            efficiency_cpu=float(eff.cpu),
            efficiency_memory=float(eff.memory),
            efficiency_gpu=float(eff.gpu),
        )

    def can_batch(self, strategy: str) -> bool:
        return strategy in BATCHABLE_STRATEGIES

    def pack_window(
        self,
        strategy: str,
        tensors,
        requests: Sequence[WindowRequest],
    ) -> list[WindowDecision]:
        """Serve a WINDOW of coalesced /predicates driver requests in ONE
        device program (VERDICT r2 #1).

        Each request becomes a SEGMENT of the scan: its pending earlier
        drivers (hypothetical rows) followed by its own application (the
        committing row). Availability rewinds to a threaded base between
        segments, so each segment sees exactly what that request's solo
        solve would have seen — decisions are identical to serving the
        requests one at a time in window order, including the FIFO
        earlier-driver semantics (resource.go:221-258). Within a segment
        the priority orders are computed ONCE from the segment-start
        availability, exactly as the reference sorts once per request
        (resource.go:299) and reuses the orders while only availability
        mutates.

        Replaces the reference's one-pod-per-call extender protocol
        limitation (cmd/endpoints.go:28-42, SURVEY.md §2d row 1): the
        device cost is one scan over sum(rows) steps instead of one full
        RPC + solve round-trip per request.

        Synchronous form: dispatch + fetch back to back. The PIPELINED
        serving path splits the two (pack_window_dispatch /
        pack_window_fetch) so the next window's host build and device
        dispatch overlap the previous window's blocking decision pull.
        """
        return self.pack_window_fetch(
            self.pack_window_dispatch(strategy, tensors, requests)
        )

    def pack_window_dispatch(
        self,
        strategy: str,
        tensors,
        requests: Sequence[WindowRequest],
    ) -> "WindowHandle":
        """Build the segmented batch and DISPATCH the device solve without
        blocking on the result. Returns a handle for pack_window_fetch.

        When `tensors` came from build_tensors_pipelined, the threaded
        committed-base availability (still on device, never fetched) is
        recorded as the base for the NEXT pipelined build, and the handle
        notes which earlier windows were still un-fetched — their placements
        are subtracted from this window's host-side base snapshot at fetch
        time, so the host reconstruction sees exactly the availability the
        device saw."""
        if strategy not in BATCHABLE_STRATEGIES:
            raise ValueError(f"strategy {strategy!r} is not batchable")
        if self._closed:
            # Fail fast like ThreadPoolExecutor after shutdown — and BEFORE
            # any device work or pipeline mutation, so a raised dispatch
            # leaves no committed-but-orphaned window behind for a retry to
            # double-commit.
            raise RuntimeError("cannot schedule new futures after shutdown")
        if not requests:
            return WindowHandle(
                strategy=strategy, blob=None, requests=(), flat_rows=[],
                host_avail=None, host_schedulable=None, priors=(), n=0,
            )
        n = tensors.available.shape[0]
        host = _host_view(tensors)
        valid_np = np.asarray(host.valid)

        flat_rows: list[tuple] = []
        commit: list[bool] = []
        reset: list[bool] = []
        cand_rows: list[np.ndarray] = []
        dom_rows: list[np.ndarray] = []
        cand_per_req: list[np.ndarray] = []
        dom_per_req: list[np.ndarray] = []
        for req in requests:
            cand = self.candidate_mask(tensors, req.driver_candidate_names)
            if req.domain_mask is not None:
                dom = np.asarray(req.domain_mask) & valid_np
            elif req.domain_node_names is not None:
                dom = self.candidate_mask(tensors, req.domain_node_names) & valid_np
            else:
                dom = valid_np
            cand_per_req.append(cand)
            dom_per_req.append(dom)
            for j, row in enumerate(req.rows):
                flat_rows.append(row)
                commit.append(j == len(req.rows) - 1)
                reset.append(j == 0)
                cand_rows.append(cand)
                dom_rows.append(dom)

        b = len(flat_rows)
        # FIFO windows repeat the SAME row objects across requests (request
        # i's hypothetical prefix shares the pending-driver parse of request
        # i+1), so materialize each distinct Resources once.
        arr_memo: dict[int, np.ndarray] = {}

        def as_arr(res) -> np.ndarray:
            a = arr_memo.get(id(res))
            if a is None:
                a = res.as_array()
                arr_memo[id(res)] = a
            return a

        drv_arr = np.stack([as_arr(r[0]) for r in flat_rows])
        exc_arr = np.stack([as_arr(r[1]) for r in flat_rows])
        counts = np.asarray([r[2] for r in flat_rows], np.int32)
        skip_arr = np.asarray([bool(r[3]) for r in flat_rows])
        emax = _bucket(max(int(counts.max()), 1), 8)
        from spark_scheduler_tpu.tracing import tracer

        # Route the segmented window to the Pallas path when the backend
        # compiles Mosaic and the strategy is a plain fill (ops/
        # pallas_window): XLA sorts per segment, Mosaic walks the rows with
        # availability in VMEM. Decisions identical (parity-suite pinned).
        seg_map = None
        from spark_scheduler_tpu.ops.pallas_window import (
            window_pallas_eligible,
        )

        use_pallas = window_pallas_eligible(strategy)
        path = "pallas" if use_pallas else "xla"
        self.window_path_counts[path] = (
            self.window_path_counts.get(path, 0) + 1
        )
        tel = self.telemetry
        compiles_before = tel.compile_count() if tel is not None else None
        seg_bucket = 1
        with tracer().span(
            "solve-dispatch", strategy=strategy, nodes=n,
            window_requests=len(requests), window_rows=b, batched=True,
            path=path,
        ):
            if use_pallas:
                win, seg_idx, row_idx, s_pad, r_pad = (
                    _build_segmented_window(
                        requests, drv_arr, exc_arr, counts, skip_arr,
                        cand_per_req, dom_per_req,
                    )
                )
                seg_map = (seg_idx, row_idx)
                row_bucket, seg_bucket = r_pad, s_pad
                blob, avail_after = _window_blob_pallas(
                    tensors, win, fill=strategy,
                    emax=emax, num_zones=self._num_zones_bucket(),
                )
            else:
                row_bucket = _bucket(b, 32)
                apps = make_app_batch(
                    drv_arr,
                    exc_arr,
                    counts,
                    skippable=skip_arr,
                    # Coarse row bucket (32): window row counts jitter with
                    # load and FIFO depth; each distinct bucket is a fresh
                    # XLA compile, which on a remote TPU stalls live
                    # serving for seconds.
                    pad_to=row_bucket,
                    driver_cand=np.stack(cand_rows),
                    domain=np.stack(dom_rows),
                    commit=commit,
                    reset=reset,
                )
                blob, avail_after = _window_blob(
                    tensors, apps, fill=strategy, emax=emax,
                    num_zones=self._num_zones_bucket(),
                )

        info = {
            "path": path,
            "nodes": n,
            "rows": b,
            "row_bucket": row_bucket * seg_bucket,
            "emax": emax,
            "compile_cache_hit": (
                tel.compile_count() == compiles_before
                if tel is not None
                else None
            ),
        }
        # The solo batched-admission path (a single-segment pack_window)
        # reads this right after its solve, like pack()'s callers do.
        self.last_solve_info = info
        if tel is not None:
            tel.on_window_dispatch(
                path, nodes=n, rows=b, row_bucket=row_bucket,
                segment_bucket=seg_bucket,
            )
            tel.on_transfer(
                "h2d",
                drv_arr.nbytes + exc_arr.nbytes + counts.nbytes
                + skip_arr.nbytes,
            )
        priors: tuple = ()
        p = self._pipe
        pipelined = p is not None and tensors is p["tensors"]
        if pipelined:
            priors = tuple(p["unfetched"])
            p["avail"] = avail_after  # the next pipelined build extends this
        handle = WindowHandle(
            strategy=strategy,
            blob=blob,
            requests=tuple(requests),
            flat_rows=flat_rows,
            host_avail=np.array(np.asarray(host.available), dtype=np.int64),
            host_schedulable=np.asarray(host.schedulable),
            priors=priors,
            n=n,
        )
        # Stacked per-row requests for the fetch-side reconstruction: int64
        # so the vectorized subtractions against the int64 base never wrap.
        handle.row_driver_req = drv_arr.astype(np.int64)
        handle.row_exec_req = exc_arr.astype(np.int64)
        handle.row_skippable = skip_arr
        handle.seg_map = seg_map  # pallas path: [S,R] blob -> flat rows
        handle.info = info
        if pipelined:
            p["unfetched"].append(handle)
            # Start the device->host pull NOW on the fetch thread: over a
            # tunneled device the transfer RTT dominates, and starting it at
            # dispatch lets it elapse under the next window's host build.
            handle.blob_future = _shared_fetch_pool().submit(
                jax.device_get, blob
            )
        return handle

    def pack_window_fetch(self, handle: "WindowHandle") -> list[WindowDecision]:
        """Block on a dispatched window's decisions and reconstruct the
        per-request outcomes (the second half of pack_window)."""
        if not handle.requests:
            return []
        from spark_scheduler_tpu.tracing import tracer

        requests, n = handle.requests, handle.n
        with tracer().span(
            "solve", strategy=handle.strategy, nodes=n,
            window_requests=len(requests), batched=True,
        ):
            try:
                if handle.blob_future is not None:
                    blob = handle.blob_future.result()
                else:
                    blob = jax.device_get(handle.blob)
            except Exception:
                # The device base embodies this window's (now unknowable)
                # placements while no reservation was created for them.
                # Drop the whole pipeline: the next build does a full upload
                # from the host view — the durable truth — restoring the
                # lost gangs' capacity. Later in-flight handles still fetch
                # fine (their blobs are independent); they just skip the
                # mirror debit of a dead pipeline.
                self._pipe = None
                if self.telemetry is not None:
                    self.telemetry.on_pipeline_event("fetch-failure")
                raise
        if self.telemetry is not None:
            self.telemetry.on_transfer("d2h", getattr(blob, "nbytes", 0))
        if handle.seg_map is not None:
            # Pallas window path: the device blob is [S, R, 3+emax];
            # flatten the real rows back into flat-row order host-side.
            blob = np.asarray(blob)[handle.seg_map[0], handle.seg_map[1]]
        drivers = blob[:, 0]
        admitted = blob[:, 1].astype(bool)
        packed = blob[:, 2].astype(bool)
        execs = blob[:, 3:]

        # Host-side reconstruction for per-request packing efficiency: the
        # availability each admitted request's final pack saw = the
        # host view at dispatch, minus the committed placements of windows
        # that were still in flight then (the device had them threaded),
        # minus committed placements of earlier segments, minus in-segment
        # admitted hypothetical placements. Vectorized over each segment's
        # rows (a FIFO window carries O(requests x pending) hypothetical
        # rows — per-row Python was the serving loop's hot spot).
        drv64 = handle.row_driver_req
        exc64 = handle.row_exec_req
        skip = handle.row_skippable
        decisions: list[WindowDecision] = []
        base = handle.host_avail.copy()
        for prior in handle.priors:
            if prior.placements is not None:
                base -= prior.placements
        placements = np.zeros_like(base)
        row = 0
        for r, req in enumerate(requests):
            nrows = len(req.rows)
            hyp = np.arange(row, row + nrows - 1)
            real = row + nrows - 1
            row += nrows
            req_admitted = bool(admitted[real])
            earlier_blocked = False
            eff = None
            if nrows > 1:
                adm_h = admitted[hyp]
                earlier_blocked = bool(
                    np.any(~adm_h & ~packed[hyp] & ~skip[hyp])
                )
            if req_admitted:
                seg_avail = base.copy()
                if nrows > 1:
                    dsel = adm_h & (drivers[hyp] >= 0)
                    if dsel.any():
                        np.subtract.at(
                            seg_avail, drivers[hyp][dsel], drv64[hyp][dsel]
                        )
                    e = execs[hyp]
                    esel = adm_h[:, None] & (e >= 0)
                    if esel.any():
                        ri, _si = np.nonzero(esel)
                        np.subtract.at(seg_avail, e[esel], exc64[hyp][ri])
                eff = avg_packing_efficiency_np(
                    handle.host_schedulable,
                    seg_avail,
                    int(drivers[real]),
                    execs[real],
                    drv64[real],
                    exc64[real],
                )
                # Commit this request's placement into the base for the
                # segments after it (mirrors the device-side base thread).
                if drivers[real] >= 0:
                    base[drivers[real]] -= drv64[real]
                    placements[drivers[real]] += drv64[real]
                ev = execs[real]
                ev = ev[ev >= 0]
                if ev.size:
                    np.subtract.at(base, ev, exc64[real])
                    np.add.at(placements, ev, exc64[real])
            exec_idx = [int(x) for x in execs[real] if int(x) >= 0]
            decisions.append(
                WindowDecision(
                    packing=HostPacking(
                        driver_node=(
                            self.registry.name_of(int(drivers[real]))
                            if drivers[real] >= 0
                            else None
                        ),
                        executor_nodes=[
                            self.registry.name_of(x) for x in exec_idx
                        ],
                        has_capacity=bool(packed[real]),
                        efficiency_max=float(eff.max) if eff else 0.0,
                        efficiency_cpu=float(eff.cpu) if eff else 0.0,
                        efficiency_memory=float(eff.memory) if eff else 0.0,
                        efficiency_gpu=float(eff.gpu) if eff else 0.0,
                    ),
                    admitted=req_admitted,
                    earlier_blocked=earlier_blocked,
                )
            )
        handle.placements = placements
        # Pipeline accounting: the device base now permanently embodies this
        # window's committed gangs; debit them from the mirror so the next
        # build's host-vs-mirror delta ships only EXTERNAL changes. When the
        # host later fails to create one of these reservations, its usage
        # never reaches the host view and the next delta restores the gang's
        # capacity on device automatically (self-correcting drift).
        p = self._pipe
        if p is not None and handle in p["unfetched"]:
            p["unfetched"].remove(handle)
            p["mirror"] -= placements
        return decisions

    def subtract_usage(self, tensors, usage: dict[str, Resources]):
        """Subtract per-node usage from availability in-place-equivalent
        (NodeGroupSchedulingMetadata.SubtractUsageIfExists,
        resources.go:128-135); returns new tensors."""
        avail = np.array(tensors.available)
        for name, res in usage.items():
            idx = self.registry.index_of(name)
            if idx is not None and idx < avail.shape[0]:
                avail[idx] = avail[idx] - res.as_array()
        import dataclasses as _dc

        return _dc.replace(tensors, available=avail)
