"""PlacementSolver — the host <-> device boundary of the scheduler.

Everything above this module speaks names and Resources; everything below it
(ops/) speaks int32 tensors over a stable node-index space. The solver:

  - interns nodes into the NodeRegistry and builds ClusterTensors with
    padded (bucketed) shapes so XLA compile caches stay warm across node
    count / executor count jitter (SURVEY.md §7 "Dynamic shapes");
  - dispatches to the jitted packing kernels;
  - maps Packing index results back to node names.

This replaces the reference's per-request map-building + sort + greedy loops
(resource.go:287-323) with one device program per request.
"""

from __future__ import annotations

import dataclasses
import itertools as _itertools
import os as _os
import threading
import time as _time
import warnings as _warnings
import weakref as _weakref
from typing import NamedTuple, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from spark_scheduler_tpu import native
from spark_scheduler_tpu.faults.errors import (
    AllSlotsQuarantinedError,
    DegradedUnavailableError,
    classify_slot_failure,
)
from spark_scheduler_tpu.models.cluster import (
    pad_bucket,
    ClusterTensors,
    NodeRegistry,
    build_cluster_tensors,
    cluster_from_statics,
    cluster_statics,
)
from spark_scheduler_tpu.models.kube import Node
from spark_scheduler_tpu.models.resources import INT32_INF, NUM_DIMS, Resources
from spark_scheduler_tpu.ops import BINPACK_FUNCTIONS
from spark_scheduler_tpu.ops.batched import batched_fifo_pack, make_app_batch
from spark_scheduler_tpu.ops.efficiency import avg_packing_efficiency_np

# Every strategy batches: the plain fills run as the scan's executor fill,
# and the single-AZ wrappers run their per-zone pack + efficiency-scored
# zone selection inside the scan step (ops/batched.py _SINGLE_AZ_INNER,
# VERDICT r2 #2). Derived, not enumerated — a new strategy registered in
# BINPACK_FUNCTIONS must also be taught to the batched scan.
BATCHABLE_STRATEGIES = frozenset(BINPACK_FUNCTIONS)

# Simulated-RTT device shim (testing/rtt_shim.py). When installed, the
# serving path calls it with "h2d" on the dispatcher thread at every
# window-batch upload/dispatch, "dispatch" on the thread running a pooled
# slot's program launch, and "d2h" on the thread paying a decision-blob
# pull — each call sleeps its configured share of a device round trip, so
# the fused dispatch's RTT amortization is benchable on CPU. None keeps
# every hot-path hook a single global read.
_DEVICE_SHIM = None


def set_device_shim(shim) -> None:
    """Install (or clear, with None) the process-wide device shim."""
    global _DEVICE_SHIM
    _DEVICE_SHIM = shim


def _shim(kind: str) -> None:
    s = _DEVICE_SHIM
    if s is not None:
        s(kind)


def _shimmed_device_get(x):
    """jax.device_get with the simulated d2h boundary, on the calling
    (fetch-pool) thread — concurrent pulls overlap exactly as the real
    tunnel's concurrent device_get RPCs do."""
    _shim("d2h")
    return jax.device_get(x)

def _build_segmented_window(
    requests, drv_arr, exc_arr, counts, skip_arr, cand_per_req, dom_per_req
):
    """Segment-major [S, R] arrays for the Pallas window path
    (ops/pallas_window.make_segmented_window), with S and R BUCKETED
    coarsely: every (s_pad, r_pad) pair is a separate scan-over-segments
    compile, and padding segments are skipped at runtime (lax.cond on
    row_count) so coarse S padding costs no device time. Returns
    (SegmentedWindow, seg_idx, row_idx) — host numpy index arrays mapping
    each flat row to its [S, R] position (used by pack_window_fetch to
    flatten the fetched blob)."""
    from spark_scheduler_tpu.ops.pallas_window import (
        segmented_window_from_flat,
    )

    s = len(requests)
    rc = np.asarray([len(req.rows) for req in requests], np.int32)
    s_pad = 4
    while s_pad < s:
        s_pad *= 8
    r_pad = 16
    while r_pad < int(rc.max()):
        r_pad *= 4
    win, seg_idx, row_idx = segmented_window_from_flat(
        drv_arr, exc_arr, counts, skip_arr, rc, cand_per_req, dom_per_req,
        pad_segments=s_pad, pad_rows=r_pad,
    )
    return win, seg_idx, row_idx, s_pad, r_pad


# THE shared sizing function (models/cluster.pad_bucket): store masters
# and solver pads must agree byte-for-byte for the zero-copy fast paths.
_bucket = pad_bucket


def _host_view(tensors) -> ClusterTensors:
    """Host-resident numpy view of cluster tensors. Device-cached tensors
    (build_tensors_cached) carry their numpy source as `.host`; using it for
    host-side math (efficiency, masks, reconstruction) avoids pulling full
    arrays back over a tunneled device link."""
    return getattr(tensors, "host", tensors)


def _tensors_nbytes(host) -> int:
    """Total byte size of a host ClusterTensors — what a full device upload
    ships (telemetry's h2d accounting)."""
    total = 0
    for f in dataclasses.fields(host):
        arr = getattr(host, f.name, None)
        total += getattr(arr, "nbytes", 0)
    return total


def _gather_statics_host(host, keep: np.ndarray, k_real: int) -> tuple:
    """Host-side gather of the static cluster fields onto a (padded) kept
    row set for the pruned sub-cluster upload. Padding repeats keep[0];
    the padded rows' `valid` is forced False so they are transparent to
    the kernel (eligibility, zone sums, capacity all mask on valid)."""
    fields = [np.asarray(f)[keep] for f in cluster_statics(host)]
    valid = fields[-1].copy()  # cluster_statics order ends with `valid`
    valid[k_real:] = False
    fields[-1] = valid
    return tuple(fields)


# Fields that force a full re-upload when they change (node topology /
# attribute changes — rare next to availability churn).
_STATIC_FIELDS = (
    "schedulable",
    "zone_id",
    "name_rank",
    "label_rank_driver",
    "label_rank_executor",
    "unschedulable",
    "ready",
    "valid",
)


@jax.jit
def _scatter_rows(avail, idx, rows):
    """Jitted row update for the device-resident availability tensor.
    Duplicate indices carry identical rows (bucketing pads by repeating a
    dirty row), so .set is deterministic."""
    return avail.at[idx].set(rows)


class _DaemonFetchPool:
    """Minimal fetch pool with DAEMON workers: a device transfer stuck on a
    dead tunnel must never block interpreter exit, which
    ThreadPoolExecutor's non-daemon workers (joined by its atexit hook)
    would. Futures are concurrent.futures.Future — result()/done()
    compatible with the executor API the handles expose.

    ONE pool is shared by every solver in the process
    (_shared_fetch_pool): the workers run stateless jax.device_get calls,
    so there is nothing per-solver about them, and a pool per solver
    accumulates leaked daemon threads wherever solvers are created without
    a paired close() (each test harness, every rebuilt app). A full test
    run leaked 100+ such threads and died with a native-thread segfault;
    the shared pool bounds the cost at `workers` threads per process."""

    def __init__(self, workers: int = 4, name: str = "window-blob-fetch"):
        import queue as _queue

        self._q: "_queue.Queue" = _queue.Queue()
        self._name = name
        self._threads = []
        for _ in range(workers):
            self._spawn_worker()

    def _spawn_worker(self) -> None:
        t = threading.Thread(
            target=self._run, daemon=True,
            name=f"{self._name}-{len(self._threads)}",
        )
        t.start()
        self._threads.append(t)

    def ensure_workers(self, n: int) -> None:
        """Grow the pool to at least `n` daemon workers (never shrinks:
        threads are parked on a queue and cost nothing idle). Lets the
        solve pool size itself to the DEVICE pool that actually exists
        instead of a hardcoded worst case (ISSUE 15 satellite)."""
        while len(self._threads) < n:
            self._spawn_worker()

    @property
    def worker_count(self) -> int:
        return len(self._threads)

    def _run(self) -> None:
        while True:
            fut, fn = self._q.get()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as exc:  # delivered via future.result()
                fut.set_exception(exc)
                if isinstance(exc, KeyboardInterrupt):
                    # Interpreter-exit signal: deliver to the waiter AND
                    # re-raise it in the main thread — a bare raise here
                    # would only kill this worker (the process-wide pool
                    # never replenishes, so fetches would hang forever)
                    # without interrupting anything (ISSUE 9 satellite).
                    import _thread

                    _thread.interrupt_main()

    def submit(self, fn, *args):
        from concurrent.futures import Future

        fut: Future = Future()
        self._q.put((fut, lambda: fn(*args)))
        return fut


_shared_pool: _DaemonFetchPool | None = None
_shared_pool_lock = threading.Lock()


def _shared_fetch_pool() -> _DaemonFetchPool:
    """The process-wide blob-fetch pool, created on first use. Never shut
    down: the workers are daemon threads idling on a queue, so they cost
    nothing and cannot block interpreter exit. Solver.close() fail-fasts
    new submits at the solver level instead of tearing the pool down under
    other solvers."""
    global _shared_pool
    with _shared_pool_lock:
        if _shared_pool is None:
            # Several workers: over the tunnel, concurrent device_get RPCs
            # overlap almost perfectly (4 fetches take ~1 RTT), so a
            # depth-N serving pipeline divides the round trip.
            _shared_pool = _DaemonFetchPool(workers=4)
        return _shared_pool


@jax.jit
def _add_rows(avail, idx, delta_rows):
    """Jitted ADDITIVE row update for the pipelined device availability:
    ships host-side deltas without clobbering gang subtractions the device
    threaded from still-in-flight windows. Padding rows carry zero deltas,
    so duplicate padded indices are harmless."""
    return avail.at[idx].add(delta_rows)


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def _window_blob(cluster, apps, *, fill, emax, num_zones):
    """batched_fifo_pack with every per-row output packed into ONE int32
    array [B, 3+Emax]: (driver, admitted, packed, exec slots...). On a
    tunneled device each fetched array is its own RPC round trip, so the
    serving path pulls a single blob instead of four arrays. Also returns
    the threaded committed-base availability so a PIPELINED caller can
    dispatch the next window from it without fetching this one."""
    out = batched_fifo_pack(
        cluster, apps, fill=fill, emax=emax, num_zones=num_zones
    )
    blob = jnp.concatenate(
        [
            out.driver_node[:, None],
            out.admitted[:, None].astype(jnp.int32),
            out.packed[:, None].astype(jnp.int32),
            out.executor_nodes,
        ],
        axis=1,
    )
    return blob, out.available_after


@_partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def _window_blob_pallas(cluster, win, *, fill, emax, num_zones):
    """Segmented-window solve on the Pallas path (ops/pallas_window). The
    blob stays [S, R, 3+emax] — pack_window_fetch flattens the real rows
    host-side via the handle's seg_map, so the device program's shape
    depends ONLY on the (segments, rows) buckets, never on the window's
    flat row count (a third shape dimension would cross-multiply the
    compile cache)."""
    from spark_scheduler_tpu.ops.pallas_window import window_pack_pallas

    meta, execs, base_after = window_pack_pallas(
        cluster, win, fill=fill, emax=emax, num_zones=num_zones
    )
    blob = jnp.concatenate([meta[:, :, :3], execs], axis=2)
    return blob, base_after


def _window_blob_split(avail, statics, apps, *, fill, emax, num_zones):
    """`_window_blob` with the availability split from the static cluster
    fields (models.cluster.cluster_statics): the multi-device engine keeps
    only the STATIC fields resident per pool slot and threads the base
    availability as its own argument, so the donated variant can consume
    the carry in place without deleting the resident replica."""
    out = batched_fifo_pack(
        cluster_from_statics(avail, statics), apps,
        fill=fill, emax=emax, num_zones=num_zones,
    )
    blob = jnp.concatenate(
        [
            out.driver_node[:, None],
            out.admitted[:, None].astype(jnp.int32),
            out.packed[:, None].astype(jnp.int32),
            out.executor_nodes,
        ],
        axis=1,
    )
    return blob, out.available_after


def _window_blob_pruned_split(
    avail, statics, apps, zone_base, *, fill, emax, num_zones
):
    """Pruned-window solve over a GATHERED top-K sub-cluster (core/prune.py):
    `avail`/`statics` hold only the kept rows, `zone_base` carries the
    excluded rows' per-zone availability sums so zone ranks stay byte-exact
    with the full solve (ops/sorting.zone_ranks). Returns the decision blob
    plus the availability DELTA (after - before): padding rows and
    duplicate padded indices then scatter back into the resident [N,3]
    carry as additive zeros — deterministic where a .set of padded values
    would race."""
    out = batched_fifo_pack(
        cluster_from_statics(avail, statics), apps,
        fill=fill, emax=emax, num_zones=num_zones, zone_base=zone_base,
    )
    blob = jnp.concatenate(
        [
            out.driver_node[:, None],
            out.admitted[:, None].astype(jnp.int32),
            out.packed[:, None].astype(jnp.int32),
            out.executor_nodes,
        ],
        axis=1,
    )
    return blob, out.available_after - avail


_window_blob_pruned = jax.jit(
    _window_blob_pruned_split, static_argnames=("fill", "emax", "num_zones")
)


_window_blob_statics = jax.jit(
    _window_blob_split, static_argnames=("fill", "emax", "num_zones")
)


def _window_blob_split_donated(avail, statics, apps, *, fill, emax, num_zones):
    """`_window_blob_split` under a DONATION-MARKED module name. The
    persistent compilation cache must never serve a donated program from
    disk: reloaded donated executables intermittently returned WRONG
    window decisions (spurious failure-fit / shifted placements —
    reproduced 4/4 on hack/ha_shard_bench.py's chaos soak whenever the
    donated `jit__window_blob_split` entry was a cache HIT, never on a
    miss; PR 8 ran that bench cache-free as the workaround). Donation is
    invisible in the cache-key string, so the jitted wrapper gets its own
    function name and InstallConfig.serialize_jax_cache_io() gates every
    donation-marked module out of cache reads AND writes — donated
    programs always compile in-process (a few seconds once per process),
    while the expensive undonated kernels keep the cache."""
    return _window_blob_split(
        avail, statics, apps, fill=fill, emax=emax, num_zones=num_zones
    )


# Double-buffered committed base: the carry is DONATED, so available_after
# reuses the input buffer in place instead of copy-on-write. The input base
# is DEAD after the call — the pipeline threads available_after forward and
# nothing else may read the consumed buffer (tests pin the deletion).
_window_blob_donated = jax.jit(
    _window_blob_split_donated,
    static_argnames=("fill", "emax", "num_zones"),
    donate_argnums=(0,),
)


@jax.jit
def _take_rows(arr, idx):
    """Row gather for partitioned window solves: the sub-cluster's CURRENT
    availability pulled out of the threaded device base (runs on the base's
    device; the small [n_g, 3] result then moves to the partition's slot)."""
    return arr[idx]


@_partial(jax.jit, donate_argnums=(0,))
def _scatter_rows_exact_donated(base, idx, rows):
    """Scatter a partition's committed sub-base back into the (DONATED)
    global base. `idx` is the partition's EXACT domain index list — no
    padding, no duplicates — so .set is deterministic and in-place.
    The "_donated" function name is load-bearing: it marks the module for
    the persistent-cache donation gate (see _window_blob_split_donated)."""
    return base.at[idx].set(rows)


@_partial(jax.jit, donate_argnums=(0,))
def _add_rows_donated(avail, idx, delta_rows):
    """`_add_rows` with the pipelined base DONATED: external availability
    deltas update the committed base in place. The input buffer is dead
    after the call; only the returned array may be threaded forward.
    "_donated" in the name feeds the persistent-cache donation gate."""
    return avail.at[idx].add(delta_rows)


_solve_pool: "_DaemonFetchPool | None" = None
_solve_pool_lock = threading.Lock()


def _shared_solve_pool(min_workers: int = 2) -> "_DaemonFetchPool":
    """Process-wide worker pool for the multi-device engine's window solves.

    On backends whose dispatch is effectively synchronous (jax CPU runs the
    program inside the jit call), concurrent per-slot solves need their own
    host threads; on async backends the worker just owns the block+fetch.
    Shared and daemon for the same reasons as the fetch pool (see
    _DaemonFetchPool): workers run stateless jit applies and device_get
    calls, and per-solver pools would leak threads across rebuilt apps.

    SIZED TO THE DEVICE POOL, not a hardcoded 8 (ISSUE 15 satellite): the
    caller passes `min(8, 2 * pool_slots)` — two workers per slot keeps the
    upload-N+1-while-N-solves overlap engaged at pipeline depth 2 — and the
    pool grows monotonically to the largest request, so a pool-1 mesh
    solver stops carrying 7 idle daemon threads."""
    global _solve_pool
    with _solve_pool_lock:
        if _solve_pool is None:
            _solve_pool = _DaemonFetchPool(
                workers=max(1, min_workers), name="window-solve"
            )
        else:
            _solve_pool.ensure_workers(min_workers)
        return _solve_pool


class _PoolSlot:
    """One slot of the window-solve device pool: a plain device, or a
    single-axis ("nodes",) sub-mesh sharding the node axis (the GSPMD
    serving mode). Keeps the slot's resident STATIC replica (and gathered
    sub-replicas per partition domain), upload stats, and in-flight count."""

    __slots__ = (
        "placement", "label", "is_mesh", "statics", "statics_epoch",
        "sub_statics", "uploads", "last_full_upload", "inflight",
        "quarantined", "quarantined_at", "last_probe", "failure_count",
        "avail", "avail_epoch", "avail_token", "mirror",
    )

    def __init__(self, placement):
        self.placement = placement
        self.is_mesh = hasattr(placement, "devices")  # jax.sharding.Mesh
        if self.is_mesh:
            devs = list(placement.devices.flat)
            self.label = (
                f"{devs[0].platform}:{devs[0].id}-{devs[-1].id}"
            )
        else:
            self.label = f"{placement.platform}:{placement.id}"
        self.statics = None  # resident static-field tuple (full cluster)
        self.statics_epoch = -1
        # idx_key -> (epoch, statics tuple, idx device array) for gathered
        # partition sub-clusters.
        self.sub_statics: dict = {}
        # Per-slot replica decisions: "full" (statics uploaded), "delta"
        # (lagging replica caught up by scattering the journal's changed
        # rows), "reuse" (resident copy served). Availability DELTAS are
        # pipeline-level (one thread for the whole pool), counted in
        # device_state_stats.
        self.uploads = {"full": 0, "delta": 0, "reuse": 0}
        self.last_full_upload = 0.0
        self.inflight = 0
        # Slot-failure quarantine (ISSUE 9): a quarantined slot takes no
        # new dispatches until a periodic probe program succeeds on it.
        self.quarantined = False
        self.quarantined_at = 0.0
        self.last_probe = 0.0
        self.failure_count = 0
        # Per-slot delta-synced availability mirror (ISSUE 15): the last
        # full-base replica this slot held, its availability epoch, and
        # the pipeline-generation token it belongs to. A lagging slot
        # whose missed epochs are all journaled catches up by ROW-SCATTER
        # from the canonical base (the PR 11 epoch-journal pattern,
        # extended from statics to availability) instead of re-shipping
        # the full [N,3] base. INVARIANT: `avail` never aliases the
        # pipeline's canonical buffer — the canonical is donated through
        # solves, and a donated buffer must have exactly one referent.
        self.avail = None
        self.avail_epoch = -1
        self.avail_token = -1
        # Mirror sync counters: delta catch-ups (events + rows scattered),
        # full re-ships ("dense" syncs), and zero-transfer reuses.
        self.mirror = {"catchup": 0, "delta_rows": 0, "dense": 0, "reuse": 0}

    def _put(self, arr):
        if self.is_mesh:
            from spark_scheduler_tpu.parallel.solve import node_sharding

            a = jnp.asarray(arr)
            return jax.device_put(
                a, node_sharding(self.placement, a.ndim)
            )
        return jax.device_put(arr, self.placement)

    def place_avail(self, avail):
        """Move the threaded base (or a gathered sub-base) onto this slot.
        A same-device put is a no-op view, so the single-slot pool costs
        nothing extra."""
        return self._put(avail)

    def place_apps(self, apps):
        """Mesh slots shard the app batch's node-axis masks with the
        cluster; plain devices let the jit follow its committed inputs."""
        if not self.is_mesh:
            return apps
        from spark_scheduler_tpu.parallel.solve import shard_apps

        return shard_apps(apps, self.placement)

    def resident_statics(self, host, epoch, clock, telemetry, journal=None):
        """The slot's resident full-cluster static replica.

        Epoch current: serve the resident copy. Epoch behind with every
        missed epoch present in `journal` (the solver's statics-delta
        journal): catch up by scattering just the union of changed rows —
        a node event costs each slot O(changed) upload bytes instead of
        the full multi-MB blob. Anything else — first touch, a shape
        change, an evicted journal epoch (delta against a stale epoch
        must NEVER silently skew), a full upload having cleared the
        journal, or a mesh slot (sharded scatter stays out of scope) —
        re-uploads the full statics."""
        if self.statics is not None and self.statics_epoch == epoch:
            self.uploads["reuse"] += 1
            if telemetry is not None:
                telemetry.on_device_upload(self.label, "reuse", 0)
            return self.statics
        statics_np = cluster_statics(host)
        if (
            self.statics is not None
            and not self.is_mesh
            and journal
            and 0 <= self.statics_epoch < epoch
            and getattr(self.statics[0], "shape", (None,))[0]
            == np.asarray(statics_np[0]).shape[0]
            and all(
                e in journal for e in range(self.statics_epoch + 1, epoch + 1)
            )
        ):
            rows = np.unique(
                np.concatenate(
                    [
                        journal[e]
                        for e in range(self.statics_epoch + 1, epoch + 1)
                    ]
                )
            )
            idx = np.resize(rows, _bucket(len(rows), 16)).astype(np.int32)
            idx_dev = self._put(idx)
            nbytes = idx.nbytes
            updated = []
            for dev_f, host_f in zip(self.statics, statics_np):
                vals = np.asarray(host_f)[idx]
                updated.append(
                    _scatter_rows(dev_f, idx_dev, self._put(vals))
                )
                nbytes += vals.nbytes
            self.statics = tuple(updated)
            self.statics_epoch = epoch
            self.uploads["delta"] += 1
            if telemetry is not None:
                telemetry.on_device_upload(self.label, "delta", nbytes)
            return self.statics
        self.statics = tuple(self._put(f) for f in statics_np)
        self.statics_epoch = epoch
        self.uploads["full"] += 1
        self.last_full_upload = clock()
        if telemetry is not None:
            nbytes = sum(getattr(f, "nbytes", 0) for f in statics_np)
            telemetry.on_device_upload(self.label, "full", nbytes)
        return self.statics

    def sub_replica(self, host, idx_key, idx, epoch, clock, telemetry):
        """Gathered static sub-cluster for a partition domain, cached per
        (domain, statics epoch). `idx` is the host-side numpy index list."""
        cached = self.sub_statics.get(idx_key)
        if cached is not None and cached[0] == epoch:
            self.uploads["reuse"] += 1
            if telemetry is not None:
                telemetry.on_device_upload(self.label, "reuse", 0)
            return cached[1]
        statics = tuple(
            self._put(np.asarray(f)[idx]) for f in cluster_statics(host)
        )
        if len(self.sub_statics) >= 64:
            self.sub_statics.clear()
        self.sub_statics[idx_key] = (epoch, statics)
        self.uploads["full"] += 1
        self.last_full_upload = clock()
        if telemetry is not None:
            nbytes = sum(getattr(f, "nbytes", 0) for f in statics)
            telemetry.on_device_upload(self.label, "full", nbytes)
        return statics

    def release(self):
        """Drop every resident device buffer (close()/discard_pipeline():
        repeated server rebuilds in one process must not accumulate dead
        replicas on the devices). In-flight accounting resets too: a
        release accompanies dropping the pipeline, and a discarded
        window's parts are never fetched — without the reset the
        DEVICE_INFLIGHT gauge would report phantom solves forever."""
        self.statics = None
        self.statics_epoch = -1
        self.sub_statics.clear()
        self.inflight = 0
        self.avail = None
        self.avail_epoch = -1
        self.avail_token = -1


class _DevicePool:
    """Slot allocator for the multi-device window-solve engine:
    least-loaded first (round-robin tiebreak), so a fresh window-batch
    UPLOADS to an idle slot while the busy slots keep SOLVING — the
    upload/solve/fetch double-buffer across slots. Slot choice never
    affects decisions (every slot serves the same resident statics), so
    pure round-robin and least-loaded are byte-identical; least-loaded
    just keeps the overlap engaged when solve times are uneven."""

    def __init__(self, slots):
        self.slots = [_PoolSlot(s) for s in slots]
        self._next = 0

    def next_slot(self) -> _PoolSlot:
        """Least-loaded HEALTHY slot (round-robin tiebreak); quarantined
        slots take no new work. Raises AllSlotsQuarantinedError when the
        pool has no healthy slot left — the degraded-mode trigger."""
        n = len(self.slots)
        best, best_i = None, 0
        for off in range(n):
            i = (self._next + off) % n
            s = self.slots[i]
            if s.quarantined:
                continue
            if best is None or s.inflight < best.inflight:
                best, best_i = s, i
                if s.inflight == 0:
                    break
        if best is None:
            raise AllSlotsQuarantinedError(
                f"all {n} device slot(s) quarantined"
            )
        self._next = (best_i + 1) % n
        return best

    def healthy_slots(self) -> "list[_PoolSlot]":
        return [s for s in self.slots if not s.quarantined]

    def quarantined_slots(self) -> "list[_PoolSlot]":
        return [s for s in self.slots if s.quarantined]

    def quarantine(self, slot: _PoolSlot, now: float) -> None:
        """Take the slot out of rotation and drop its resident buffers —
        the device (or its tunnel) is suspect, so the replicas on it are
        unreachable state, not a cache."""
        slot.quarantined = True
        slot.quarantined_at = now
        slot.last_probe = now
        slot.failure_count += 1
        slot.release()

    def reinstate(self, slot: _PoolSlot) -> None:
        """Probe succeeded: back into rotation. Resident state was
        released at quarantine, so the next dispatch re-uploads statics."""
        slot.quarantined = False

    def occupancy(self) -> float:
        """Fraction of slots with at least one in-flight solve — the
        overlap-occupancy telemetry sample taken at each dispatch."""
        busy = sum(1 for s in self.slots if s.inflight > 0)
        return busy / max(1, len(self.slots))

    def health(self) -> dict:
        q = [s.label for s in self.slots if s.quarantined]
        return {
            "slots": len(self.slots),
            "healthy": len(self.slots) - len(q),
            "quarantined": q,
        }

    def release(self):
        for s in self.slots:
            s.release()

    def stats(self) -> dict:
        return {
            s.label: {
                **s.uploads,
                "inflight": s.inflight,
                "quarantined": s.quarantined,
                "failures": s.failure_count,
                "mirror": dict(s.mirror),
            }
            for s in self.slots
        }


class _PendingBase:
    """A pooled window's committed-base combine, deferred until the next
    pipelined build resolves it ON THE BUILD THREAD. Running the combine
    lazily (instead of as a worker task) means combines can never park
    pool workers waiting on other pool tasks — the classic bounded-pool
    deadlock — and the scatter work is tiny next to the solves it waits
    on. Duck-typed to Future.result() for _resolve_base."""

    __slots__ = ("_fn", "_done", "_val", "_exc")

    def __init__(self, fn):
        self._fn = fn
        self._done = False
        self._val = None
        self._exc = None

    def result(self):
        if not self._done:
            # Exception (not BaseException): KeyboardInterrupt/SystemExit
            # propagate to the build thread instead of being parked as the
            # combine's "result" (ISSUE 9 satellite).
            try:
                self._val = self._fn()
            except Exception as exc:  # surfaced by _resolve_base
                self._exc = exc
            self._done = True
            self._fn = None
        if self._exc is not None:
            raise self._exc
        return self._val


class _WindowPart:
    """One partition of a pooled window: its request slice, the worker
    future resolving to the fetched blob + timings, the EARLY future
    carrying just the committed sub-base (set the moment the solve
    finishes, BEFORE the blob d2h — the next window's base combine must
    not wait out a decision-blob transfer), and the global-node index map
    when the partition solved a gathered sub-cluster."""

    __slots__ = (
        "future", "after_future", "req_ids", "requests", "row_drv",
        "row_exc", "row_skip", "idx", "slot", "rows", "idx_key", "apps",
        "prune", "base_kept",
    )

    def __init__(self, *, future, after_future, req_ids, requests, row_drv,
                 row_exc, row_skip, idx, slot, rows, idx_key=None,
                 apps=None, prune=None, base_kept=None):
        self.future = future
        self.after_future = after_future
        self.req_ids = req_ids  # original positions in the window
        self.requests = requests
        self.row_drv = row_drv  # int64 [b_g, 3]
        self.row_exc = row_exc
        self.row_skip = row_skip
        self.idx = idx  # np int32 global node indices, None = full cluster
        self.slot = slot
        self.rows = rows
        # Re-dispatch inputs (slot-failure recovery): the HOST-side app
        # batch and the sub-replica cache key — enough to re-run this
        # part's solve on a surviving slot byte-identically.
        self.idx_key = idx_key
        self.apps = apps
        # PrunePlan when this part solved a pruned top-K gather of its
        # domain (core/prune.py): its after_future then carries a DELTA
        # (combined additively), and the fetch runs the certificate.
        self.prune = prune
        # Gathered-part dispatch-time base: the [len(idx), 3] int64
        # availability of this part's rows, captured AT DISPATCH (the
        # resident host buffer mutates in place afterwards) — the
        # compact fetch reconstructs in part-local space against this,
        # never touching an [N]-wide array (ISSUE 15).
        self.base_kept = base_kept


@_partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def _pack_blob(cluster, dreq, ereq, count, dmask, dom, *, fill, emax, num_zones):
    """Single-app pack with the Packing flattened to one int32 [2+Emax]
    array: (driver, has_capacity, exec slots...) — one device fetch."""
    p = BINPACK_FUNCTIONS[fill](
        cluster, dreq, ereq, count, dmask, dom, emax=emax, num_zones=num_zones
    )
    return jnp.concatenate(
        [p.driver_node[None], p.has_capacity.astype(jnp.int32)[None], p.executor_nodes]
    )


class _NameRankSpace:
    """Order-maintenance name ranks for the native arena (the node-ADD
    cold-rebuild fix, ISSUE 11).

    Every kernel and certificate consumes name_rank as a lexsort KEY —
    rank order matters, values never do (the native builder already
    documents global-vs-subset value deviation). So ranks need not be
    dense: values are assigned with GAPS, and an added node takes the
    midpoint between its lexicographic neighbours' values — O(log n)
    bisect + one arena scatter, where the dense scheme renumbered every
    slot per add (the measured ~96 ms at 100k). Gap exhaustion (adds
    landing repeatedly in one interval) triggers a full renumber, counted
    in `renumbers`.

    Values stay under 2^29 < kInt32Inf/2, so they can never collide with
    the arena's invalid-slot sentinel."""

    _SPAN = 1 << 29

    __slots__ = ("names", "ranks", "renumbers", "rebalances")

    def __init__(self):
        self.names: list[str] = []  # lexicographically sorted
        self.ranks: list[int] = []  # parallel gapped values, ascending
        self.renumbers = 0
        self.rebalances = 0

    def assign_all(self, names_sorted) -> None:
        self.names = list(names_sorted)
        gap = max(1, self._SPAN // (len(self.names) + 1))
        self.ranks = [(i + 1) * gap for i in range(len(self.names))]
        self.renumbers += 1

    def insert(self, name: str):
        """Insert one name. Returns the list of names whose rank VALUES
        changed — just `name` for a clean gap insert, a small rebalanced
        neighborhood when the local gap exhausted — or None when the
        whole space had to renumber (the caller re-scatters EVERY rank).
        A sequential append pattern (node-ADD bursts land adjacent names
        in ONE gap) used to exhaust its gap every ~log(gap) inserts and
        pay the O(n log n) full renumber each time — the measured
        tier-dependent full-snapshot spikes of ISSUE 13; the local
        relabel bounds that to an O(window) scatter."""
        import bisect as _bisect

        i = _bisect.bisect_left(self.names, name)
        if i < len(self.names) and self.names[i] == name:
            return []  # already ranked (idempotent re-add)
        lo = self.ranks[i - 1] if i > 0 else 0
        hi = (
            self.ranks[i]
            if i < len(self.ranks)
            else min(lo + 2 * max(1, self._SPAN // (len(self.names) + 2)),
                     self._SPAN)
        )
        if hi - lo < 2:
            self.names.insert(i, name)
            self.ranks.insert(i, lo)  # placeholder; _rebalance assigns
            return self._rebalance(i)
        self.names.insert(i, name)
        self.ranks.insert(i, (lo + hi) // 2)
        return [name]

    def _rebalance(self, i: int):
        """Order-maintenance local relabel: spread a geometrically grown
        neighborhood of position `i` evenly across its enclosing value
        interval. Returns the names whose values moved, or None when no
        enclosing interval had room (genuine exhaustion: full renumber)."""
        n = len(self.names)
        half = 4
        while True:
            a = max(0, i - half)
            b = min(n, i + half)
            lo = self.ranks[a - 1] if a > 0 else 0
            hi = self.ranks[b] if b < n else self._SPAN
            count = b - a
            if hi - lo >= 4 * (count + 1):
                gap = (hi - lo) // (count + 1)
                changed: list[str] = []
                for k in range(a, b):
                    val = lo + (k - a + 1) * gap
                    if self.ranks[k] != val:
                        self.ranks[k] = val
                        changed.append(self.names[k])
                self.rebalances += 1
                return changed
            if a == 0 and b == n:
                self.assign_all(self.names)
                return None
            half *= 2

    def remove(self, name: str) -> None:
        """Drop one name (node DELETE tombstone): its rank value simply
        leaves the space — neighbours keep their values, and the freed
        gap makes future inserts cheaper. Never renumbers."""
        import bisect as _bisect

        i = _bisect.bisect_left(self.names, name)
        if i < len(self.names) and self.names[i] == name:
            self.names.pop(i)
            self.ranks.pop(i)

    def rank_of(self, name: str) -> int:
        import bisect as _bisect

        i = _bisect.bisect_left(self.names, name)
        return self.ranks[i]


class HostPacking(NamedTuple):
    driver_node: Optional[str]
    executor_nodes: list[str]
    has_capacity: bool
    efficiency_max: float
    efficiency_cpu: float
    efficiency_memory: float
    efficiency_gpu: float


class WindowRequest(NamedTuple):
    """One serving request inside a coalesced /predicates window
    (see PlacementSolver.pack_window)."""

    # (driver_resources, executor_resources, executor_count, skippable) in
    # FIFO order; the LAST row is the request's own application, earlier
    # rows are its pending earlier drivers (fitEarlierDrivers semantics —
    # every unscheduled earlier driver re-packs hypothetically, even one
    # whose own admission this window just committed; the reference does
    # the same, resource.go:221-258 + sparkpods.go:60-77).
    rows: Sequence[tuple]
    driver_candidate_names: Sequence[str]
    domain_node_names: Sequence[str] | None = None  # None = all valid nodes
    domain_mask: "np.ndarray | None" = None  # precomputed [N] bool override


class WindowDecision(NamedTuple):
    """Outcome of one window request (see PlacementSolver.pack_window)."""

    packing: HostPacking
    admitted: bool
    # A non-skippable, still-pending earlier driver failed to fit => the
    # request fails FAILURE_EARLIER_DRIVER instead of FAILURE_FIT
    # (resource.go:241-249).
    earlier_blocked: bool


class PipelineDrainRequired(RuntimeError):
    """Raised by build_tensors_pipelined when node topology/attributes
    changed while a dispatched window is still un-fetched: the caller must
    fetch (complete) the pending window first, then retry — the fresh full
    upload would otherwise discard the in-flight window's threaded base."""


class WindowHandle:
    """A dispatched-but-not-yet-fetched window solve
    (PlacementSolver.pack_window_dispatch -> pack_window_fetch)."""

    __slots__ = (
        "strategy", "blob", "blob_future", "requests", "flat_rows",
        "host_avail", "host_avail32", "host_schedulable", "priors",
        "placements", "placement_rows", "placement_vals", "n",
        "row_driver_req", "row_exec_req", "row_skippable", "seg_map",
        "info", "parts", "request_device", "dispatch_id", "dispatched_at",
        "fused_decisions", "released", "host_tensors", "use_fallback",
        "prune", "fallback_reason", "base_kept", "avail_gen",
        "avail_note_epoch", "__weakref__",
    )

    def __init__(self, *, strategy, blob, requests, flat_rows, host_avail,
                 host_schedulable, priors, n):
        self.strategy = strategy
        # Multi-device engine: list[_WindowPart] when the window was served
        # by the device pool (possibly partitioned); None on the classic
        # single-device path. request_device[i] names the slot that solved
        # request i (flight-recorder attribution).
        self.parts = None
        self.request_device = None
        # Device blob, not yet transferred: flat [B, 3+emax] int32 on the
        # XLA path; [S, R, 3+emax] on the Pallas window path (seg_map set
        # — pack_window_fetch flattens the real rows after the pull).
        self.blob = blob
        # Device->host transfer started EAGERLY on a side thread at dispatch
        # (pipelined path): the ~RTT-bound pull elapses concurrently with
        # the dispatcher's host work instead of serializing after it.
        self.blob_future = None
        self.requests = requests
        self.flat_rows = flat_rows
        # Host availability view at dispatch (int64 [N,3]); the device base
        # additionally lacks the placements of `priors` (windows dispatched
        # earlier but un-fetched at this dispatch). Pruned windows skip the
        # per-dispatch int64 materialization: host_avail is None and
        # host_avail32 references the int32 host view (ISSUE 12 —
        # _dense_base materializes lazily on the rare dense paths).
        self.host_avail = host_avail
        self.host_avail32 = None
        self.host_schedulable = host_schedulable
        self.priors = priors  # tuple[WindowHandle] — fetched before this one
        self.placements = None  # int64 [N,3], filled at DENSE fetches only
        # Sparse committed placements (pruned and pooled fetches):
        # `placement_rows` [P] sorted global rows + `placement_vals`
        # [P,3] int64 — later windows subtract priors sparsely and the
        # dense [N,3] placements tensor is never materialized on the hot
        # path at the 1M tier (ISSUE 15).
        self.placement_rows = None
        self.placement_vals = None
        self.n = n
        self.row_driver_req = None  # int64 [B,3], set after dispatch
        self.row_exec_req = None
        self.row_skippable = None
        self.seg_map = None  # pallas window path: (seg_idx, row_idx)
        # Flight-recorder dispatch info: {"path", "nodes", "rows",
        # "row_bucket", "emax", "compile_cache_hit", "dispatch_id",
        # "fused_k"} — set at dispatch.
        self.info = None
        # Monotone per-solver id of the device dispatch that solved this
        # window. Every FusedWindowView of one fused batch shares its
        # umbrella's id — the serving loop's pipeline-depth accounting
        # counts DISPATCHES, not windows.
        self.dispatch_id = None
        self.dispatched_at = 0.0
        # Fused umbrella only: memoized ("ok", decisions) / ("err", exc)
        # of the one real fetch, shared by every view's pack_window_fetch.
        self.fused_decisions = None
        self.released = False
        # Host ClusterTensors view at dispatch (static fields + masks):
        # what slot-failure re-dispatch and the greedy degraded fallback
        # re-solve from. A reference, not a copy — the host arrays are
        # immutable between builds.
        self.host_tensors = None
        # True: no device solved this window (every slot quarantined at
        # dispatch); pack_window_fetch serves it via the greedy fallback.
        self.use_fallback = False
        # Candidate-pruning state (core/prune.PrunePlan) when this window
        # was solved over a gathered top-K sub-cluster; pack_window_fetch
        # maps the blob's local indices back and runs the certificate.
        self.prune = None
        # Why use_fallback was set ("prune-escalation" = a sibling window's
        # failed certificate invalidated the carry this window solved on;
        # None = degraded-mode serving — only the latter counts against the
        # degraded controller's decision gauges).
        self.fallback_reason = None
        # Pruned dispatch (ISSUE 13): the [k_real, 3] int64 dispatch-time
        # availability of the kept rows, gathered AT DISPATCH — the
        # resident host buffer mutates in place afterwards, so the fetch
        # path must never gather from it. `avail_gen` is the resident
        # buffer's generation at dispatch (the undo-journal replay point
        # for the rare dense reconstructions).
        self.base_kept = None
        self.avail_gen = None
        # Pooled idx-None dispatch: the availability epoch this window
        # journaled as UNKNOWABLE — its fetch patches the entry with the
        # exact commit rows so slot mirrors can cross the epoch.
        self.avail_note_epoch = None

    def release_buffers(self) -> None:
        """Drop the dispatch's staging buffers: the device decision blob
        and any in-flight pulls (close()/discard_pipeline() — a discarded
        fused batch must not keep its [K, ...] device blob alive through
        view handles parked in the serving pipeline). A later fetch of a
        released handle fails fast instead of pulling freed state."""
        self.released = True
        self.blob = None
        fut = self.blob_future
        if fut is not None:
            fut.cancel()
        if self.parts:
            for p in self.parts:
                p.future.cancel()

    def fetch_ready(self) -> bool:
        """True when every decision pull this window started eagerly has
        landed — completing it costs no blocking wait. False when no eager
        pull exists (the caller decides whether to block)."""
        if self.parts is not None:
            return all(p.future.done() for p in self.parts)
        return self.blob_future is not None and self.blob_future.done()

    def has_eager_fetch(self) -> bool:
        """Whether a decision pull is in flight on a side thread (the
        serving loop sleeps on it instead of blocking in result())."""
        if self.parts is not None:
            return True
        return self.blob_future is not None


class FusedWindowView:
    """One sub-window of a fused K-window dispatch
    (PlacementSolver.pack_windows_dispatch): a slice view over the
    umbrella WindowHandle that solved the K windows' concatenated
    segmented batch in one device program. Duck-typed to the WindowHandle
    surface the serving loop and extender consume (fetch_ready /
    has_eager_fetch / requests / request_device / info / dispatch_id);
    pack_window_fetch on a view fetches the umbrella ONCE (memoized on
    the owner) and returns the view's request slice — the first completed
    view pays the single d2h, the rest are free."""

    __slots__ = ("owner", "lo", "hi", "index", "fused_k", "info")

    def __init__(self, owner: "WindowHandle", lo: int, hi: int,
                 index: int, fused_k: int):
        self.owner = owner
        self.lo = lo
        self.hi = hi
        self.index = index
        self.fused_k = fused_k
        # Per-view copy so a record's solve_info names the view's position
        # inside the fused batch without mutating the shared owner info.
        self.info = {**(owner.info or {}), "fused_index": index}

    @property
    def dispatch_id(self):
        return self.owner.dispatch_id

    @property
    def strategy(self):
        return self.owner.strategy

    @property
    def requests(self):
        return self.owner.requests[self.lo:self.hi]

    @property
    def request_device(self):
        rd = self.owner.request_device
        return rd[self.lo:self.hi] if rd is not None else None

    # Serving-loop eager-fetch surface (server/http.py eager_futures).
    @property
    def parts(self):
        return self.owner.parts

    @property
    def blob_future(self):
        return self.owner.blob_future

    def fetch_ready(self) -> bool:
        if self.owner.fused_decisions is not None:
            return True
        return self.owner.fetch_ready()

    def has_eager_fetch(self) -> bool:
        return self.owner.has_eager_fetch()


class PlacementSolver:
    def __init__(
        self,
        driver_label_priority: tuple[str, list[str]] | None = None,
        executor_label_priority: tuple[str, list[str]] | None = None,
        use_native: bool = True,
        device_pool: int = 1,
        mesh: tuple[int, int] | None = None,
        quarantine_probe_s: float = 5.0,
        prune_top_k: int = 0,
        prune_slack: float = 2.0,
        delta_statics: bool = True,
        scale_tier: bool = False,
        build_oracle: bool = False,
        lazy_warm_start: bool = True,
    ):
        self.registry = NodeRegistry()
        # Delta STATIC uploads (`solver.delta-statics`, ISSUE 11): a node
        # event that touches few rows ships a row-scatter of the changed
        # static-field rows instead of the full multi-MB blob (and pool
        # replicas catch up from the epoch journal). Default ON — pinned
        # byte-identical to the full upload by the delta-equivalence
        # suite; False restores the full-upload-per-statics-change paths.
        self._delta_statics = bool(delta_statics)
        # Statics-epoch journal: epoch -> the rows that changed in that
        # epoch's delta. A pool slot whose resident replica is E epochs
        # behind scatters the union of those rows; a slot whose needed
        # epochs were evicted (or that predates a full upload, which
        # clears the journal) must full re-upload — the torn-update
        # contract.
        self._static_journal: dict[int, np.ndarray] = {}
        # Scale-tier serving (`solver.scale-tier`): certificate
        # escalations and cold full-tensor re-solves run as a node-sharded
        # device solve over the mesh of local devices instead of the
        # host-Python greedy walk — the [N] escalation path stops being a
        # host O(N x rows) cost at the million-node tier. Decisions are
        # byte-identical (same kernels; parity-suite pinned); any failure
        # falls back to the host greedy oracle. Default OFF.
        self._scale_tier = bool(scale_tier)
        self._scale_mesh = None  # lazy ("nodes",) mesh over local devices
        self.scale_tier_stats = {"resolves": 0, "sharded": 0, "fallbacks": 0}
        # Candidate pruning (`solver.prune-top-k` / `solver.prune-slack`,
        # core/prune.py): when top-k > 0, eligible pipelined windows solve
        # a gathered top-K sub-cluster and every decision is certified
        # against the full solve at fetch (escalating to the exact host
        # re-solve on a failed certificate). 0 = off (the default): the
        # classic full-tensor paths byte-for-byte.
        self._prune_top_k = int(prune_top_k)
        self._prune_slack = float(prune_slack)
        self._planner = None  # lazy core/prune.PrunePlanner
        # Statics-gather reuse (ISSUE 12 tentpole (c), generalized per
        # domain in ISSUE 15): gathered statics sub-blobs keyed by the
        # kept-row array's identity (per-domain plan reuse re-serves the
        # same keep object; each entry pins its keep, so ids cannot
        # recycle), re-served while no static row-delta touches a kept
        # row. Single-device entries also carry the device buffers; pool
        # slots cache their device copies per (keep, generation).
        self._prune_gather_cache: dict = {}
        self._gather_gen = _itertools.count(1)
        # (domain key, epochs) -> "is the full valid mask" memo — gates
        # the planner's resident-aggregate path for named full-roster
        # domains without an O(N) compare per window.
        self._full_dom_memo: dict = {}
        self.prune_stats = {
            "windows": 0,
            "escalations": 0,
            "kept_rows": 0,
            "window_rows": 0,
            "candidate_rows": 0,
            "reasons": {},
            # O(K + changed) planning evidence (ISSUE 12): rows the
            # planner actually examined (zone re-scans), the cold-build
            # rows, the legacy subset-domain sweeps, resync compares,
            # cache activity, and the per-phase wall-time accumulators.
            "planner_rows_scanned": 0,
            "planner_cold_rows": 0,
            "planner_sweep_rows": 0,
            "planner_resync_rows": 0,
            "planner_zone_rescans": 0,
            "planner_zone_refreshes": 0,
            "planner_merges": 0,
            "planner_boundary_inserts": 0,
            "plan_reuse": 0,
            "gather_reuse": 0,
            "plan_ms": 0.0,
            "gather_ms": 0.0,
            "offset_ms": 0.0,
        }
        # Multi-device window-solve engine (`solver.device-pool` /
        # `solver.mesh` install keys): `mesh=(groups, node_shards)` builds
        # `groups` pool slots of `node_shards` devices each (node_shards>1
        # = the GSPMD sharded serving mode); `device_pool=P` is shorthand
        # for mesh (P, 1). Default (pool 1, no mesh) keeps the classic
        # single-device serving path byte-for-byte.
        self._pool: _DevicePool | None = None
        pool_spec = mesh if mesh is not None else (device_pool, 1)
        if pool_spec and (pool_spec[0] > 1 or pool_spec[1] > 1):
            from spark_scheduler_tpu.parallel.mesh import make_pool_slots

            slots = make_pool_slots(pool_spec[0], pool_spec[1])
            if len(slots) > 1 or pool_spec[1] > 1:
                self._pool = _DevicePool(slots)
        if (
            self._pool is not None
            and any(s.is_mesh for s in self._pool.slots)
            and jax.default_backend() != "tpu"
        ):
            # Startup warning, not an error: the config is legal, but
            # node-axis GSPMD sharding needs an ICI-class interconnect —
            # the CPU mesh measured 0.5x the plain pool (PR 4) and used
            # to degrade silently.
            _warnings.warn(
                "solver.mesh node-shards="
                f"{pool_spec[1]} on backend {jax.default_backend()!r}: "
                "node-axis sharding needs an ICI-class interconnect "
                "(measured 0.5x on a CPU mesh); serving will be slower "
                "than an unsharded pool of the same devices",
                RuntimeWarning,
                stacklevel=2,
            )
        # Statics epoch: bumped on every full host upload (topology or
        # attribute change); pool replicas re-upload when their epoch lags.
        self._static_epoch = 0
        # Fused multi-window dispatch (pack_windows_dispatch): monotone
        # dispatch ids for the serving loop's depth accounting, and weak
        # refs to live fused umbrellas so close()/discard_pipeline() can
        # release their [K, ...] staging buffers even while view handles
        # are still parked in the serving pipeline.
        self._dispatch_seq = _itertools.count(1)
        # Pipeline-generation tokens for the per-slot availability
        # mirrors (ISSUE 15): a slot's resident full-base replica is only
        # a valid catch-up base within the pipeline generation that wrote
        # it — a full re-upload starts a new generation and every replica
        # goes stale at once.
        self._pipe_tokens = _itertools.count(1)
        self._fused_owners: "_weakref.WeakSet[WindowHandle]" = (
            _weakref.WeakSet()
        )
        # How the LAST pipelined/cached build reached the device
        # ("full" | "delta" | "reuse") — flight-recorder state_upload.
        self.last_state_upload: str | None = None
        # Deferred-dispatch lane (ISSUE 18 replay/sweep.py, ISSUE 20
        # fleet/dispatch.py) — None on the plain serving path. A lane is a
        # coordinator that intercepts the pipelined XLA window solve and
        # defers it into a stacked multi-window dispatch: the sweep stacks
        # the SAME window across config arms at its lockstep barrier
        # (arm_stacked_fifo_pack); the fleet coordinator stacks CONCURRENT
        # windows from different clusters inside a short gather window
        # (bucket_stacked_fifo_pack). Lane protocol: `accepts(solver)`
        # gates per-dispatch deferral (the fleet lane declines when fewer
        # than two clusters are live, so those windows take the normal
        # path untouched), `row_bucket_quantum` (None = use the solver's)
        # sets the app-row bucket for DEFERRED windows only, and
        # `defer_window(...)` parks the window, returning lazy blob/avail
        # stand-ins resolved at flush. `_sweep_shared` is the sweep's
        # cross-lane candidate-mask memo (roster state is arm-invariant,
        # so lane 2..M reuse lane 1's mask build). `_row_bucket_quantum`
        # stays 32 for serving (compile-cache coarseness on live
        # traffic); sweep lanes drop it to 8 — under vmap padding rows
        # EXECUTE (lax.cond lowers to select), so tight buckets are pure
        # win there and the sweep pre-compiles its buckets up front.
        self._dispatch_lane = None
        self._sweep_shared: dict | None = None
        self._row_bucket_quantum = 32
        # In-flight worker/fetch futures, cancelled (if unstarted) on
        # close() so repeated server restarts drain the shared pools'
        # queues instead of leaking device buffers through parked closures.
        self._inflight_futures: set = set()
        self._clock = _time.time
        self._driver_label_priority = driver_label_priority
        self._executor_label_priority = executor_label_priority
        # Native C++ arena (native/runtime.cpp): per-node state is upserted
        # only when a node object actually changes, and the dense tensor
        # inputs are materialized in one C call per request instead of a
        # Python walk over every node.
        self._arena = None
        self._node_seen: dict[str, Node] = {}
        self._rank_epoch = -1
        # Deleted-node registry rows awaiting recycling (ISSUE 12): a
        # tombstoned row re-enters the registry free list only once its
        # reservation usage/overhead drained to zero AND no window is in
        # flight that could still name it — until then it stays parked
        # (masked invalid) and is retried every build.
        self._pending_tombstones: set[str] = set()
        self.tombstones_recycled = 0
        # Gapped name-rank order (see _NameRankSpace): a node ADD inserts
        # one rank value instead of renumbering every slot.
        self._rank_space = _NameRankSpace()
        if use_native and native.available():
            self._arena = native.ClusterArena()
        # Device-resident cluster state (VERDICT r2 #3): the last uploaded
        # tensors + their numpy source. build_tensors_cached diffs against
        # the mirror and ships only changed availability rows.
        self._dev: dict | None = None
        # Pipelined serving state (build_tensors_pipelined /
        # pack_window_dispatch / pack_window_fetch): the device availability
        # threaded ACROSS windows, an int64 mirror of it in host terms, and
        # the dispatched-but-unfetched handles. Single-threaded by contract
        # (the predicate batcher is the serialization point); the fetch pool
        # only runs stateless jax.device_get calls.
        self._pipe: dict | None = None
        self._closed = False
        # Candidate-mask memo: serving windows pass the same (usually
        # cluster-wide) candidate list once per request, and building the
        # [N] bool mask is a walk over every name. Keyed by the full
        # name tuple + registry epoch + padded size, so a stale mapping can
        # never serve (collision-safe: dict equality compares the tuple).
        # LRU-evicting: a 65th live signature must not wipe the 64 hottest.
        from spark_scheduler_tpu.core.lru import LRUCache

        self._cand_cache: LRUCache = LRUCache(64)
        # (domain mask, valid mask) -> their AND, identity-keyed with the
        # operands pinned alive: the per-window `dom & valid` product is
        # an O(N) allocation, and — more importantly — a STABLE result
        # object is what lets the prune planner's per-domain contexts
        # recognize an unchanged domain across windows (ISSUE 15).
        self._dom_and_memo: LRUCache = LRUCache(32)
        # Per-names patch bases for the epoch-journal candidate-mask
        # patch (ISSUE 13): names-key -> (epoch, n, mask, unresolved
        # names, removed member names) — see _cand_try_patch.
        self._cand_patch: LRUCache = LRUCache(16)
        # Topology-version memo (see build_tensors' topo_version contract):
        # lets the native tensor build skip its O(nodes) sync walk between
        # requests when no node changed.
        self._topo_seen = None
        self._topo_request_mask = None  # ((version, pad, n), [pad] bool)
        self.device_state_stats = {
            "full_uploads": 0,
            "delta_uploads": 0,
            "delta_rows": 0,
            "reuse_hits": 0,
            # Delta STATIC uploads (row-scatters of changed static-field
            # rows — node events that used to force the full blob).
            "static_delta_uploads": 0,
            "static_delta_rows": 0,
            # Total h2d bytes of every state upload above (full blobs +
            # both delta kinds) — upload_bytes / (full + delta uploads)
            # is the bench's upload_bytes_per_event.
            "upload_bytes": 0,
        }
        # Which device path served each dispatched window (pallas | xla).
        self.window_path_counts: dict[str, int] = {}
        # SolverTelemetry hook surface (observability/telemetry.py) — wired
        # by build_scheduler_app; None keeps every hot-path hook a single
        # attribute test.
        self.telemetry = None
        # Dispatch info of the most recent SOLO pack() ({"path", "nodes",
        # "emax", "compile_cache_hit"}) for the flight recorder.
        # Single-threaded by the same contract as the pipeline state.
        self.last_solve_info: dict | None = None
        # Device-slot fault recovery (ISSUE 9): how often a quarantined
        # slot is probed for reinstatement, the degraded-mode controller
        # (faults/degraded.py, wired by build_scheduler_app; None =
        # device failures propagate as before), and the lazy host-side
        # greedy fallback the degraded "greedy" policy serves through.
        self.quarantine_probe_s = quarantine_probe_s
        self.degraded = None
        self._fallback = None
        self.redispatch_count = 0
        # Resident native tensor build (ISSUE 13): the nine host field
        # buffers stay RESIDENT between serving builds — the feature
        # store's availability journal + the arena's upsert feed name
        # exactly which rows changed, `arena_snapshot_rows` recomputes
        # just those at C speed, and static fields copy-on-write so
        # in-flight window handles keep their dispatch-time view.
        self._snap_res: dict | None = None
        # Arena rows upserted since the resident arrays last absorbed
        # them (any build path may upsert; the next resident build
        # patches the union). The full flag marks un-nameable static
        # drift (rank renumber, cold identity walk) — resident rebuild.
        self._res_pending: list = []
        self._res_full_pending = False
        # In-place availability patches are UNDO-journaled while pruned
        # handles are in flight: (gen, buffer, rows, old int32 rows) —
        # escalation/fallback re-solves reconstruct their dispatch-time
        # dense view by replaying entries in reverse (_avail_at_dispatch).
        # The hot fetch path never needs it (base_kept gathers at
        # dispatch).
        self._avail_gen = 0
        self._avail_undo: list = []
        self._avail_handles: "_weakref.WeakSet[WindowHandle]" = (
            _weakref.WeakSet()
        )
        # (usage rows, static rows) the LAST build patched; None = the
        # build could not name them (full snapshot / python builder).
        self._last_build_rows: "tuple | None" = None
        # Union of rows EVERY build patched since the pipelined statics
        # last synced (None = some build could not name its rows): the
        # O(changed) candidate set for _plan_static_delta's field diff —
        # robust to solo builds interleaving between pipelined ones.
        self._static_acc: "list | None" = []
        # `solver.build-oracle`: after every dirty-set mirror sync, run
        # the dense compare as an ORACLE and fail loudly if the event-fed
        # candidate set missed a changed row (equivalence suites turn
        # this on; SPARK_SCHEDULER_BUILD_ORACLE=1 forces it).
        self.build_oracle = bool(build_oracle) or (
            _os.environ.get("SPARK_SCHEDULER_BUILD_ORACLE", "")
            not in ("", "0")
        )
        # `solver.lazy-warm-start`: a full device upload whose host-side
        # change feed stayed exact KEEPS the prune planner resident (a
        # warm restart skips the O(N log N) cold replan); False restores
        # the hard invalidate.
        self._lazy_warm_start = bool(lazy_warm_start)
        self.build_stats = {
            "builds": 0,
            "build_ms": 0.0,
            "incremental_builds": 0,
            "full_snapshots": 0,
            # Rows examined by the DENSE mirror sweep (the fallback; 0 in
            # steady state — CI-pinned) vs rows the event-fed dirty-set
            # sync examined.
            "mirror_rows_compared": 0,
            "mirror_dense_syncs": 0,
            "dirty_rows": 0,
            # Rows pooled fetches debited sparsely into the mirror +
            # pending ledger (ISSUE 15 — the pooled path's O(placed)
            # claim as a counter; /debug/state surfaces it).
            "pooled_debit_rows": 0,
            "oracle_checks": 0,
        }

    @property
    def fallback(self):
        if self._fallback is None:
            from spark_scheduler_tpu.core.fallback import (
                GreedyFallbackSolver,
            )

            self._fallback = GreedyFallbackSolver(self)
        return self._fallback

    # -- candidate pruning (core/prune.py) --------------------------------

    def _prune_eligible(self, strategy: str) -> bool:
        """Static gate for the two-tier solve: plain fills only (single-AZ
        wrappers score zones by subset-dependent efficiencies) and no
        configured label priorities (the prefilter/certificate keys assume
        a uniform label rank)."""
        from spark_scheduler_tpu.core.prune import PLAIN_FILLS

        return (
            self._prune_top_k > 0
            and strategy in PLAIN_FILLS
            and self._driver_label_priority is None
            and self._executor_label_priority is None
        )

    def _prune_planner(self):
        """The lazy PrunePlanner (resident per-zone rank index + zone
        aggregates + plan cache, core/prune.py)."""
        if self._planner is None:
            from spark_scheduler_tpu.core.prune import PrunePlanner

            self._planner = PrunePlanner(self.prune_stats)
        return self._planner

    def _prune_invalidate(self) -> None:
        """Drop every resident prefilter artifact (planner state + the
        statics-gather cache's device buffers) — the full-upload /
        topology-change contract."""
        if self._planner is not None:
            self._planner.invalidate()
        self._prune_gather_cache.clear()

    def _prune_note_rows(self, rows) -> None:
        """Feed EXACT changed rows to the planner (O(changed) sync)."""
        if self._planner is not None and len(rows):
            self._planner.note_dirty(rows)

    def _prune_full_upload(self) -> None:
        """A full DEVICE upload is happening. The statics-gather cache's
        device buffers die with it unconditionally; the PLANNER, though,
        keys on HOST state — when the build that triggered this upload
        named its changed rows exactly (the resident tensor build), the
        per-zone orders and aggregates are still exact once those rows are
        fed through the note paths, so a warm restart (discard_pipeline →
        full re-upload of unchanged host state) re-serves WITHOUT paying
        the O(N log N) cold replan (ISSUE 13 tentpole (d)). Any build that
        could not name its rows keeps the hard invalidate."""
        self._prune_gather_cache.clear()
        planner = self._planner
        if planner is None:
            return
        rows = self._last_build_rows
        if self._lazy_warm_start and rows is not None:
            arows, srows = rows
            if len(arows):
                planner.note_dirty(arows)
            if len(srows):
                planner.note_static(srows)
        else:
            planner.invalidate()

    def _prune_mark_unknown(self) -> None:
        """A path that cannot name its changed rows touched availability:
        the planner's next sync diff-scans the snapshots instead."""
        if self._planner is not None:
            self._planner.mark_unknown()

    def _prune_gather_entry(self, host, plan) -> dict:
        """Host-side gathered-statics cache entry for a plan's kept rows,
        keyed by the keep array's IDENTITY (per-domain plan reuse
        re-serves the same object; the entry pins it, so the id cannot
        recycle). Entries drop on static row-deltas touching their kept
        rows, full uploads, and close(); the device-side copies ride the
        entry's generation (single-device: stored here; pool slots: in
        their sub-statics cache)."""
        cache = self._prune_gather_cache
        ent = cache.get(id(plan.keep))
        if ent is not None and ent["keep"] is plan.keep:
            return ent
        while len(cache) >= 17:
            # Evict the oldest entry only: a >16-domain rotation must not
            # wipe every warm gather (and every slot's generation-checked
            # device copy) on each new keep set.
            cache.pop(next(iter(cache)))
        ent = {
            "keep": plan.keep,
            "statics_np": _gather_statics_host(host, plan.keep, plan.k_real),
            "gen": next(self._gather_gen),
        }
        cache[id(plan.keep)] = ent
        return ent

    def _plan_prune(
        self, host, dom_mask, cand_per_req, drv_arr, exc_arr, counts,
        dom_key=None, dom_ref=None,
    ):
        """Build a PrunePlan for one window/partition, or None.

        A full-valid-mask domain — by identity (no names pinned) or by
        memoized content equality (a named domain enumerating the whole
        roster) — takes the O(K + changed) resident-aggregate path;
        genuine subset domains take the counted legacy sweep."""
        planner = self._prune_planner()
        planner.sync(host, self._num_zones_bucket())
        if self._is_full_domain(
            dom_mask, np.asarray(host.valid), dom_key, dom_ref
        ):
            plan = planner.plan_full_domain(
                host,
                cand_per_req=cand_per_req,
                drv_arr=drv_arr,
                exc_arr=exc_arr,
                counts=counts,
                num_zones=self._num_zones_bucket(),
                top_k=self._prune_top_k,
                slack=self._prune_slack,
            )
        else:
            plan = planner.plan_with_masks(
                host,
                dom_mask=np.asarray(dom_mask, bool),
                cand_per_req=cand_per_req,
                drv_arr=drv_arr,
                exc_arr=exc_arr,
                counts=counts,
                num_zones=self._num_zones_bucket(),
                top_k=self._prune_top_k,
                slack=self._prune_slack,
                # Per-domain plan contexts (ISSUE 15 tentpole (b)): the
                # pooled partition path re-serves cached kept sets per
                # instance group instead of re-sweeping O(N) per window.
                dom_key=dom_key,
            )
        if plan is not None:
            st = self.prune_stats
            st["plan_ms"] += plan.plan_ms
            st["offset_ms"] += plan.offset_ms
        return plan

    def _shared_prune_domain(self, requests, dom_keys, dom_per_req):
        """(domain mask, domain key) of the single shared window domain,
        or (None, None) when requests pin distinct domains (the pooled
        partition path prunes per-partition instead; a mixed single-device
        window solves full)."""
        if any(r.domain_mask is not None for r in requests):
            return None, None
        keys = set(dom_keys)
        if len(keys) != 1:
            return None, None
        return dom_per_req[0], dom_keys[0]

    def _is_full_domain(self, dom, valid_np, dom_key, dom_ref) -> bool:
        """Whether a window's shared domain covers the ENTIRE valid mask —
        the gate for the planner's O(K + changed) resident-aggregate path.
        The default (no names pinned) is the valid mask by identity; a
        named domain that happens to enumerate the whole roster (the
        common serving request carries the full node list as its
        instance-group domain) is detected by ONE content compare memoized
        on (domain key, registry epoch, statics epoch) — both epochs pin
        the compared arrays' content, so the O(N) compare runs once per
        roster generation, not per window. `dom_ref` (the names object
        behind the key) is held ALIVE by the memo entry: identity-derived
        keys (digest tickets, huge plain lists) must never be re-matched
        after their object's id is recycled — a subset domain
        misclassified as full would desynchronize the certificate from
        the kernel's domain mask."""
        if dom is valid_np:
            return True
        if dom_key is None:
            return False
        memo_key = (
            dom_key, self.registry.epoch, self._static_epoch,
            valid_np.shape[0],
        )
        hit = self._full_dom_memo.get(memo_key)
        if hit is None:
            if len(self._full_dom_memo) > 16:
                self._full_dom_memo.clear()
            hit = (dom_ref, bool(np.array_equal(dom, valid_np)))
            self._full_dom_memo[memo_key] = hit
        return hit[1]

    def _note_prune_dispatch(self, plan, window_rows: int) -> None:
        st = self.prune_stats
        st["windows"] += 1
        st["kept_rows"] += plan.k_real
        st["window_rows"] += window_rows
        st["candidate_rows"] += plan.dom_rows
        if self.telemetry is not None:
            self.telemetry.on_prune_dispatch(plan.k_real, plan.dom_rows)

    def _note_prune_escalation(self, handle, reason: str) -> None:
        st = self.prune_stats
        st["escalations"] += 1
        st["reasons"][reason] = st["reasons"].get(reason, 0) + 1
        if self._planner is not None:
            # Re-scan to exactness: the failed certificate may trace to
            # conservative drift in a cached entry — an escalation must
            # never loop on the same stale summaries (ISSUE 15).
            self._planner.reset_plan_entries()
        if handle.info is not None:
            handle.info["prune_escalated"] = reason
        if self.telemetry is not None:
            self.telemetry.on_prune_escalation(reason)
            self.telemetry.on_pipeline_event("prune-escalation")
        # The carry embodies the pruned (now-discarded) placements: every
        # window dispatched on it re-solves from its exact host
        # reconstruction, and the next build full-uploads host truth.
        p = self._pipe
        if p is not None:
            if handle in p["unfetched"]:
                p["unfetched"].remove(handle)
            for h in p["unfetched"]:
                h.use_fallback = True
                h.fallback_reason = "prune-escalation"
            self._pipe = None

    def _collect_priors(self, handle, strict: bool):
        """Sparse union (rows, summed deltas) of in-flight prior windows'
        committed placements — O(placed), not O(N): pruned/pooled priors
        carry (placement_rows, placement_vals). `strict` (the
        certificate's contract): a prior whose placements are UNKNOWN
        (failed fetch) returns None — the caller escalates. Lenient (the
        dense-base reconstruction contract): an unknown prior contributes
        nothing — its capacity returns via the next full upload."""
        rows_list: list[np.ndarray] = []
        deltas_list: list[np.ndarray] = []
        for prior in handle.priors:
            pr = prior.placement_rows
            if pr is not None and prior.placement_vals is not None:
                rows_list.append(pr)
                deltas_list.append(prior.placement_vals)
                continue
            if prior.placements is None:
                if strict:
                    return None
                continue
            if pr is None:
                pr = np.flatnonzero(prior.placements.any(axis=1))
            rows_list.append(pr)
            deltas_list.append(prior.placements[pr])
        if not rows_list:
            return (
                np.empty(0, np.int64),
                np.empty((0, NUM_DIMS), np.int64),
            )
        rows = np.concatenate(rows_list)
        deltas = np.concatenate(deltas_list)
        uniq, inv = np.unique(rows, return_inverse=True)
        out = np.zeros((uniq.size, deltas.shape[1]), np.int64)
        np.add.at(out, inv, deltas)
        return uniq.astype(np.int64), out

    def _prior_sparse(self, handle):
        """The certificate's excluded-row-integrity input: strict prior
        collection (None when any prior's placements are unknown, which
        the caller maps to an escalation)."""
        return self._collect_priors(handle, strict=True)

    @staticmethod
    def _commit_rows(requests, drivers, admitted, execs) -> np.ndarray:
        """Global rows a window's COMMITTED placements touched, read
        straight from the decision blob in O(B · emax):
        `_reconstruct_requests` only mutates `placements` at each admitted
        request's final (committing) row — its driver and executor
        indices — so this is exactly the dense placement tensor's
        support. Feeds the sparse mirror debit and the planner's
        dirty-row feed on the dense fetch paths (ISSUE 15)."""
        rows: list[int] = []
        r = 0
        for req in requests:
            real = r + len(req.rows) - 1
            r += len(req.rows)
            if not bool(admitted[real]):
                continue
            d = int(drivers[real])
            if d >= 0:
                rows.append(d)
            ev = np.asarray(execs[real])
            rows.extend(int(x) for x in ev[ev >= 0])
        if not rows:
            return np.empty(0, np.int64)
        return np.unique(np.asarray(rows, np.int64))

    def _dense_base(self, handle) -> np.ndarray:
        """The dense [N,3] int64 fetch-side base reconstruction (host view
        at dispatch minus in-flight priors' placements). Pruned handles
        skip the per-dispatch int64 materialization and pay it only here
        (escalations, fallback re-solves, dense fetch paths); priors with
        known placement rows subtract sparsely."""
        if handle.host_avail is not None:
            base = handle.host_avail.copy()
        else:
            base = self._avail_at_dispatch(handle).astype(np.int64)
        for prior in handle.priors:
            pr = prior.placement_rows
            if pr is not None and prior.placement_vals is not None:
                if pr.size:
                    base[pr] -= prior.placement_vals
                continue
            if prior.placements is None:
                continue
            if pr is not None:
                if pr.size:
                    base[pr] -= prior.placements[pr]
            else:
                base -= prior.placements
        return base

    def _avail_at_dispatch(self, handle) -> np.ndarray:
        """The int32 host availability AS OF `handle`'s dispatch. The
        resident build patches the live buffer in place, journaling each
        patch while pruned handles are in flight — replaying the entries
        newer than the handle's generation in reverse reconstructs the
        dispatch-time view exactly. Rare paths only (escalations, fallback
        re-solves, dense fetches); the hot pruned fetch reads the [K,3]
        base gathered at dispatch."""
        arr = handle.host_avail32
        gen = handle.avail_gen
        if gen is None or not self._avail_undo:
            return arr
        entries = [
            e for e in self._avail_undo if e[1] is arr and e[0] >= gen
        ]
        if not entries:
            return arr
        out = arr.copy()
        for _g, _buf, rows, old in reversed(entries):
            out[rows] = old
        return out

    def device_health(self) -> dict:
        """{slots, healthy, quarantined: [labels]} — /debug/state and the
        readiness probe's degraded view."""
        if self._pool is None:
            return {"slots": 1, "healthy": 1, "quarantined": []}
        return self._pool.health()

    def _on_slot_event(self, event: str, label: str) -> None:
        if self.telemetry is not None:
            self.telemetry.on_slot_event(event, label)
            if self._pool is not None:
                self.telemetry.on_quarantine_count(
                    len(self._pool.quarantined_slots())
                )

    def _quarantine_slot(self, slot, exc) -> None:
        self._pool.quarantine(slot, self._clock())
        self._on_slot_event("quarantine", slot.label)
        from spark_scheduler_tpu.tracing import svc1log

        svc1log().warn(
            "device slot quarantined",
            device=slot.label,
            error=f"{type(exc).__name__}: {exc}",
            failures=slot.failure_count,
        )

    def probe_quarantined(self, force: bool = False) -> int:
        """Run a tiny device program on each quarantined slot whose probe
        interval elapsed; success reinstates the slot (statics re-upload
        on its next dispatch). Returns the number reinstated. Called at
        every pooled dispatch — cheap when nothing is quarantined."""
        pool = self._pool
        if pool is None:
            return 0
        reinstated = 0
        now = self._clock()
        for s in pool.quarantined_slots():
            if not force and now - s.last_probe < self.quarantine_probe_s:
                continue
            s.last_probe = now
            try:
                # The probe pays the same boundaries a real dispatch
                # would (shim'd, so injected device partitions keep the
                # slot down until the plan's window ends).
                _shim("dispatch")
                arr = s._put(np.arange(8, dtype=np.int32))
                np.asarray(jax.device_get(arr + 1))
            except Exception as exc:
                if classify_slot_failure(exc):
                    self._on_slot_event("probe-failed", s.label)
                    continue
                raise
            pool.reinstate(s)
            reinstated += 1
            self._on_slot_event("reinstate", s.label)
        if reinstated and self.degraded is not None and pool.healthy_slots():
            self.degraded.clear()
        return reinstated

    def _degraded_or_raise(self, exc):
        """A device failure with no healthy slot to retry on: consult the
        degraded policy. Returns True when the caller should serve via
        the greedy fallback; raises DegradedUnavailableError (shed) or
        re-raises `exc` (no controller wired)."""
        d = self.degraded
        if d is None:
            raise exc
        d.engage(f"{type(exc).__name__}: {exc}")
        if d.sheds:
            d.on_shed()
            raise DegradedUnavailableError(
                f"no device slot available: {exc}", d.retry_after_s
            ) from exc
        return True

    def _device_recovered(self) -> None:
        """A device solve completed: if degraded mode was engaged by a
        transient single-device failure, serving recovered — clear it.
        (Pool-quarantine degradation clears via probe reinstatement.)"""
        d = self.degraded
        if d is not None and d.active:
            if self._pool is None or self._pool.healthy_slots():
                d.clear()

    @property
    def uses_native_arena(self) -> bool:
        return self._arena is not None

    @property
    def pool_size(self) -> int:
        """Slot count of the multi-device window-solve engine (1 = the
        classic single-device serving path)."""
        return len(self._pool.slots) if self._pool is not None else 1

    def device_pool_stats(self) -> dict:
        """Per-slot resident-state stats ({label: {full, reuse,
        inflight}}) — surfaced by bench.py's multi-device section."""
        return self._pool.stats() if self._pool is not None else {}


    def build_tensors(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        *,
        full_node_list: bool = False,
        topo_version: Optional[int] = None,
        roster_rows: "np.ndarray | None" = None,
        dirty_hint: "tuple | None" = None,
        avail_epoch: "int | None" = None,
        avail_journal: "dict | None" = None,
    ):
        """`usage` / `overhead` are either {node: Resources} maps (the
        reference's shape) or dense int64 [cap, 3] arrays indexed by this
        solver's registry (the incremental-tracker fast path — no
        per-reservation host walk).

        `avail_epoch` / `avail_journal` are the feature store's
        availability-input change journal (ISSUE 13): when the chain of
        epochs since the resident build's last sync is fully present, the
        nine host field buffers are PATCHED at the named rows instead of
        re-materialized over every slot — the per-window O(N) arena
        snapshot becomes O(K + changed). Absent or gapped, one full
        materialization runs (fresh buffers; in-flight handles keep the
        old ones).

        `full_node_list` asserts `nodes` is the backend's complete current
        node list (the serving contract of the cached/pipelined builders).
        `topo_version` is the backend's node-mutation counter
        (store/backend.py nodes_version) captured by the caller BEFORE
        listing `nodes` — capture-before-list means a concurrent mutation
        makes the version look stale (extra walk, safe) and never fresh
        (skipped walk over unsynced state, unsafe). Both together enable
        skipping the O(nodes) sync walk and memoizing the request mask.

        `roster_rows` / `dirty_hint` are the HostFeatureStore's cold-path
        accelerators (FeatureSnapshot fields): the registry row of each
        node (the request mask becomes one scatter instead of an O(nodes)
        name->index walk), and the changed Node objects since
        `dirty_hint[0]` (an update-only node event upserts O(changed)
        arena rows instead of the O(nodes) identity walk). Both optional
        and verified before use — a mismatched hint falls back to the
        full walk."""
        if self._arena is not None:
            # `nodes` is passed as-is (tuple/list/store-owned roster): the
            # fast paths only take len(); copying a million-entry list per
            # window was a measured cost.
            return self._build_tensors_native(
                nodes, usage, overhead,
                full_node_list=full_node_list, topo_version=topo_version,
                roster_rows=roster_rows, dirty_hint=dirty_hint,
                avail_epoch=avail_epoch, avail_journal=avail_journal,
            )
        self._last_build_rows = None
        self._acc_build_rows()
        self._note_consumers_unknown()
        for n in nodes:
            self.registry.intern(n.name)
        pad = _bucket(self.registry.capacity, 8)
        return build_cluster_tensors(
            list(nodes),
            usage,
            overhead,
            self.registry,
            driver_label_priority=self._driver_label_priority,
            executor_label_priority=self._executor_label_priority,
            pad_to=pad,
        )

    def build_tensors_cached(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        topo_version: Optional[int] = None,
        roster_rows=None,
        dirty_hint=None,
        avail_epoch=None,
        avail_journal=None,
    ) -> ClusterTensors:
        """Device-resident cluster state with delta updates (VERDICT r2 #3).

        Builds the host tensors exactly like `build_tensors`, then keeps the
        device copy ALIVE between requests: when only availability rows
        changed since the previous call (reservation deltas, overhead
        drift), a jitted row-scatter ships just those rows; unchanged state
        re-uses the resident arrays outright; topology/attribute changes
        (any non-availability field) trigger a full upload. The numpy source
        rides along as `.host` so host-side consumers (efficiency, masks)
        never pull arrays back off the device.

        Callers should pass the FULL current node list and express
        per-request affinity/candidate filtering through the kernels'
        domain/candidate masks — that keeps the cached topology stable
        across requests (SURVEY.md §7 "persistent device state + small
        delta updates")."""
        host = self.build_tensors(
            nodes, usage, overhead,
            full_node_list=True, topo_version=topo_version,
            roster_rows=roster_rows, dirty_hint=dirty_hint,
            avail_epoch=avail_epoch, avail_journal=avail_journal,
        )
        stats = self.device_state_stats
        dev = self._dev
        tensors = None
        if dev is not None and dev["host"].available.shape == host.available.shape:
            prev = dev["host"]
            if all(
                getattr(prev, f) is getattr(host, f)
                or np.array_equal(getattr(prev, f), getattr(host, f))
                for f in _STATIC_FIELDS
            ):
                if prev.available is host.available:
                    # Resident build: the buffer is patched in place, so
                    # a value diff sees nothing — the pending ledger
                    # carries the patched rows instead (None = a build
                    # could not name them: full availability re-upload).
                    pend = dev.get("pending")
                    if pend is None:
                        dirty = None
                    elif pend:
                        dirty = np.unique(
                            np.concatenate([np.asarray(c) for c in pend])
                        )
                        dirty = dirty[dirty < host.available.shape[0]]
                    else:
                        dirty = np.empty(0, np.int64)
                else:
                    dirty = np.flatnonzero(
                        np.any(prev.available != host.available, axis=1)
                    )
                if dirty is None:
                    k = host.available.shape[0]  # unknown: ship all rows
                else:
                    k = len(dirty)
                if dirty is not None and k == 0:
                    tensors = dev["tensors"]
                    stats["reuse_hits"] += 1
                    self.last_state_upload = "reuse"
                elif dirty is not None and k <= max(
                    32, host.available.shape[0] // 8
                ):
                    # Bucket the row count so the scatter program compiles
                    # once per bucket; padding repeats dirty rows (set with
                    # identical values — deterministic).
                    idx = np.resize(dirty, _bucket(k, 16))
                    rows = host.available[idx]
                    new_avail = _scatter_rows(
                        dev["tensors"].available,
                        jnp.asarray(idx.astype(np.int32)),
                        jnp.asarray(rows),
                    )
                    tensors = dataclasses.replace(
                        dev["tensors"], available=new_avail
                    )
                    stats["delta_uploads"] += 1
                    stats["delta_rows"] += k
                    stats["upload_bytes"] += rows.nbytes + idx.nbytes
                    self.last_state_upload = "delta"
                    if self.telemetry is not None:
                        self.telemetry.on_transfer(
                            "h2d", rows.nbytes + idx.nbytes
                        )
                else:
                    # COPY before upload: CPU device_put may zero-copy
                    # an aligned buffer, and this one is patched in
                    # place by the resident build (see the pipelined
                    # full upload's aliasing note).
                    tensors = dataclasses.replace(
                        dev["tensors"],
                        available=jax.device_put(host.available.copy()),
                    )
                    stats["full_uploads"] += 1
                    stats["upload_bytes"] += host.available.nbytes
                    self.last_state_upload = "full"
                    if self.telemetry is not None:
                        self.telemetry.on_transfer(
                            "h2d", host.available.nbytes
                        )
        if tensors is None:
            tensors = jax.device_put(
                dataclasses.replace(host, available=host.available.copy())
            )
            stats["full_uploads"] += 1
            stats["upload_bytes"] += _tensors_nbytes(host)
            self.last_state_upload = "full"
            if self.telemetry is not None:
                self.telemetry.on_transfer("h2d", _tensors_nbytes(host))
        tensors.host = host
        self._dev = {"host": host, "tensors": tensors, "pending": []}
        return tensors

    def close(self) -> None:
        """Stop accepting new pipelined fetch submits (they would enqueue a
        Future whose result nobody will pull), CANCEL any queued-but-unrun
        fetch/solve work this solver still has in the shared pools, and
        release every device-resident buffer (pipeline state, cached
        tensors, pool replicas). The pools themselves are process-shared
        (_shared_fetch_pool / _shared_solve_pool) and stay up for other
        solvers — their workers are a bounded set of daemon threads — but
        without the cancel+release, repeated server restarts in one
        process leak device buffers through parked closures."""
        self._closed = True
        for fut in list(self._inflight_futures):
            fut.cancel()  # no-op if already running; queued work is dropped
        self._inflight_futures.clear()
        self._pipe = None
        self._dev = None
        self._snap_res = None  # resident host buffers
        self._avail_undo.clear()
        self._prune_gather_cache.clear()  # release cached device statics
        self._release_fused()
        self._release_pool()

    def _release_pool(self) -> None:
        if self._pool is None:
            return
        self._pool.release()
        if self.telemetry is not None:
            for s in self._pool.slots:
                self.telemetry.on_device_inflight(s.label, 0)

    def discard_pipeline(self) -> None:
        """Drop the pipelined device state: the next build_tensors_pipelined
        does a full upload from the host view. Used when in-flight window
        decisions are being discarded (capacity changed under them) — the
        host view is the durable truth once every surviving window has
        applied. Pool replicas are released with it (the next build bumps
        the statics epoch, so every slot re-uploads on its next turn), and
        so are the staging buffers of any un-fetched FUSED batches — their
        decisions are being discarded with the pipeline (the caller's
        epoch bump re-solves every in-flight window from host truth), so
        keeping the [K, ...] device blobs alive through parked view
        handles would be a restart-shaped leak."""
        self._pipe = None
        self._prune_gather_cache.clear()  # release cached device statics
        self._release_fused()
        self._release_pool()
        if self.telemetry is not None:
            self.telemetry.on_pipeline_event("discard")

    def _release_fused(self) -> None:
        for h in list(self._fused_owners):
            h.release_buffers()
        # WeakSet: survivors were only kept alive by external view refs;
        # they are released now and need no second pass.
        self._fused_owners.clear()

    def build_tensors_pipelined(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        topo_version: Optional[int] = None,
        statics_version: Optional[int] = None,
        roster_rows=None,
        dirty_hint=None,
        avail_epoch=None,
        avail_journal=None,
    ) -> ClusterTensors:
        """Timing/telemetry shell around the pipelined build — the
        O(K + changed) claim lands as `build_stats` counters and the
        foundry.spark.scheduler.solver.build.* gauges."""
        bs = self.build_stats
        compared0 = bs["mirror_rows_compared"]
        dirty0 = bs["dirty_rows"]
        t0 = _time.perf_counter()
        try:
            return self._build_tensors_pipelined(
                nodes, usage, overhead,
                topo_version=topo_version,
                statics_version=statics_version,
                roster_rows=roster_rows,
                dirty_hint=dirty_hint,
                avail_epoch=avail_epoch,
                avail_journal=avail_journal,
            )
        finally:
            ms = (_time.perf_counter() - t0) * 1e3
            bs["builds"] += 1
            bs["build_ms"] += ms
            if self.telemetry is not None:
                self.telemetry.on_build(
                    ms,
                    bs["mirror_rows_compared"] - compared0,
                    bs["dirty_rows"] - dirty0,
                )

    def _build_tensors_pipelined(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        topo_version: Optional[int] = None,
        statics_version: Optional[int] = None,
        roster_rows=None,
        dirty_hint=None,
        avail_epoch=None,
        avail_journal=None,
    ) -> ClusterTensors:
        """Device-resident availability threaded ACROSS serving windows.

        Unlike build_tensors_cached (which re-uploads the host availability
        rows verbatim), this keeps the device availability equal to
        `last window's committed base` + `external deltas`: the kernel's
        `available_after` from the previous dispatch is extended with the
        ADDITIVE difference between the current host view and an int64
        mirror of what the device already embodies. Gang placements of a
        window are debited from the mirror when the window is fetched
        (pack_window_fetch), so the host's own reservation bookkeeping for
        those gangs does not get shipped a second time — and a gang whose
        reservation the host then failed to create is automatically
        restored by the next delta. This is what makes it safe to DISPATCH
        window k+1 before FETCHING window k (the pipelined serving loop):
        k's admissions ride the device-side thread, not the host view.

        Raises PipelineDrainRequired when a non-availability field changed
        while a window is still in flight — fetch it first, then retry.
        Single-threaded by contract (the predicate batcher thread).

        `statics_version` is the HostFeatureStore's statics epoch: when the
        caller passes one and it matches the epoch of the resident pipeline
        state, the eight per-window O(nodes) static-field array compares
        are skipped outright (the epoch bumps on every node event, so an
        unchanged epoch proves the fields unchanged). Without it (or on a
        mismatch) the array compares run as before."""
        host = self.build_tensors(
            nodes, usage, overhead,
            full_node_list=True, topo_version=topo_version,
            roster_rows=roster_rows, dirty_hint=dirty_hint,
            avail_epoch=avail_epoch, avail_journal=avail_journal,
        )
        stats = self.device_state_stats
        p = self._pipe
        if p is not None and not self._resolve_base(p):
            p = None  # pooled combine failed: pipeline dead, full re-upload
        static_plan = None
        if p is not None and p["host"].available.shape == host.available.shape:
            statics_same = (
                statics_version is not None
                and statics_version == p.get("statics_version")
            ) or all(
                # Identity first: the resident build shares unchanged
                # static arrays across builds, so `is` settles most
                # fields without an O(N) value compare.
                getattr(p["host"], f) is getattr(host, f)
                or np.array_equal(getattr(p["host"], f), getattr(host, f))
                for f in _STATIC_FIELDS
            )
            if not statics_same and self._delta_statics:
                # Node event touching few rows: ship a static row-scatter
                # delta instead of the full blob (and instead of draining
                # the pipeline). In-flight windows are unaffected — their
                # decisions were computed from (and reconstruct against)
                # their own dispatch-time host view, exactly as with
                # availability deltas.
                static_plan = self._plan_static_delta(p["host"], host)
        else:
            statics_same = False
        if statics_same or static_plan is not None:
            mirror = p["mirror"]
            dirty = self._mirror_dirty(p, host, mirror)
            avail = p["avail"]
            k = len(dirty)
            if k:
                delta_rows = (
                    host.available[dirty].astype(np.int64) - mirror[dirty]
                )
            # An external availability swing too large for the int32 delta
            # rows falls through to a FULL re-upload instead of wrapping
            # silently and corrupting the device base (with windows in
            # flight that raises PipelineDrainRequired below — the standard
            # retry contract of this method).
            fits_i32 = k == 0 or (
                delta_rows.min() >= np.iinfo(np.int32).min
                and delta_rows.max() <= np.iinfo(np.int32).max
            )
            if not fits_i32 and p["unfetched"]:
                if self.telemetry is not None:
                    self.telemetry.on_pipeline_event("drain")
                raise PipelineDrainRequired(
                    "availability delta exceeds int32 with a window in flight"
                )
            if fits_i32:
                static_fields = {}
                if static_plan is not None:
                    static_fields = self._apply_static_delta(
                        p, host, static_plan
                    )
                if k:
                    # The prune planner's O(changed) sync rides exactly
                    # this dirty set (plus fetched placement rows).
                    self._prune_note_rows(dirty)
                    # ... and so do the pool slots' availability mirrors:
                    # the canonical device base changes at these rows.
                    self._avail_journal_note(p, dirty)
                    # Pad with a repeated index but ZERO delta rows: .add
                    # is cumulative, so padding must contribute nothing.
                    # The base is DONATED into the add — committed-base
                    # updates are in place, and the consumed buffer (the
                    # previous build's availability) is dead by contract.
                    kb = _bucket(k, 16)
                    idx = np.full(kb, dirty[0], dtype=np.int32)
                    idx[:k] = dirty
                    rows = np.zeros((kb, host.available.shape[1]), np.int32)
                    rows[:k] = delta_rows
                    avail = _add_rows_donated(
                        avail, jnp.asarray(idx), jnp.asarray(rows)
                    )
                    # The mirror is pipeline-private: patch the dirty rows
                    # in place instead of re-materializing the full int64
                    # view per window.
                    mirror[dirty] = host.available[dirty]
                    stats["delta_uploads"] += 1
                    stats["delta_rows"] += k
                    stats["upload_bytes"] += rows.nbytes + idx.nbytes
                    self.last_state_upload = "delta"
                    if self.telemetry is not None:
                        self.telemetry.on_transfer(
                            "h2d", rows.nbytes + idx.nbytes
                        )
                elif static_plan is not None:
                    self.last_state_upload = "delta"
                else:
                    stats["reuse_hits"] += 1
                    self.last_state_upload = "reuse"
                tensors = dataclasses.replace(
                    p["tensors"], available=avail, **static_fields
                )
                tensors.host = host
                p.update(
                    host=host, tensors=tensors, avail=avail,
                    statics_version=statics_version,
                    # Mirror synced: the pending ledger drains (a dense
                    # sync equally re-established mirror == host).
                    pending=[],
                )
                # Statics synced to `host`: restart the delta-diff
                # candidate accumulator.
                self._static_acc = []
                return tensors
        if p is not None and p["unfetched"]:
            if self.telemetry is not None:
                self.telemetry.on_pipeline_event("drain")
            raise PipelineDrainRequired(
                "cluster topology changed with a window in flight"
            )
        # Upload a COPY of the availability: jax's CPU device_put
        # ZERO-COPIES a suitably-aligned numpy buffer, so device_put of
        # the resident host buffer can leave the device base ALIASING
        # memory the resident build then patches in place — the base
        # absorbs the change by aliasing AND again via the next delta
        # upload (double debit; reproduced on the pooled path whenever
        # the allocator happened to align the buffer). Statics buffers
        # are safe as-is: changed static rows always COW before the
        # write, and same-value writes cannot skew an alias. One [N,3]
        # int32 copy per FULL upload, never on the delta path.
        tensors = jax.device_put(
            dataclasses.replace(host, available=host.available.copy())
        )
        tensors.host = host
        stats["full_uploads"] += 1
        stats["upload_bytes"] += _tensors_nbytes(host)
        self.last_state_upload = "full"
        # Statics may have changed with this full upload: pool replicas
        # re-upload on their next turn. The delta journal cannot bridge a
        # full upload — clearing it forces every lagging replica onto the
        # full path (the torn-update contract). The prune PLANNER keys on
        # HOST state, not device state: when this build named its changed
        # rows exactly, it persists (lazy warm start) instead of re-paying
        # the O(N log N) cold replan.
        self._static_epoch += 1
        self._static_journal.clear()
        self._static_acc = []  # fresh statics baseline on device
        self._prune_full_upload()
        if self.telemetry is not None:
            self.telemetry.on_transfer("h2d", _tensors_nbytes(host))
        self._pipe = {
            "host": host,
            "tensors": tensors,
            "avail": tensors.available,
            "mirror": host.available.astype(np.int64),
            "unfetched": [],
            "statics_version": statics_version,
            # Dirty-row ledger for the event-fed mirror sync: rows the
            # resident build patches + rows fetched placements debit;
            # None = unknown (dense compare next build). Starts empty —
            # the mirror IS the host view at this instant.
            "pending": [],
            # Availability epoch + journal for the per-slot device
            # mirrors (ISSUE 15): each canonical-base mutation bumps the
            # epoch and journals the rows it touched (None = unknowable,
            # forcing a full re-ship across that epoch). Fresh pipeline
            # generation: every slot replica from before is stale.
            "avail_epoch": 0,
            "avail_journal": {},
            "token": next(self._pipe_tokens),
        }
        return tensors

    def _avail_journal_note(self, p, rows) -> None:
        """Bump the pipeline's availability epoch with the rows the
        canonical device base just changed on — a window's kept/partition
        rows at dispatch, a delta upload's dirty rows — or None when the
        rows are unknowable (an unpruned whole-window commit). Pool-slot
        mirrors catch up by scattering the journaled union; any gap or
        None epoch in a slot's missed chain forces the full re-ship. A
        journaled row set may be a SUPERSET of what actually changed:
        catch-up scatters values gathered from the canonical base, so
        extra rows are byte-identical no-ops. Returns the epoch (None
        when no pool): an unknowable (None) entry can be PATCHED once the
        window's fetch learns its exact commit rows — later catch-ups
        then cross the epoch instead of full re-shipping."""
        if self._pool is None or p is None:
            return None
        e = p["avail_epoch"] + 1
        p["avail_epoch"] = e
        j = p["avail_journal"]
        j[e] = None if rows is None else np.asarray(rows)
        while len(j) > 64:
            j.pop(next(iter(j)))
        return e

    def _journal_rows_between(self, p, lo: int, hi: int):
        """Union of journaled rows across epochs (lo, hi], or None when
        the chain has a gap / an unknowable epoch."""
        if lo == hi:
            return np.empty(0, np.int64)
        j = p["avail_journal"]
        out = []
        for e in range(lo + 1, hi + 1):
            rows = j.get(e)
            if rows is None:
                return None
            out.append(rows)
        return np.unique(np.concatenate(out).astype(np.int64))

    def _pool_full_base(self, p, slot, base, base_device):
        """The full committed base, on `slot`, for a whole-window pooled
        solve — via the slot's delta-synced availability MIRROR (ISSUE
        15, the PR 11 statics epoch-journal pattern extended to
        availability). The canonical base lives on one device; a
        dispatch landing elsewhere used to re-ship the whole [N,3] — now
        a slot holding a replica whose missed epochs are all journaled
        catches up by scattering just the union of changed rows.

        Donation invariant: the returned array is consumed by the solve,
        so it must have no other referent. The canonical buffer is never
        returned to a non-owner slot (they get a caught-up replica or a
        fresh copy), and when the canonical migrates, the OLD buffer is
        handed to the slot hosting it as that slot's mirror — p["avail"]
        stops referencing it, so it is never donated again."""
        tel = self.telemetry
        if slot.is_mesh:
            return slot.place_avail(base)
        token, epoch = p["token"], p["avail_epoch"]
        if base_device == slot.placement:
            # Canonical already lives here; the solve donates it in
            # place. Clear any stale replica — it must never alias the
            # canonical, and after this solve the slot's state IS the
            # new canonical.
            slot.avail = None
            slot.avail_epoch = -1
            slot.mirror["reuse"] += 1
            return base
        # The canonical migrates to `slot`: hand the old buffer to the
        # slot that hosts it as ITS mirror (it will catch up by scatter
        # when the canonical comes back around).
        for o in self._pool.slots:
            if not o.is_mesh and o.placement == base_device:
                o.avail = base
                o.avail_epoch = epoch
                o.avail_token = token
                break
        rep = slot.avail
        rows = None
        if (
            rep is not None
            and slot.avail_token == token
            and 0 <= slot.avail_epoch <= epoch
            and getattr(rep, "shape", None) == getattr(base, "shape", None)
        ):
            rows = self._journal_rows_between(p, slot.avail_epoch, epoch)
        slot.avail = None
        slot.avail_epoch = -1
        if rows is not None:
            if not rows.size:
                slot.mirror["reuse"] += 1
                return rep
            idx = np.resize(rows, _bucket(len(rows), 16)).astype(np.int32)
            vals = _take_rows(base, jax.device_put(idx, base_device))
            out = _scatter_rows(
                rep,
                slot._put(idx),
                jax.device_put(vals, slot.placement),
            )
            nbytes = idx.nbytes + int(getattr(vals, "nbytes", 0))
            slot.mirror["catchup"] += 1
            slot.mirror["delta_rows"] += int(rows.size)
            if tel is not None:
                tel.on_device_mirror(
                    slot.label, "catchup", int(rows.size), nbytes
                )
            return out
        slot.mirror["dense"] += 1
        if tel is not None:
            tel.on_device_mirror(
                slot.label, "dense", int(base.shape[0]),
                int(getattr(base, "nbytes", 0)),
            )
        return slot.place_avail(base)

    def _plan_static_delta(self, prev, host):
        """(changed field names, dirty rows) when the static drift between
        two same-shape host views is small enough to ship as a row
        scatter; None sends the caller to the full-upload/drain path.
        Called only when at least one static field differs.

        When the resident build NAMED its changed rows
        (`_last_build_rows`), the diff runs over just those rows: the
        statics copy-on-write only ever rewrites the named patch rows, so
        they are a proven superset of every field difference — the
        8-field O(N) compare per node event becomes O(changed) at the
        million-node tier (ISSUE 15). A build that could not name its
        rows keeps the dense diff."""
        n = host.available.shape[0]
        acc = self._static_acc
        cand = None
        if acc is not None:
            cand = (
                np.unique(np.concatenate(acc)).astype(np.int64)
                if acc
                else np.empty(0, np.int64)
            )
            cand = cand[cand < n]
            if not cand.size:
                # A field differs but no build named a row since the
                # last sync: inconsistent — take the dense diff.
                cand = None
        changed: list[str] = []
        sel = cand if cand is not None else slice(None)
        rows_mask = np.zeros(
            cand.shape[0] if cand is not None else n, dtype=bool
        )
        for f in _STATIC_FIELDS:
            a = np.asarray(getattr(prev, f))
            b = np.asarray(getattr(host, f))
            if a is b:
                continue
            neq = a[sel] != b[sel]
            if neq.ndim == 2:
                neq = neq.any(axis=1)
            if not neq.any():
                continue
            changed.append(f)
            rows_mask |= neq
        if not changed:
            return None
        rows = (
            cand[rows_mask] if cand is not None
            else np.flatnonzero(rows_mask)
        )
        if len(rows) > max(32, n // 8):
            return None
        return changed, rows

    def _apply_static_delta(self, p, host, plan) -> dict:
        """Scatter the changed static-field rows into the resident device
        tensors; returns the replaced device fields for
        dataclasses.replace. Bumps the statics epoch with a JOURNAL entry
        so pool replicas catch up by scattering the same rows, and
        re-keys the prefilter's rank index rows in place (instead of the
        full-upload invalidate)."""
        changed, rows = plan
        k = len(rows)
        # np.resize pads by cycling the dirty rows; duplicate indices then
        # carry identical values, so .set stays deterministic.
        idx = np.resize(rows, _bucket(k, 16)).astype(np.int32)
        idx_dev = jnp.asarray(idx)
        out = {}
        nbytes = idx.nbytes
        for f in changed:
            vals = np.asarray(getattr(host, f))[idx]
            out[f] = _scatter_rows(
                getattr(p["tensors"], f), idx_dev, jnp.asarray(vals)
            )
            nbytes += vals.nbytes
        self._static_epoch += 1
        self._static_journal[self._static_epoch] = rows
        while len(self._static_journal) > 64:
            self._static_journal.pop(next(iter(self._static_journal)))
        stats = self.device_state_stats
        stats["static_delta_uploads"] += 1
        stats["static_delta_rows"] += k
        stats["upload_bytes"] += nbytes
        if self.telemetry is not None:
            self.telemetry.on_transfer("h2d", nbytes)
        if self._planner is not None:
            # Static row-deltas (validity/zone/name-rank/eligibility
            # flips) feed the planner as STATIC dirt: a kept row's static
            # flip re-scans its zone, a new row merges exactly.
            self._planner.note_static(rows)
        for ck, ent in list(self._prune_gather_cache.items()):
            # A cached statics sub-blob gathered rows that just changed:
            # drop that entry (the kept set itself usually changes too,
            # but a static flip on a kept row with an unchanged keep must
            # still force a re-gather). Entries whose kept rows the delta
            # missed keep serving.
            if np.isin(rows, ent["keep"]).any():
                self._prune_gather_cache.pop(ck, None)
        return out

    def _resolve_base(self, p) -> bool:
        """Resolve a pooled window's pending committed-base combine (the
        scatter of every partition's sub-base back into the global base).
        False when the combine failed — the pipeline is dead exactly like
        a failed decision fetch: drop it, count it, rebuild from host
        truth (in-flight handles still fetch fine on their own futures)."""
        avail = p.get("avail")
        if not hasattr(avail, "result"):
            return True
        try:
            p["avail"] = avail.result()
            return True
        except Exception:
            self._pipe = None
            if self.telemetry is not None:
                self.telemetry.on_pipeline_event("fetch-failure")
            return False

    def _label_rank(self, node: Node, prio) -> int:
        if prio is None:
            return INT32_INF
        label, values = prio
        val = node.labels.get(label)
        if val is not None and val in values:
            return values.index(val)
        return INT32_INF

    def _build_tensors_native(
        self,
        nodes: Sequence[Node],
        usage,
        overhead,
        *,
        full_node_list: bool = False,
        topo_version: Optional[int] = None,
        roster_rows: "np.ndarray | None" = None,
        dirty_hint: "tuple | None" = None,
        avail_epoch: "int | None" = None,
        avail_journal: "dict | None" = None,
    ) -> ClusterTensors:
        """Arena-backed ClusterTensors. Deviation from the Python builder,
        deliberate: name ranks are GLOBAL over all known nodes rather than
        recomputed over the request's filtered subset — the rank values
        differ but their relative order (all the sort kernels consume) is
        identical for any subset.

        RESIDENT since ISSUE 13: the serving path (full node list + a
        verified topology chain + a gap-free availability journal) keeps
        the nine output buffers alive between builds and patches exactly
        the changed rows (journal rows + arena upserts) in one C call.
        Static fields copy-on-write when their rows change, so in-flight
        window handles keep their dispatch-time statics; `available` is
        patched in place with an undo journal for the rare dense
        reconstructions. Every other caller (filtered subsets, missing
        epochs, pad growth) takes the full materialization into FRESH
        buffers — prior handles' arrays are never touched."""
        arena = self._arena
        seen = self._node_seen
        # Topology-version fast path: when the backend exposes a node
        # version (store/backend.py nodes_version) and it hasn't moved
        # since the last build, the whole O(nodes) identity walk is
        # skipped — at 10k nodes this walk was a measured serving-window
        # hotspot despite doing no upserts.
        # Skipping is safe regardless of subset: an unchanged version means
        # no node was created/updated/deleted since the FULL-list build that
        # recorded it, so the walk would upsert nothing.
        topo = topo_version

        def _upsert(node) -> None:
            seen[node.name] = node
            # A deleted-then-re-added name is LIVE again: its parked
            # tombstone must not release the row out from under it (a
            # deferred _release_tombstones would unmap a live node and
            # hand its registry row to the free list).
            self._pending_tombstones.discard(node.name)
            idx = self.registry.intern(node.name)
            arena.upsert(
                idx,
                node.allocatable.as_array(),
                self.registry.zone_id(node.zone),
                node.unschedulable,
                node.ready,
                self._label_rank(node, self._driver_label_priority),
                self._label_rank(node, self._executor_label_priority),
            )
            # The resident buffers no longer embody this row's statics:
            # pending until a resident patch (or full rebuild) absorbs it.
            self._res_pending.append(idx)

        if not (topo is not None and topo == self._topo_seen):
            if (
                dirty_hint is not None
                and full_node_list
                and topo is not None
                and dirty_hint[0] == self._topo_seen
            ):
                # Update/ADD/DELETE node event with a verified version
                # chain (the feature store captured exactly what changed
                # since the version this arena last synced to): upsert
                # just the changed rows. New names intern and take a
                # GAPPED name rank between their lexicographic neighbours
                # (_NameRankSpace); deleted names tombstone — their rows
                # are masked out by the roster-row request mask and
                # recycled by _release_tombstones once their usage
                # drains. The existing roster is never re-walked.
                new_names = [
                    n.name for n in dirty_hint[1] if n.name not in seen
                ]
                for node in dirty_hint[1]:
                    _upsert(node)
                if new_names:
                    self._insert_name_ranks(new_names)
                for name in (
                    dirty_hint[2] if len(dirty_hint) > 2 else ()
                ):
                    if name in seen:
                        seen.pop(name, None)
                        self._rank_space.remove(name)
                        self._pending_tombstones.add(name)
                self._topo_seen = topo
            else:
                changed_names = False
                for node in nodes:
                    if seen.get(node.name) is node:
                        continue
                    if node.name not in seen:
                        changed_names = True
                    _upsert(node)
                if changed_names or self._rank_epoch < 0:
                    self._assign_all_name_ranks()
                if full_node_list and topo is not None:
                    # Only a full-list walk proves the arena is synced for
                    # this version; a filtered subset must not suppress
                    # future walks.
                    self._topo_seen = topo
        pad = _bucket(self.registry.capacity, 8)

        usage_t = self._dense_or_scatter(usage, pad)
        overhead_t = self._dense_or_scatter(overhead, pad)
        if self._pending_tombstones:
            self._release_tombstones(usage_t, overhead_t)

        # Only the serving contract (full node list + topology chain) may
        # consume the resident buffers — a filtered subset would bake its
        # request mask into them.
        serving = topo is not None and full_node_list
        res = self._snap_res
        rows_hint = None
        if (
            serving
            and res is not None
            and not self._res_full_pending
            and res["pad"] == pad
        ):
            rows_hint = self._avail_rows_between(
                res.get("avail_epoch"), avail_epoch, avail_journal
            )
        if rows_hint is not None:
            tensors = self._patch_resident(
                res, rows_hint, usage_t, overhead_t,
                nodes, topo, pad, roster_rows,
            )
            res["avail_epoch"] = avail_epoch
            return tensors
        return self._snapshot_full(
            pad, usage_t, overhead_t, nodes, topo, serving,
            roster_rows, avail_epoch,
        )

    def _request_mask(self, nodes, topo, pad, roster_rows, cacheable):
        """[pad] bool mask of this request's candidate rows. The arena
        knows every node ever seen; this request's candidate set is the
        (selector-filtered) `nodes` list. The O(nodes) index walk is
        memoized on the topology version; only a FULL node list is
        memoizable (caller-asserted) — a filtered subset of the same
        length would collide."""
        cached = self._topo_request_mask
        if (
            cacheable
            and cached is not None
            and cached[0] == (topo, pad, len(nodes))
        ):
            return cached[1]
        request_mask = np.zeros(pad, dtype=bool)
        if roster_rows is not None and len(roster_rows) == len(nodes):
            # Feature-store rows for exactly this node list: the mask
            # is one scatter, not an O(nodes) name->index walk.
            request_mask[roster_rows[roster_rows < pad]] = True
        else:
            idxs = [self.registry.index_of(n.name) for n in nodes]
            request_mask[
                [i for i in idxs if i is not None and i < pad]
            ] = True
        if cacheable:
            if (
                cached is not None
                and cached[1].shape[0] == pad
                and np.array_equal(cached[1], request_mask)
            ):
                # Topology moved but membership did not (the routine
                # node-UPDATE case): keep the OLD array object — mask
                # identity is what keeps valid_req, the domain-AND memo
                # and the planner's per-domain contexts stable across
                # events (ISSUE 15). One O(N) bool compare per node
                # event, never per window.
                request_mask = cached[1]
            self._topo_request_mask = (
                (topo, pad, len(nodes)), request_mask,
            )
        return request_mask

    def _avail_rows_between(self, prev, cur, journal):
        """(usage rows, overhead rows, node rows) changed between the
        resident build's synced availability epoch and the snapshot's,
        from the feature store's journal — None when the chain has a gap
        (journal break, eviction, or a caller that does not thread the
        journal): the build then runs one full materialization. The
        3-way split drives COW granularity: usage rows touch only
        `available`, overhead rows additionally `schedulable`, node rows
        any static field."""
        if prev is None or cur is None or journal is None:
            return None
        if cur < prev or cur - prev > 64:
            return None
        empty = np.empty(0, np.int64)
        if cur == prev:
            return empty, empty, empty
        arows: list = []
        orows: list = []
        nrows: list = []
        for e in range(prev + 1, cur + 1):
            ent = journal.get(e)
            if ent is None:
                return None
            arows.append(ent[0])
            orows.append(ent[1])
            nrows.append(ent[2])
        return (
            np.unique(np.concatenate(arows)),
            np.unique(np.concatenate(orows)),
            np.unique(np.concatenate(nrows)),
        )

    def _acc_build_rows(self) -> None:
        """Fold the build's named rows into the statics-delta candidate
        accumulator (None = a build could not name rows: the next
        _plan_static_delta falls back to the dense field diff)."""
        rows = self._last_build_rows
        if rows is None:
            self._static_acc = None
            return
        if self._static_acc is None:
            return
        if rows[0].size:
            self._static_acc.append(rows[0])
        if rows[1].size:
            self._static_acc.append(rows[1])

    def _note_consumer_rows(self, rows) -> None:
        """Rows the resident build just patched, appended to the device
        mirrors' pending ledgers (the pipelined mirror sync and the cached
        solo path scatter exactly these instead of dense-comparing)."""
        p = self._pipe
        if p is not None and p.get("pending") is not None:
            p["pending"].append(rows)
        d = self._dev
        if d is not None and d.get("pending") is not None:
            d["pending"].append(rows)

    def _note_consumers_unknown(self) -> None:
        """This build could not name its changed rows: the device mirrors
        fall back to one dense compare each."""
        p = self._pipe
        if p is not None:
            p["pending"] = None
        d = self._dev
        if d is not None:
            d["pending"] = None

    def _mirror_dirty(self, p, host, mirror) -> np.ndarray:
        """Rows whose availability the next delta upload must ship.

        Event-fed dirty set (ISSUE 13): the pipeline's pending ledger —
        rows the resident build patched plus rows fetched placements
        debited from the mirror — is a proven superset of every
        mirror-vs-host difference, so the sync compares just those rows.
        A build that could not name its rows leaves the ledger None and
        this runs the dense [N]-wide compare once (counted in
        mirror_rows_compared — the counter CI pins at 0 in steady state).
        `build_oracle` re-runs the dense compare after the dirty-set sync
        and fails loudly on a missed row (the equivalence suites' guard).
        """
        pend = p.get("pending")
        bs = self.build_stats
        if pend is None:
            dirty = np.flatnonzero((mirror != host.available).any(axis=1))
            bs["mirror_rows_compared"] += int(mirror.shape[0])
            bs["mirror_dense_syncs"] += 1
            return dirty
        if pend:
            cand = np.unique(
                np.concatenate([np.asarray(c) for c in pend])
            ).astype(np.int64)
            cand = cand[cand < mirror.shape[0]]
        else:
            cand = np.empty(0, np.int64)
        if cand.size:
            neq = (mirror[cand] != host.available[cand]).any(axis=1)
            dirty = cand[neq]
        else:
            dirty = cand
        bs["dirty_rows"] += int(cand.size)
        if self.build_oracle:
            bs["oracle_checks"] += 1
            oracle = np.flatnonzero((mirror != host.available).any(axis=1))
            missed = np.setdiff1d(oracle, dirty)
            if missed.size:
                raise AssertionError(
                    "dirty-set mirror sync missed changed rows "
                    f"{missed[:8].tolist()} (of {missed.size})"
                )
        return dirty

    _RES_FIELDS = (
        "available", "schedulable", "zone_id", "name_rank",
        "label_rank_driver", "label_rank_executor",
        "unschedulable", "ready", "valid",
    )

    def _res_tensors(self, res) -> ClusterTensors:
        f = res["fields"]
        # Memoized bool views of the uint8 backings: view IDENTITY is
        # stable while the backing is (the pipelined statics compare
        # settles unchanged fields with `is`, not an O(N) compare).
        views = res.setdefault("views", {})
        for name in ("unschedulable", "ready"):
            v = views.get(name)
            if v is None or v.base is not f[name]:
                views[name] = v = f[name].view(np.bool_)
        return ClusterTensors(
            f["available"],
            f["schedulable"],
            f["zone_id"],
            f["name_rank"],
            f["label_rank_driver"],
            f["label_rank_executor"],
            views["unschedulable"],
            views["ready"],
            res["valid_req"],
        )

    def _snapshot_full(
        self, pad, usage_t, overhead_t, nodes, topo, serving,
        roster_rows, avail_epoch,
    ) -> ClusterTensors:
        """Full arena materialization into FRESH buffers (cold build, pad
        growth, journal gap, filtered subset). Prior handles keep the old
        arrays; a serving build replaces the resident state with the new
        buffers."""
        raw = self._arena.snapshot_raw(pad, usage_t, overhead_t)
        fields = dict(zip(self._RES_FIELDS, raw))
        request_mask = self._request_mask(
            nodes, topo, pad, roster_rows, serving
        )
        valid_req = fields["valid"].view(np.bool_) & request_mask
        self._last_build_rows = None
        self._acc_build_rows()
        self._note_consumers_unknown()
        if serving:
            self._snap_res = res = {
                "pad": pad,
                "avail_epoch": avail_epoch,
                "mask": request_mask,
                "fields": fields,
                "valid_req": valid_req,
            }
            self._res_pending = []
            self._res_full_pending = False
            self.build_stats["full_snapshots"] += 1
            return self._res_tensors(res)
        return ClusterTensors(
            *raw[:6],
            raw[6].view(np.bool_),
            raw[7].view(np.bool_),
            valid_req,
        )

    def _patch_resident(
        self, res, rows_hint, usage_t, overhead_t, nodes, topo, pad,
        roster_rows,
    ) -> ClusterTensors:
        """O(K + changed) build: recompute exactly the changed rows into
        the resident buffers. Statics copy-on-write at the granularity
        their change class requires — node rows COW every static field,
        overhead rows only `schedulable` (in-flight handles keep
        dispatch-time arrays either way); `available` patches in place
        with an undo journal while pruned handles are in flight."""
        arows, orows, nrows = rows_hint
        if self._res_pending:
            prows = np.unique(np.asarray(self._res_pending, np.int64))
            self._res_pending = []
            nrows = np.union1d(nrows, prows) if nrows.size else prows
        patch = arows
        for extra in (orows, nrows):
            if extra.size:
                patch = np.union1d(patch, extra) if patch.size else extra
        f = res["fields"]
        mask = self._request_mask(nodes, topo, pad, roster_rows, True)
        mask_changed = mask is not res["mask"]
        if patch.size:
            if nrows.size:
                # Node rows: any static field may move — COW them all so
                # stale handles' certify/fallback/escalation inputs stay
                # dispatch-time exact. The COW is also LOAD-BEARING for
                # the device protocol: _plan_static_delta detects which
                # static rows must ship by diffing the previous build's
                # arrays against these — an in-place statics patch would
                # make every node event invisible to the delta upload.
                # (O(N) memcpy per node event is the accepted cost; the
                # steady serving path never enters this branch.)
                for name in self._RES_FIELDS[1:]:
                    f[name] = f[name].copy()
            elif orows.size:
                # Overhead rows touch available + schedulable only: one
                # COW instead of eight (the routine pod-churn case).
                f["schedulable"] = f["schedulable"].copy()
            avail = f["available"]
            if self._avail_handles:
                # GC the undo journal to the oldest live handle's
                # generation before appending — sustained pipelined
                # serving always has a handle in flight, so an
                # only-clear-when-empty policy would grow it forever.
                gens = [
                    h.avail_gen
                    for h in self._avail_handles
                    if h.avail_gen is not None
                ]
                if gens:
                    min_gen = min(gens)
                    if self._avail_undo and self._avail_undo[0][0] < min_gen:
                        self._avail_undo = [
                            e for e in self._avail_undo if e[0] >= min_gen
                        ]
                self._avail_undo.append(
                    (self._avail_gen, avail, patch, avail[patch].copy())
                )
            elif self._avail_undo:
                self._avail_undo.clear()
            self._avail_gen += 1
            self._arena.snapshot_rows(
                patch, usage_t, overhead_t,
                f["available"], f["schedulable"], f["zone_id"],
                f["name_rank"], f["label_rank_driver"],
                f["label_rank_executor"], f["unschedulable"], f["ready"],
                f["valid"],
            )
            self._note_consumer_rows(patch)
        if mask_changed:
            res["mask"] = mask
            res["valid_req"] = f["valid"].view(np.bool_) & mask
        elif nrows.size:
            vals = f["valid"].view(np.bool_)[nrows] & mask[nrows]
            if not np.array_equal(vals, res["valid_req"][nrows]):
                # COW only when the valid mask actually moved: a static
                # flip that leaves validity intact (unschedulable,
                # labels) keeps the valid_req OBJECT stable — identity
                # the domain-AND memo and the planner's per-domain
                # contexts key on (ISSUE 15).
                vr = res["valid_req"].copy()
                vr[nrows] = vals
                res["valid_req"] = vr
        # Planner feed classes: overhead rows change AVAILABILITY keys
        # (avail = alloc - usage - overhead), node rows are static dirt.
        self._last_build_rows = (
            np.union1d(arows, orows) if orows.size else arows,
            nrows,
        )
        self._acc_build_rows()
        self.build_stats["incremental_builds"] += 1
        return self._res_tensors(res)

    def _release_tombstones(self, usage_t, overhead_t) -> None:
        """Recycle deleted nodes' registry rows (the delete-patch
        satellite's second half): a tombstoned row re-enters the
        registry's free list — a future node ADD reuses the index, whose
        fresh statics then ship as an ordinary delta-statics journal row.
        A row with residual reservation usage or schedulable overhead
        stays parked (recycling it would graft the leftovers onto the
        next node) and is retried every build; so does everything while
        a dispatched window is in flight (its fetch may still resolve
        the row's name)."""
        p = self._pipe
        if p is not None and p["unfetched"]:
            return
        still = set()
        for name in self._pending_tombstones:
            row = self.registry.index_of(name)
            if row is None:
                continue
            if (
                row < usage_t.shape[0]
                and row < overhead_t.shape[0]
                and not usage_t[row].any()
                and not overhead_t[row].any()
            ):
                self.registry.remove(name)
                self.tombstones_recycled += 1
            else:
                still.add(name)
        self._pending_tombstones = still

    def _assign_all_name_ranks(self) -> None:
        """Full (re)assignment of the arena's name ranks from the sorted
        known-name set — the cold path, and the gap-exhaustion fallback."""
        self._res_full_pending = True  # every slot's rank value moved
        space = self._rank_space
        space.assign_all(sorted(self._node_seen))
        index_of = self.registry.index_of
        idx = np.fromiter(
            (index_of(name) for name in space.names),
            np.int64,
            count=len(space.names),
        )
        self._arena.set_name_ranks(np.empty(0, np.int64))  # reset to INF
        self._arena.set_name_rank_values(
            idx, np.asarray(space.ranks, np.int32)
        )
        self._rank_epoch += 1

    def _insert_name_ranks(self, names: list[str]) -> None:
        """O(changed) rank insertion for newly-added names. A crowded gap
        triggers a LOCAL order-maintenance relabel (the rebalanced
        neighborhood re-scatters and rides the resident build's static
        dirt); only genuine space exhaustion falls back to the full
        renumber (counted on the space)."""
        space = self._rank_space
        changed: list[str] = []
        renumbered = False
        for name in names:
            out = space.insert(name)
            if out is None:
                renumbered = True
            elif not renumbered:
                changed.extend(out)
        index_of = self.registry.index_of
        if renumbered:
            idx = np.fromiter(
                (index_of(name) for name in space.names),
                np.int64,
                count=len(space.names),
            )
            self._arena.set_name_ranks(np.empty(0, np.int64))
            self._arena.set_name_rank_values(
                idx, np.asarray(space.ranks, np.int32)
            )
            # Every row's rank value moved: resident order keys are stale,
            # and so are the resident build's name-rank rows.
            self._res_full_pending = True
            self._prune_invalidate()
        elif changed:
            # Every rank-space name has a registry row by construction
            # (tombstones leave the space before their row recycles); the
            # filter is belt+braces against a future ordering change.
            pairs = [
                (r, n)
                for r, n in ((index_of(n), n) for n in changed)
                if r is not None
            ]
            if pairs:
                self._arena.set_name_rank_values(
                    np.asarray([r for r, _ in pairs], np.int64),
                    # rank_of at scatter time: duplicates across
                    # rebalances resolve to the FINAL value regardless of
                    # visit order.
                    np.asarray(
                        [space.rank_of(n) for _, n in pairs], np.int32
                    ),
                )
                # Rebalanced rows' name ranks moved: resident static dirt
                # (the build patches them; the planner re-keys via the
                # static row-delta it detects).
                self._res_pending.extend(int(r) for r, _ in pairs)
        self._rank_epoch += 1

    def _dense_or_scatter(self, mapping, pad: int) -> np.ndarray:
        """[pad, 3] int64: a dense array is padded/truncated in one vectorized
        op (rows past `pad` can only be registry-unused zeros); a map is
        scattered entry-by-entry (the fallback path)."""
        if isinstance(mapping, np.ndarray):
            if (
                mapping.shape[0] == pad
                and mapping.dtype == np.int64
                and mapping.flags.c_contiguous
            ):
                # Zero-copy fast path: the feature store's resident dense
                # aggregates already match the pad bucket in steady state,
                # and every consumer reads without mutating — copying
                # [N,3] int64 per window was a measured 1M-tier cost.
                return mapping
            out = np.zeros((pad, NUM_DIMS), dtype=np.int64)
            rows = min(pad, mapping.shape[0])
            out[:rows] = mapping[:rows]
            return out
        out = np.zeros((pad, NUM_DIMS), dtype=np.int64)
        for name, res in mapping.items():
            idx = self.registry.index_of(name)
            if idx is not None and idx < pad:
                out[idx] += res.as_array()
        return out

    def candidate_mask(self, tensors, node_names: Sequence[str]) -> np.ndarray:
        n = tensors.available.shape[0]
        # Native-ingest tickets (server/ingest.NativeNodeNames) are hashable
        # by content digest with memcmp equality — key the cache on the
        # ticket itself so a steady-state request (kube-scheduler resends
        # the same candidate list every call) hits WITHOUT materializing
        # its 10k names or hashing a 10k-string tuple; only a cold miss
        # iterates. Plain lists keep the tuple key.
        names = (
            node_names
            if getattr(node_names, "names_digest", None) is not None
            else tuple(node_names)
        )

        def _build():
            mask = np.zeros(n, dtype=bool)
            unresolved: set = set()
            index_of = self.registry.index_of
            for name in names:
                idx = index_of(name)
                if idx is not None and idx < n:
                    mask[idx] = True
                elif idx is None:
                    # A candidate name with no registry row yet: if it
                    # ever interns, the mask must flip — remembered so
                    # the epoch-journal patch stays exact.
                    unresolved.add(name)
            # Shared across callers — must be treated read-only (every
            # consumer either copies via `&`/stack or hands it straight to
            # the device).
            mask.flags.writeable = False
            return mask, unresolved

        for _ in range(4):
            epoch = self.registry.epoch
            if epoch & 1:  # mutation in flight: the walk would be torn
                continue
            key = (n, epoch, names)
            mask = self._cand_cache.get(key)
            if mask is not None:
                return mask
            patched = self._cand_try_patch(names, n, epoch)
            shared = self._sweep_shared
            if patched is not None:
                mask, unresolved, removed = patched
            elif shared is not None and key in shared:
                # Replay sweep (ISSUE 18): the registry state is
                # arm-invariant (node events are inputs, not decisions), so
                # a sibling lane's mask for the same (n, epoch, ticket) is
                # THIS lane's mask — reuse it instead of re-walking the
                # name->row map. Validated by the same seqlock below.
                mask, unresolved = shared[key]
                removed = set()
                shared["__hits__"] = shared.get("__hits__", 0) + 1
            else:
                mask, unresolved = _build()
                removed = set()
            # Seqlock read: the walk is valid only if the epoch is unchanged
            # after it — otherwise the mask may mix old and new name->index
            # mappings; rebuild.
            if self.registry.epoch == epoch:
                if shared is not None and key not in shared:
                    shared[key] = (mask, unresolved)
                self._cand_cache.put(key, mask)
                self._cand_patch.put(
                    names, (epoch, n, mask, unresolved, removed)
                )
                if getattr(names, "patch_base", None) is not None:
                    # Re-based: drop the lineage back-reference so old
                    # ticket generations can be collected.
                    try:
                        names.patch_base = None
                    except AttributeError:
                        pass
                return mask
        # Registry churning continuously: one consistent build under the
        # registry's lock (uncached — the epoch is stale by construction).
        return self.registry.read_consistent(lambda: _build()[0])

    def _cand_try_patch(self, names, n: int, epoch: int):
        """Patch a previously built candidate mask across registry epochs
        via the mapping-change journal (ISSUE 13): a node ADD used to
        rebuild every cached mask with an O(N) name->row walk — at the
        million-node tier that walk dominated the ADD budget. The patch
        is EXACT: a newly interned name is a member iff it was previously
        unresolved (named by the candidate list before it had a row) or
        previously removed (delete -> re-add); a removed name clears its
        row and parks in `removed` so its re-add re-members. Returns
        (mask, unresolved, removed) or None (no base / journal gap / too
        many ops / pad moved).

        Domain tickets additionally carry LINEAGE (extender._DomainNames
        patch_base/added/removed): a node event that changed an affinity
        domain's membership creates a NEW ticket naming its exact deltas
        — the patch follows the chain to the last ticket it has a base
        for, applies the registry ops, then replays the membership deltas
        oldest-first."""
        prev = self._cand_patch.get(names)
        lineage: list = []
        base_key = names
        while prev is None and len(lineage) < 8:
            base = getattr(base_key, "patch_base", None)
            if base is None:
                return None
            lineage.append(base_key)
            base_key = base
            prev = self._cand_patch.get(base_key)
        if prev is None:
            return None
        e0, n0, mask0, unresolved0, removed0 = prev
        # epoch == e0 is patchable: update/delete-driven domain membership
        # changes arrive as lineage deltas WITHOUT interning a name, so
        # the registry epoch does not move (journal replay is then empty
        # and the lineage alone is exact). Without lineage an equal epoch
        # means nothing changed — the LRU hit would have served.
        if n0 != n or epoch < e0 or (epoch == e0 and not lineage):
            return None
        ops = self.registry.journal_between(e0, epoch)
        if ops is None or len(ops) > 4096:
            return None
        # Copy-on-WRITE, not copy-on-patch: when no op actually flips a
        # bit (the overwhelmingly common case — a node event elsewhere in
        # the roster bumped the epoch, this domain's membership is
        # untouched), the ORIGINAL mask object re-caches under the new
        # epoch. Mask identity is load-bearing (ISSUE 15): the domain-AND
        # memo and the planner's per-domain plan contexts key on it, so
        # an unrelated node ADD must not cold-restart every partition's
        # planning context.
        mask = mask0
        writable = False

        def _w():
            nonlocal mask, writable
            if not writable:
                mask = mask0.copy()
                writable = True

        unresolved = set(unresolved0)
        removed = set(removed0)
        for op, nm, row in ops:
            if op == "add":
                member = nm in removed or nm in unresolved
                removed.discard(nm)
                unresolved.discard(nm)
                if row < n:
                    if bool(mask[row]) != member:
                        _w()
                        mask[row] = member
                elif member:
                    return None  # member beyond the pad: rebuild
            else:  # remove
                if row < n and mask[row]:
                    removed.add(nm)
                    _w()
                    mask[row] = False
        # Membership deltas, oldest ticket first (each delta is relative
        # to its immediate base's content).
        index_of = self.registry.index_of
        for tk in reversed(lineage):
            for nm in tk.patch_removed:
                row = index_of(nm)
                if row is not None and row < n and mask[row]:
                    _w()
                    mask[row] = False
                unresolved.discard(nm)
                removed.discard(nm)
            for nm in tk.patch_added:
                removed.discard(nm)
                row = index_of(nm)
                if row is None:
                    unresolved.add(nm)
                elif row < n:
                    if not mask[row]:
                        _w()
                        mask[row] = True
                else:
                    return None
        if writable:
            mask.flags.writeable = False
        return mask, unresolved, removed

    def _and_valid(self, mask: np.ndarray, valid_np: np.ndarray) -> np.ndarray:
        """Memoized `mask & valid` for window domains. Identity-keyed with
        both operands pinned alive by the entry (id-recycle-safe): while
        neither the candidate mask nor the valid mask changed object, the
        SAME result object returns — which both skips the O(N) AND per
        window and keys the planner's per-domain context reuse."""
        key = (id(mask), id(valid_np))
        hit = self._dom_and_memo.get(key)
        if hit is not None and hit[0] is mask and hit[1] is valid_np:
            return hit[2]
        out = mask & valid_np
        out.flags.writeable = False
        self._dom_and_memo.put(key, (mask, valid_np, out))
        return out

    def _num_zones_bucket(self) -> int:
        return _bucket(max(len(self.registry._zone_names), 1), 2)

    def pack(
        self,
        strategy: str,
        tensors,
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_candidate_names: Sequence[str],
        domain_mask: np.ndarray | None = None,
    ) -> HostPacking:
        from spark_scheduler_tpu.tracing import tracer

        n = tensors.available.shape[0]
        host = _host_view(tensors)
        driver_mask = self.candidate_mask(tensors, driver_candidate_names)
        if domain_mask is None:
            domain_mask = np.asarray(host.valid)
        emax = _bucket(max(executor_count, 1), 8)
        tel = self.telemetry
        compiles_before = tel.compile_count() if tel is not None else None
        # The span covers dispatch AND the device->host transfer — the
        # transfer is where the device work is actually awaited.
        try:
            with tracer().span(
                "solve", strategy=strategy, nodes=n, executors=executor_count
            ):
                # ONE device->host transfer (one flat int32 blob) for the whole
                # decision: on a tunneled TPU every fetched array is a full RPC
                # round-trip (SURVEY.md §7 latency budget). Efficiency reporting
                # runs as pure numpy on the host-resident cluster arrays — zero
                # extra pulls.
                _shim("h2d")
                blob = _shimmed_device_get(
                    _pack_blob(
                        tensors,
                        jnp.asarray(driver_resources.as_array()),
                        jnp.asarray(executor_resources.as_array()),
                        jnp.int32(executor_count),
                        jnp.asarray(driver_mask),
                        jnp.asarray(domain_mask),
                        fill=strategy,
                        emax=emax,
                        num_zones=self._num_zones_bucket(),
                    )
                )
        except Exception as exc:
            if not (
                classify_slot_failure(exc) and self.degraded is not None
            ):
                raise
            # Solo pack does not thread the pipelined base, so the
            # pipeline survives; just this decision serves degraded.
            self._degraded_or_raise(exc)
            self.last_solve_info = {
                "path": "greedy-fallback",
                "nodes": n,
                "emax": emax,
                "compile_cache_hit": None,
                "degraded": True,
            }
            packing = self.fallback.pack(
                strategy, host, driver_resources, executor_resources,
                executor_count, driver_mask, domain_mask,
            )
            self.degraded.on_fallback_decision()
            return packing
        self.last_solve_info = {
            "path": "xla",
            "nodes": n,
            "emax": emax,
            "compile_cache_hit": (
                tel.compile_count() == compiles_before
                if tel is not None
                else None
            ),
        }
        if tel is not None:
            tel.on_pack(nodes=n, emax=emax)
            tel.on_transfer("d2h", getattr(blob, "nbytes", 0))
        driver_idx = int(blob[0])
        has_cap = bool(blob[1])
        executor_nodes = blob[2:]
        eff = avg_packing_efficiency_np(
            np.asarray(host.schedulable),
            np.asarray(host.available),
            driver_idx,
            executor_nodes,
            driver_resources.as_array(),
            executor_resources.as_array(),
        )
        exec_idx = [int(x) for x in executor_nodes if int(x) >= 0]
        self._device_recovered()
        return HostPacking(
            driver_node=self.registry.name_of(driver_idx) if driver_idx >= 0 else None,
            executor_nodes=[self.registry.name_of(i) for i in exec_idx],
            has_capacity=has_cap,
            efficiency_max=float(eff.max),
            efficiency_cpu=float(eff.cpu),
            efficiency_memory=float(eff.memory),
            efficiency_gpu=float(eff.gpu),
        )

    def can_batch(self, strategy: str) -> bool:
        return strategy in BATCHABLE_STRATEGIES

    def preemption_search(
        self,
        strategy: str,
        tensors,
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_candidate_names: Sequence[str],
        freed_cum: np.ndarray,  # [C, rows, 3] int — per-candidate freed capacity
        domain_mask: np.ndarray | None = None,
    ) -> tuple[int, dict]:
        """Batched masked-fit probe over candidate eviction sets (policy
        subsystem): candidate c's availability is the cluster plus
        `freed_cum[c]` (in registry index space). ONE vmapped device program
        solves all candidates (ops/packing.py preemption_batched_fit); with
        nested prefixes the first feasible index is the minimal eviction
        set. Returns (first feasible candidate index or -1, solve info)."""
        from spark_scheduler_tpu.ops.packing import (
            PREEMPTION_FILL,
            preemption_batched_fit,
        )

        n = tensors.available.shape[0]
        host = _host_view(tensors)
        driver_mask = self.candidate_mask(tensors, driver_candidate_names)
        if domain_mask is None:
            domain_mask = np.asarray(host.valid)
        emax = _bucket(max(executor_count, 1), 8)
        c = freed_cum.shape[0]
        freed = np.zeros((c, n, freed_cum.shape[2]), dtype=np.int32)
        rows = min(freed_cum.shape[1], n)
        freed[:, :rows, :] = freed_cum[:, :rows, :]
        fill = PREEMPTION_FILL.get(strategy, "tightly-pack")
        ok, _drv, _execs = preemption_batched_fit(
            tensors,
            jnp.asarray(freed),
            jnp.asarray(driver_resources.as_array()),
            jnp.asarray(executor_resources.as_array()),
            jnp.int32(executor_count),
            jnp.asarray(driver_mask),
            jnp.asarray(domain_mask),
            fill=fill,
            emax=emax,
            num_zones=self._num_zones_bucket(),
        )
        ok_host = np.asarray(ok)
        idx = int(np.argmax(ok_host)) if bool(ok_host.any()) else -1
        return idx, {
            "path": "xla-batched-preemption",
            "candidates": c,
            "nodes": n,
            "emax": emax,
            "fill": fill,
        }

    def pack_window(
        self,
        strategy: str,
        tensors,
        requests: Sequence[WindowRequest],
    ) -> list[WindowDecision]:
        """Serve a WINDOW of coalesced /predicates driver requests in ONE
        device program (VERDICT r2 #1).

        Each request becomes a SEGMENT of the scan: its pending earlier
        drivers (hypothetical rows) followed by its own application (the
        committing row). Availability rewinds to a threaded base between
        segments, so each segment sees exactly what that request's solo
        solve would have seen — decisions are identical to serving the
        requests one at a time in window order, including the FIFO
        earlier-driver semantics (resource.go:221-258). Within a segment
        the priority orders are computed ONCE from the segment-start
        availability, exactly as the reference sorts once per request
        (resource.go:299) and reuses the orders while only availability
        mutates.

        Replaces the reference's one-pod-per-call extender protocol
        limitation (cmd/endpoints.go:28-42, SURVEY.md §2d row 1): the
        device cost is one scan over sum(rows) steps instead of one full
        RPC + solve round-trip per request.

        Synchronous form: dispatch + fetch back to back. The PIPELINED
        serving path splits the two (pack_window_dispatch /
        pack_window_fetch) so the next window's host build and device
        dispatch overlap the previous window's blocking decision pull.
        """
        return self.pack_window_fetch(
            self.pack_window_dispatch(strategy, tensors, requests)
        )

    def pack_window_dispatch(
        self,
        strategy: str,
        tensors,
        requests: Sequence[WindowRequest],
    ) -> "WindowHandle":
        """Build the segmented batch and DISPATCH the device solve without
        blocking on the result. Returns a handle for pack_window_fetch.

        When `tensors` came from build_tensors_pipelined, the threaded
        committed-base availability (still on device, never fetched) is
        recorded as the base for the NEXT pipelined build, and the handle
        notes which earlier windows were still un-fetched — their placements
        are subtracted from this window's host-side base snapshot at fetch
        time, so the host reconstruction sees exactly the availability the
        device saw."""
        if strategy not in BATCHABLE_STRATEGIES:
            raise ValueError(f"strategy {strategy!r} is not batchable")
        if self._closed:
            # Fail fast like ThreadPoolExecutor after shutdown — and BEFORE
            # any device work or pipeline mutation, so a raised dispatch
            # leaves no committed-but-orphaned window behind for a retry to
            # double-commit.
            raise RuntimeError("cannot schedule new futures after shutdown")
        if not requests:
            return WindowHandle(
                strategy=strategy, blob=None, requests=(), flat_rows=[],
                host_avail=None, host_schedulable=None, priors=(), n=0,
            )
        n = tensors.available.shape[0]
        host = _host_view(tensors)
        valid_np = np.asarray(host.valid)

        flat_rows: list[tuple] = []
        commit: list[bool] = []
        reset: list[bool] = []
        cand_rows: list[np.ndarray] = []
        dom_rows: list[np.ndarray] = []
        cand_per_req: list[np.ndarray] = []
        dom_per_req: list[np.ndarray] = []
        # Affinity-domain identity per request, for the multi-device
        # engine's partition plan: requests sharing a domain_node_names
        # tuple share ONE mask build and one partition key; None marks a
        # request whose domain cannot key a partition (precomputed mask
        # override, or the all-valid default that overlaps everything).
        dom_memo: dict[tuple, np.ndarray] = {}
        dom_keys: list[tuple | None] = []
        req_row_ranges: list[tuple[int, int]] = []
        for req in requests:
            cand = self.candidate_mask(tensors, req.driver_candidate_names)
            key: tuple | None = None
            if req.domain_mask is not None:
                dom = np.asarray(req.domain_mask) & valid_np
            elif req.domain_node_names is not None:
                dom_names = req.domain_node_names
                # Domain identity key: a digest ticket (extender
                # _DomainNames / native ingest) keys O(1); small lists
                # keep the content tuple (cross-object partition dedup);
                # a huge plain list keys by object identity — building
                # and hashing a million-name tuple per request was a
                # measured per-window host cost, and identity keying only
                # costs the partition plan on equal-content DISTINCT
                # objects (decisions unaffected — unkeyed windows solve
                # whole).
                digest = getattr(dom_names, "names_digest", None)
                if digest is not None:
                    key = ("digest", digest)
                elif len(dom_names) <= 4096:
                    key = tuple(dom_names)
                else:
                    key = ("id", id(dom_names))
                dom = dom_memo.get(key)
                if dom is None:
                    dom = self._and_valid(
                        self.candidate_mask(tensors, dom_names), valid_np
                    )
                    dom_memo[key] = dom
            else:
                dom = valid_np
            dom_keys.append(key)
            cand_per_req.append(cand)
            dom_per_req.append(dom)
            lo = len(flat_rows)
            for j, row in enumerate(req.rows):
                flat_rows.append(row)
                commit.append(j == len(req.rows) - 1)
                reset.append(j == 0)
                cand_rows.append(cand)
                dom_rows.append(dom)
            req_row_ranges.append((lo, len(flat_rows)))

        b = len(flat_rows)
        # FIFO windows repeat the SAME row objects across requests (request
        # i's hypothetical prefix shares the pending-driver parse of request
        # i+1), so materialize each distinct Resources once.
        arr_memo: dict[int, np.ndarray] = {}

        def as_arr(res) -> np.ndarray:
            a = arr_memo.get(id(res))
            if a is None:
                a = res.as_array()
                arr_memo[id(res)] = a
            return a

        drv_arr = np.stack([as_arr(r[0]) for r in flat_rows])
        exc_arr = np.stack([as_arr(r[1]) for r in flat_rows])
        counts = np.asarray([r[2] for r in flat_rows], np.int32)
        skip_arr = np.asarray([bool(r[3]) for r in flat_rows])
        emax = _bucket(max(int(counts.max()), 1), 8)
        p = self._pipe
        pipelined = p is not None and tensors is p["tensors"]
        if self._pool is not None and pipelined:
            # Multi-device engine: round-robin the window (partitioned by
            # disjoint affinity domains when possible) across the pool.
            return self._dispatch_pooled(
                strategy, tensors, requests,
                host=host,
                drv_arr=drv_arr, exc_arr=exc_arr, counts=counts,
                skip_arr=skip_arr, emax=emax,
                cand_per_req=cand_per_req, dom_per_req=dom_per_req,
                dom_keys=dom_keys, req_row_ranges=req_row_ranges,
            )
        if pipelined and self._prune_eligible(strategy):
            # Two-tier solve (core/prune.py): gather the prefilter's top-K
            # candidate rows out of the resident carry and solve a [K,3]
            # sub-cluster instead of [N,3]; decisions are certified at
            # fetch and escalate to the exact host re-solve on failure.
            dom_shared, dom_key = self._shared_prune_domain(
                requests, dom_keys, dom_per_req
            )
            if dom_shared is not None:
                handle = self._dispatch_pruned(
                    strategy, requests, host=host, p=p, n=n,
                    drv_arr=drv_arr, exc_arr=exc_arr, counts=counts,
                    skip_arr=skip_arr, emax=emax, cand_rows=cand_rows,
                    commit=commit, reset=reset, dom_shared=dom_shared,
                    cand_per_req=cand_per_req, dom_key=dom_key,
                )
                if handle is not None:
                    return handle
        from spark_scheduler_tpu.tracing import tracer

        # Route the segmented window to the Pallas path when the backend
        # compiles Mosaic and the strategy is a plain fill (ops/
        # pallas_window): XLA sorts per segment, Mosaic walks the rows with
        # availability in VMEM. Decisions identical (parity-suite pinned).
        seg_map = None
        from spark_scheduler_tpu.ops.pallas_window import (
            window_pallas_eligible,
        )

        use_pallas = window_pallas_eligible(strategy)
        path = "pallas" if use_pallas else "xla"
        self.window_path_counts[path] = (
            self.window_path_counts.get(path, 0) + 1
        )
        tel = self.telemetry
        compiles_before = tel.compile_count() if tel is not None else None
        seg_bucket = 1
        # Deferred-dispatch lane (sweep arms / fleet stacking): decided up
        # front because a deferring dispatch must NOT pay the h2d shim —
        # the coordinator pays ONE h2d per stacked flush, which is the
        # whole point of fusing the launches.
        lane = self._dispatch_lane
        defer = (
            pipelined
            and not use_pallas
            and lane is not None
            and lane.accepts(self)
        )
        try:
            with tracer().span(
                "solve-dispatch", strategy=strategy, nodes=n,
                window_requests=len(requests), window_rows=b, batched=True,
                path=path,
            ):
                # One simulated h2d/dispatch boundary per DISPATCH, on the
                # dispatcher thread — a fused K-window batch pays this once
                # where K sequential dispatches pay it K times.
                if not defer:
                    _shim("h2d")
                if use_pallas:
                    win, seg_idx, row_idx, s_pad, r_pad = (
                        _build_segmented_window(
                            requests, drv_arr, exc_arr, counts, skip_arr,
                            cand_per_req, dom_per_req,
                        )
                    )
                    seg_map = (seg_idx, row_idx)
                    row_bucket, seg_bucket = r_pad, s_pad
                    blob, avail_after = _window_blob_pallas(
                        tensors, win, fill=strategy,
                        emax=emax, num_zones=self._num_zones_bucket(),
                    )
                else:
                    quantum = self._row_bucket_quantum
                    if defer:
                        # The lane may carry its own row-bucket policy
                        # (fleet lane: 8, like the sweep) — it applies ONLY
                        # to deferred windows, so the serving hot path's
                        # compile-cache coarseness (32) is untouched when
                        # stacking cannot trigger.
                        quantum = (
                            getattr(lane, "row_bucket_quantum", None)
                            or quantum
                        )
                    row_bucket = _bucket(b, quantum)
                    apps = make_app_batch(
                        drv_arr,
                        exc_arr,
                        counts,
                        skippable=skip_arr,
                        # Coarse row bucket (32 on serving paths): window row
                        # counts jitter with load and FIFO depth; each
                        # distinct bucket is a fresh XLA compile, which on a
                        # remote TPU stalls live serving for seconds.
                        pad_to=row_bucket,
                        driver_cand=np.stack(cand_rows),
                        domain=np.stack(dom_rows),
                        commit=commit,
                        reset=reset,
                    )
                    if defer:
                        # Deferred lane (ISSUE 18 sweep / ISSUE 20 fleet):
                        # don't solve yet — park the window with the
                        # coordinator, which stacks it with its peers'
                        # payloads into ONE vmapped dispatch (at the sweep's
                        # lockstep barrier, or the fleet's gather-window
                        # flush). The returned blob/avail are lazy stand-ins
                        # resolved at flush (or singly, on a forced early
                        # fetch / straggler timeout).
                        blob, avail_after = lane.defer_window(
                            self, apps,
                            avail=tensors.available,
                            statics=cluster_statics(tensors),
                            host=host,
                            fill=strategy, emax=emax,
                            num_zones=self._num_zones_bucket(),
                        )
                    elif pipelined:
                        # Double-buffered committed base: the pipeline owns the
                        # availability buffer exclusively (nothing reads it
                        # after this dispatch), so DONATE it — available_after
                        # updates it in place instead of copy-on-write.
                        blob, avail_after = _window_blob_donated(
                            tensors.available, cluster_statics(tensors), apps,
                            fill=strategy, emax=emax,
                            num_zones=self._num_zones_bucket(),
                        )
                    else:
                        blob, avail_after = _window_blob(
                            tensors, apps, fill=strategy, emax=emax,
                            num_zones=self._num_zones_bucket(),
                        )
        except Exception as exc:
            if not classify_slot_failure(exc):
                raise
            # The single device (or its tunnel) failed AT DISPATCH. The
            # pipelined base may be half-mutated (donation): drop it —
            # the next build full-uploads host truth. Per the degraded
            # policy: serve this window via the host greedy fallback, or
            # shed (DegradedUnavailableError), or propagate (no
            # controller wired).
            priors = tuple(p["unfetched"]) if pipelined else ()
            self._pipe = None
            if tel is not None:
                tel.on_pipeline_event("device-failure")
            self._degraded_or_raise(exc)
            return self._make_fallback_handle(
                strategy, requests, host, n, priors
            )

        info = {
            "path": path,
            "nodes": n,
            "rows": b,
            "row_bucket": row_bucket * seg_bucket,
            "emax": emax,
            "state_upload": self.last_state_upload if pipelined else None,
            "compile_cache_hit": (
                tel.compile_count() == compiles_before
                if tel is not None
                else None
            ),
            "dispatch_id": next(self._dispatch_seq),
            # Overwritten by pack_windows_dispatch when this dispatch
            # carries a fused K-window batch.
            "fused_k": 1,
        }
        # The solo batched-admission path (a single-segment pack_window)
        # reads this right after its solve, like pack()'s callers do.
        self.last_solve_info = info
        if tel is not None:
            tel.on_window_dispatch(
                path, nodes=n, rows=b, row_bucket=row_bucket,
                segment_bucket=seg_bucket,
            )
            if use_pallas:
                nbytes = (
                    drv_arr.nbytes + exc_arr.nbytes + counts.nbytes
                    + skip_arr.nbytes
                )
            else:
                # What the XLA window dispatch actually ships: the app
                # batch INCLUDING its [B, N] candidate/domain masks — at
                # 100k nodes the masks dominate the per-window h2d (the
                # O(N) blob the pruned path shrinks to [B, K]).
                nbytes = sum(
                    getattr(f, "nbytes", 0) for f in apps
                )
            tel.on_transfer("h2d", nbytes)
        priors: tuple = ()
        if pipelined:
            priors = tuple(p["unfetched"])
            p["avail"] = avail_after  # the next pipelined build extends this
        handle = WindowHandle(
            strategy=strategy,
            blob=blob,
            requests=tuple(requests),
            flat_rows=flat_rows,
            host_avail=np.array(np.asarray(host.available), dtype=np.int64),
            host_schedulable=np.asarray(host.schedulable),
            priors=priors,
            n=n,
        )
        # Stacked per-row requests for the fetch-side reconstruction: int64
        # so the vectorized subtractions against the int64 base never wrap.
        handle.row_driver_req = drv_arr.astype(np.int64)
        handle.row_exec_req = exc_arr.astype(np.int64)
        handle.row_skippable = skip_arr
        handle.seg_map = seg_map  # pallas path: [S,R] blob -> flat rows
        handle.host_tensors = host  # degraded-fallback re-solve inputs
        handle.info = info
        handle.dispatch_id = info["dispatch_id"]
        handle.dispatched_at = self._clock()
        if pipelined:
            p["unfetched"].append(handle)
            sweep_future = getattr(blob, "sweep_future", None)
            if sweep_future is not None:
                # Deferred sweep window: the coordinator fulfils the blob at
                # its stacked flush (one grouped d2h for all arms) — no fetch
                # thread, no per-arm device_get.
                handle.blob_future = sweep_future
            else:
                # Start the device->host pull NOW on the fetch thread: over a
                # tunneled device the transfer RTT dominates, and starting it
                # at dispatch lets it elapse under the next window's host
                # build.
                handle.blob_future = _shared_fetch_pool().submit(
                    _shimmed_device_get, blob
                )
                self._track(handle.blob_future)
        return handle

    def _make_fallback_handle(
        self, strategy, requests, host, n, priors
    ) -> "WindowHandle":
        """A dispatch-less window handle: no device touched it (degraded
        'greedy' policy with no serving device); pack_window_fetch routes
        it through the host greedy fallback. Keeps the two-phase
        dispatch/fetch API intact so the serving loop and extender need
        no special case."""
        handle = WindowHandle(
            strategy=strategy,
            blob=None,
            requests=tuple(requests),
            flat_rows=[],
            host_avail=np.array(np.asarray(host.available), dtype=np.int64),
            host_schedulable=np.asarray(host.schedulable),
            priors=priors,
            n=n,
        )
        handle.use_fallback = True
        handle.host_tensors = host
        handle.info = {
            "path": "greedy-fallback",
            "nodes": n,
            "rows": sum(len(r.rows) for r in requests),
            "row_bucket": 0,
            "emax": 0,
            "state_upload": None,
            "compile_cache_hit": None,
            "dispatch_id": next(self._dispatch_seq),
            "fused_k": 1,
            "degraded": True,
        }
        handle.dispatch_id = handle.info["dispatch_id"]
        handle.dispatched_at = self._clock()
        self.last_solve_info = handle.info
        self.window_path_counts["greedy-fallback"] = (
            self.window_path_counts.get("greedy-fallback", 0) + 1
        )
        return handle

    def _fetch_fallback(self, handle: "WindowHandle") -> "list[WindowDecision]":
        """Serve a window on the host greedy fallback: the base is the
        same reconstruction every fetch path uses (host view at dispatch
        minus the placements of windows that were still in flight then),
        so degraded decisions see exactly the availability a device solve
        would have."""
        base = self._dense_base(handle)
        if handle.fallback_reason == "prune-escalation":
            # Correctness machinery with a healthy device: the sibling
            # re-solve may ride the scale-tier sharded path. Degraded-mode
            # serving (fallback_reason None) must stay host-side — the
            # device is exactly what failed.
            decisions, placements = self._escalation_decisions(
                handle.strategy, handle.host_tensors, base, handle.requests
            )
        else:
            decisions, placements = self.fallback.window_decisions(
                handle.strategy, handle.host_tensors, base, handle.requests
            )
        handle.placements = placements
        d = self.degraded
        if d is not None and handle.fallback_reason is None:
            # Prune-escalation re-solves are correctness machinery, not
            # degraded-mode serving — they must not flip the degraded
            # controller's decision gauges.
            d.on_fallback_decision(len(decisions))
        p = self._pipe
        if p is not None and handle in p["unfetched"]:
            p["unfetched"].remove(handle)
            p["mirror"] -= placements
            p["pending"] = None  # dense debit: rows unknown to the ledger
        self._prune_mark_unknown()
        self._note_dispatch_complete(handle)
        return decisions

    def _track(self, fut) -> None:
        """Register an in-flight pool future for cancel-on-close()."""
        self._inflight_futures.add(fut)
        fut.add_done_callback(self._inflight_futures.discard)

    def dispatch_occupancy(self) -> float:
        """Busy fraction of the dispatch surface at this instant: pooled =
        fraction of slots with an in-flight solve; single device = 1.0
        when a dispatched window is still un-fetched (a new dispatch
        overlaps it). The overlap-occupancy telemetry sample."""
        if self._pool is not None:
            return self._pool.occupancy()
        p = self._pipe
        return 1.0 if p is not None and p["unfetched"] else 0.0

    def pack_windows_dispatch(
        self,
        strategy: str,
        tensors,
        request_windows: Sequence[Sequence[WindowRequest]],
    ) -> "list[FusedWindowView]":
        """FUSED K-window dispatch on the resident carry state (ROADMAP
        Open item 2): the K serving windows concatenate into ONE segmented
        batch — a window boundary is an ordinary segment boundary, so the
        committed base carries ON DEVICE across the windows exactly as
        `available_after` is threaded between K sequential dispatches
        (ops/batched.py AppBatch window mode; fuse_app_batches pins the
        identity at the ops layer) — and ship as one h2d of K window
        blobs, one jitted dispatch, and one d2h of K placements instead
        of K full device round trips.

        Decisions are byte-identical to dispatching the K windows
        sequentially back-to-back (the fused-vs-sequential equivalence
        suite pins this across churn, K, and domain partitioning); the
        caller's contract is that all K windows were claimed from the
        queue at one instant, before any of them completed — exactly the
        PredicateBatcher's fused claim. On a device pool the concatenated
        batch rides the same partition/overlap machinery as a single
        window (disjoint-domain partitions still solve concurrently).

        Returns one FusedWindowView per window; fetch each IN DISPATCH
        ORDER via pack_window_fetch — the first fetch pays the single
        blocking pull, later views are free."""
        windows = [list(w) for w in request_windows]
        occupancy = self.dispatch_occupancy()
        flat: list[WindowRequest] = [r for w in windows for r in w]
        owner = self.pack_window_dispatch(strategy, tensors, flat)
        k = len(windows)
        if owner.info is not None:
            owner.info["fused_k"] = k
        self._fused_owners.add(owner)
        if self.telemetry is not None:
            self.telemetry.on_fused_dispatch(k, occupancy)
        views: list[FusedWindowView] = []
        lo = 0
        for i, w in enumerate(windows):
            hi = lo + len(w)
            views.append(FusedWindowView(owner, lo, hi, i, k))
            lo = hi
        return views

    def _dispatch_pruned(
        self, strategy, requests, *, host, p, n, drv_arr, exc_arr, counts,
        skip_arr, emax, cand_rows, commit, reset, dom_shared, cand_per_req,
        dom_key=None,
    ) -> "WindowHandle | None":
        """Tier-1 dispatch of the two-tier solve (single-device pipelined
        path): the prefilter's kept rows gather out of the resident device
        carry (a device-side [K] gather — the [N,3] base never moves), the
        statics gather host-side into a small fresh upload, the app batch
        ships [B,K] masks instead of [B,N], and the solve's availability
        DELTA scatters back into the carry additively (padding rows add
        zero). Returns None when the planner declines — the caller falls
        through to the full-tensor paths."""
        from spark_scheduler_tpu.tracing import tracer

        plan = self._plan_prune(
            host, dom_shared, cand_per_req, drv_arr, exc_arr, counts,
            dom_key=dom_key, dom_ref=requests[0].domain_node_names,
        )
        if plan is None:
            return None
        b = len(drv_arr)
        tel = self.telemetry
        compiles_before = tel.compile_count() if tel is not None else None
        keep = plan.keep
        t_gather = self._clock()
        # Statics-gather reuse (ISSUE 12 tentpole (c) + the repeat-window
        # bugfix): an unchanged kept row set (the planner re-served the
        # SAME keep array) whose gathered rows saw no static row-delta
        # re-serves the host gather AND the resident device sub-blob —
        # zero host-array touches, zero re-upload. Entries drop via
        # _apply_static_delta (rows ∩ keep), full uploads, and close().
        ent = self._prune_gather_entry(host, plan)
        statics_np = ent["statics_np"]
        gather_reused = "statics_dev" in ent
        if gather_reused:
            self.prune_stats["gather_reuse"] += 1
        # Per-request candidate gathers deduped by mask identity: serving
        # requests overwhelmingly share ONE candidate ticket, so a
        # 16-wide window pays one [K] gather from the [N] mask instead of
        # B_rows of them (ISSUE 15 tentpole (d)).
        cand_memo: dict[int, np.ndarray] = {}
        cand_subs = []
        for c in cand_rows:
            s = cand_memo.get(id(c))
            if s is None:
                s = c[keep]
                cand_memo[id(c)] = s
            cand_subs.append(s)
        cand_sub = np.stack(cand_subs)
        dom_sub = np.broadcast_to(
            np.asarray(dom_shared)[keep], (b, len(keep))
        )
        try:
            with tracer().span(
                "solve-dispatch", strategy=strategy, nodes=n,
                window_requests=len(requests), window_rows=b, batched=True,
                path="xla-pruned",
            ):
                _shim("h2d")
                if gather_reused:
                    idx_dev = ent["idx_dev"]
                    statics_dev = ent["statics_dev"]
                else:
                    idx_dev = jnp.asarray(keep)
                    statics_dev = tuple(
                        jax.device_put(f) for f in statics_np
                    )
                    ent["idx_dev"] = idx_dev
                    ent["statics_dev"] = statics_dev
                sub_avail = _take_rows(p["avail"], idx_dev)
                zone_base_dev = tuple(
                    jnp.asarray(a) for a in plan.zone_base
                )
                apps = make_app_batch(
                    drv_arr, exc_arr, counts, skippable=skip_arr,
                    pad_to=_bucket(b, self._row_bucket_quantum),
                    driver_cand=cand_sub,
                    domain=dom_sub,
                    commit=commit, reset=reset,
                )
                blob, delta = _window_blob_pruned(
                    sub_avail, statics_dev, apps,
                    zone_base_dev, fill=strategy, emax=emax,
                    num_zones=self._num_zones_bucket(),
                )
                p["avail"] = _add_rows_donated(p["avail"], idx_dev, delta)
        except Exception as exc:
            if not classify_slot_failure(exc):
                raise
            # Same contract as the full-tensor dispatch: the carry may be
            # half-mutated — drop the pipeline and serve per the degraded
            # policy.
            priors = tuple(p["unfetched"])
            self._pipe = None
            if tel is not None:
                tel.on_pipeline_event("device-failure")
            self._degraded_or_raise(exc)
            return self._make_fallback_handle(
                strategy, requests, host, n, priors
            )

        gather_ms = (self._clock() - t_gather) * 1e3
        self.prune_stats["gather_ms"] += gather_ms
        self.window_path_counts["xla-pruned"] = (
            self.window_path_counts.get("xla-pruned", 0) + 1
        )
        row_bucket = _bucket(b, self._row_bucket_quantum)
        info = {
            "path": "xla-pruned",
            "nodes": n,
            "rows": b,
            "row_bucket": row_bucket,
            "emax": emax,
            "state_upload": self.last_state_upload,
            "compile_cache_hit": (
                tel.compile_count() == compiles_before
                if tel is not None
                else None
            ),
            "dispatch_id": next(self._dispatch_seq),
            "fused_k": 1,
            "pruned": True,
            "kept_rows": plan.k_real,
            "candidate_rows": plan.dom_rows,
            "gather_reused": gather_reused,
        }
        self.last_solve_info = info
        self._note_prune_dispatch(plan, b)
        if tel is not None:
            tel.on_window_dispatch(
                "xla-pruned", nodes=n, rows=b, row_bucket=row_bucket,
            )
            tel.on_prune_phases(plan.plan_ms, gather_ms, plan.offset_ms)
            if gather_reused:
                tel.on_prune_gather_reuse()
            # What the pruned dispatch actually ships: gathered statics +
            # app arrays + [B,K] masks + the zone offsets — the O(N) blob
            # (and the [B,N] masks) never leave the host, and a reused
            # gather re-serves the resident statics sub-blob without
            # re-uploading it.
            tel.on_transfer(
                "h2d",
                (
                    0
                    if gather_reused
                    else sum(f.nbytes for f in statics_np) + keep.nbytes
                )
                + drv_arr.nbytes + exc_arr.nbytes + counts.nbytes
                + skip_arr.nbytes + cand_sub.nbytes + dom_sub.nbytes
                + sum(a.nbytes for a in plan.zone_base),
            )
        handle = WindowHandle(
            strategy=strategy,
            blob=blob,
            requests=tuple(requests),
            flat_rows=[],
            host_avail=None,
            host_schedulable=np.asarray(host.schedulable),
            priors=tuple(p["unfetched"]),
            n=n,
        )
        handle.host_avail32 = np.asarray(host.available)
        # Dispatch-time kept-row base, gathered NOW (ISSUE 13): the
        # resident host buffer mutates in place under later builds, so
        # the certificate's base must be captured in [K,3] space here —
        # the fetch path never touches an [N]-wide array. avail_gen +
        # the undo journal cover the rare dense reconstructions
        # (escalation / fallback re-solves).
        handle.base_kept = handle.host_avail32[
            plan.keep[: plan.k_real]
        ].astype(np.int64)
        handle.avail_gen = self._avail_gen
        self._avail_handles.add(handle)
        handle.row_driver_req = drv_arr.astype(np.int64)
        handle.row_exec_req = exc_arr.astype(np.int64)
        handle.row_skippable = skip_arr
        handle.host_tensors = host
        handle.prune = plan
        handle.info = info
        handle.dispatch_id = info["dispatch_id"]
        handle.dispatched_at = self._clock()
        p["unfetched"].append(handle)
        handle.blob_future = _shared_fetch_pool().submit(
            _shimmed_device_get, blob
        )
        self._track(handle.blob_future)
        return handle

    def _fetch_pruned(self, handle: "WindowHandle", blob) -> "list[WindowDecision]":
        """Tier 2 of the two-tier solve: run the soundness certificate
        against the exact host reconstruction and either apply the
        decisions (the normal path) or escalate the window to the exact
        host re-solve.

        O(K + rows) host work since ISSUE 12: the certificate and the
        decision reconstruction both operate on the KEPT rows (base and
        placements gathered to [K,3]); nothing on this path copies,
        compares, or subtracts an [N,3] array — the dense `placements`
        tensor is a lazily-zeroed scatter target for downstream priors."""
        from spark_scheduler_tpu.core.prune import certify_window

        plan = handle.prune
        blob = np.asarray(blob)
        gmap = plan.keep.astype(np.int64)
        keep_real = plan.keep[: plan.k_real]
        drivers_l = blob[:, 0].astype(np.int64)
        admitted = blob[:, 1].astype(bool)
        packed = blob[:, 2].astype(bool)
        execs_l = blob[:, 3:].astype(np.int64)
        drivers = np.where(
            drivers_l >= 0, gmap[np.clip(drivers_l, 0, None)], -1
        )
        execs = np.where(execs_l >= 0, gmap[np.clip(execs_l, 0, None)], -1)
        host_avail32 = handle.host_avail32
        ps = self._prior_sparse(handle)
        if ps is None:
            ok, reason = False, "prior-unknown"
        else:
            prior_rows, prior_deltas = ps
            # Dispatch-time [K,3] base captured at dispatch — the live
            # host buffer has moved on under later resident builds.
            base_kept = handle.base_kept.copy()
            if prior_rows.size:
                loc = np.searchsorted(keep_real, prior_rows)
                locc = np.clip(loc, 0, keep_real.size - 1)
                on_kept = keep_real[locc] == prior_rows
                if on_kept.any():
                    base_kept[locc[on_kept]] -= prior_deltas[on_kept]
            ok, reason = certify_window(
                plan,
                strategy=handle.strategy,
                requests=handle.requests,
                drivers=drivers,
                admitted=admitted,
                packed=packed,
                execs=execs,
                drv64=handle.row_driver_req,
                exc64=handle.row_exec_req,
                base_kept=base_kept.copy(),  # certify threads commits
                host=handle.host_tensors,
                prior_rows=prior_rows,
                prior_deltas=prior_deltas,
            )
        if not ok:
            return self._escalate_pruned(
                handle, self._dense_base(handle), reason
            )
        # Compact reconstruction over the kept rows: base/placements are
        # [Kp,3], decision indices stay LOCAL, and gmap resolves names.
        kp = plan.keep.shape[0]
        base_loc = np.zeros((kp, host_avail32.shape[1]), np.int64)
        base_loc[: plan.k_real] = base_kept
        placements_loc = np.zeros_like(base_loc)
        sched_kept = np.asarray(handle.host_schedulable)[plan.keep]
        decisions = self._reconstruct_requests(
            handle.requests, drivers_l, admitted, packed, execs_l,
            handle.row_driver_req, handle.row_exec_req,
            handle.row_skippable, base_loc, placements_loc,
            sched_kept, row_map=gmap,
        )
        # Sparse committed placements: the dense [N,3] tensor (a 24 MB
        # calloc per window at 1M) is never materialized — later windows
        # subtract priors through (placement_rows, placement_vals), and
        # the rare dense consumers reconstruct on demand (ISSUE 15).
        loc_rows = np.flatnonzero(placements_loc.any(axis=1))
        prows = gmap[loc_rows]  # keep's real part is sorted: prows too
        pvals = placements_loc[loc_rows]
        handle.placement_rows = prows
        handle.placement_vals = pvals
        p = self._pipe
        if p is not None and handle in p["unfetched"]:
            p["unfetched"].remove(handle)
            if prows.size:
                p["mirror"][prows] -= pvals
                if p.get("pending") is not None:
                    # Debited rows differ from the host view until the
                    # reservations write back: the mirror sync must keep
                    # comparing them (the event-fed dirty set's second
                    # feed, next to the resident build's patch rows).
                    p["pending"].append(prows)
        # The placed rows are availability churn the planner can absorb
        # exactly (they are kept rows by construction).
        self._prune_note_rows(prows)
        self._note_dispatch_complete(handle)
        self._device_recovered()
        return decisions

    def _escalation_decisions(self, strategy, host, base, requests):
        """Exact re-solve of a window from host truth — the escalation
        path's solver. With `solver.scale-tier` on, the re-solve runs as
        a NODE-SHARDED device solve over the local mesh (parallel/solve
        node_sharding): the [N] tensors stream across device slots
        instead of a host-Python O(N x rows) walk, which is what keeps
        certificate escalations affordable at the million-node tier.
        Decisions are byte-identical either way — the device kernels ARE
        the greedy oracle's semantics (golden-parity pinned), and the
        escalation-parity test pins this seam. Any device failure falls
        back to the host greedy oracle. Returns (decisions,
        placements[N,3] int64); `base` is never mutated."""
        if self._scale_tier and strategy in BATCHABLE_STRATEGIES:
            try:
                out = self._scale_tier_decisions(
                    strategy, host, base, requests
                )
                self.scale_tier_stats["resolves"] += 1
                return out
            except Exception:
                self.scale_tier_stats["fallbacks"] += 1
        return self.fallback.window_decisions(strategy, host, base, requests)

    def _scale_mesh_for(self, n: int):
        """The ("nodes",) mesh for scale-tier re-solves, over the largest
        power-of-two local device count dividing `n` (row counts are
        power-of-two bucketed, so this is all of them in practice).
        None = one device (unsharded fast path)."""
        devs = jax.devices()
        shards = 1
        while shards * 2 <= len(devs) and n % (shards * 2) == 0:
            shards *= 2
        if shards <= 1:
            return None
        cached = self._scale_mesh
        if cached is not None and cached.devices.size == shards:
            return cached
        from jax.sharding import Mesh

        self._scale_mesh = Mesh(np.asarray(devs[:shards]), ("nodes",))
        return self._scale_mesh

    def _scale_tier_decisions(self, strategy, host, base, requests):
        """One synchronous node-sharded window solve from the exact host
        reconstruction (`base` = host view at dispatch minus in-flight
        priors' placements — precisely what the escalated decisions must
        be computed against)."""
        n = host.available.shape[0]
        valid_np = np.asarray(host.valid)
        flat_rows: list[tuple] = []
        commit: list[bool] = []
        reset: list[bool] = []
        cand_rows: list[np.ndarray] = []
        dom_rows: list[np.ndarray] = []
        for req in requests:
            cand = self.candidate_mask(host, req.driver_candidate_names)
            if req.domain_mask is not None:
                dom = np.asarray(req.domain_mask) & valid_np
            elif req.domain_node_names is not None:
                dom = (
                    self.candidate_mask(host, req.domain_node_names)
                    & valid_np
                )
            else:
                dom = valid_np
            for j, row in enumerate(req.rows):
                flat_rows.append(row)
                commit.append(j == len(req.rows) - 1)
                reset.append(j == 0)
                cand_rows.append(cand)
                dom_rows.append(dom)
        b = len(flat_rows)
        drv_arr = np.stack([r[0].as_array() for r in flat_rows])
        exc_arr = np.stack([r[1].as_array() for r in flat_rows])
        counts = np.asarray([r[2] for r in flat_rows], np.int32)
        skip_arr = np.asarray([bool(r[3]) for r in flat_rows])
        emax = _bucket(max(int(counts.max()), 1), 8)
        apps = make_app_batch(
            drv_arr, exc_arr, counts, skippable=skip_arr,
            pad_to=_bucket(b, 32),
            driver_cand=np.stack(cand_rows), domain=np.stack(dom_rows),
            commit=commit, reset=reset,
        )
        avail32 = np.clip(base, -INT32_INF, INT32_INF).astype(np.int32)
        statics_np = cluster_statics(host)
        mesh = self._scale_mesh_for(n)
        if mesh is not None:
            from spark_scheduler_tpu.parallel.solve import (
                node_sharding,
                shard_apps,
            )

            avail_dev = jax.device_put(
                jnp.asarray(avail32), node_sharding(mesh, 2)
            )
            statics_dev = tuple(
                jax.device_put(
                    jnp.asarray(np.asarray(f)),
                    node_sharding(mesh, np.asarray(f).ndim),
                )
                for f in statics_np
            )
            apps_dev = shard_apps(apps, mesh)
            self.scale_tier_stats["sharded"] += 1
        else:
            avail_dev = jnp.asarray(avail32)
            statics_dev = tuple(jnp.asarray(np.asarray(f)) for f in statics_np)
            apps_dev = apps
        blob, _after = _window_blob_statics(
            avail_dev, statics_dev, apps_dev,
            fill=strategy, emax=emax,
            num_zones=self._num_zones_bucket(),
        )
        blob = np.asarray(jax.device_get(blob))
        drivers = blob[:, 0].astype(np.int64)
        admitted = blob[:, 1].astype(bool)
        packed = blob[:, 2].astype(bool)
        execs = blob[:, 3:].astype(np.int64)
        base_thread = np.asarray(base).astype(np.int64).copy()
        placements = np.zeros_like(base_thread)
        decisions = self._reconstruct_requests(
            requests, drivers, admitted, packed, execs,
            drv_arr.astype(np.int64), exc_arr.astype(np.int64), skip_arr,
            base_thread, placements, np.asarray(host.schedulable),
        )
        return decisions, placements

    def _escalate_pruned(self, handle, base, reason) -> "list[WindowDecision]":
        """Failed certificate: re-solve the whole window from host truth —
        host-side via the greedy oracle (slot-for-slot the kernels'
        semantics — pinned by the golden parity suite), or, under
        `solver.scale-tier`, as the node-sharded device re-solve — so the
        escalated decisions equal the full-tensor device solve's byte for
        byte. The poisoned carry and every window dispatched on it are
        invalidated by _note_prune_escalation."""
        decisions, placements = self._escalation_decisions(
            handle.strategy, handle.host_tensors, base, handle.requests
        )
        handle.placements = placements
        self._note_prune_escalation(handle, reason)
        self._note_dispatch_complete(handle)
        return decisions

    def _dispatch_pooled(
        self, strategy, tensors, requests, *, host, drv_arr, exc_arr,
        counts, skip_arr, emax, cand_per_req, dom_per_req, dom_keys,
        req_row_ranges,
    ) -> "WindowHandle":
        """Multi-device window dispatch (the engine behind `solver.mesh` /
        `solver.device-pool`).

        The window is split into PARTITIONS of requests whose affinity
        domains are provably pairwise-disjoint (instance groups in
        practice: failover.go:276-313 groups nodes by the instance-group
        label, and every request's node selector pins it to one group).
        Requests inside a partition interact only through availability
        rows of their own domain, and zone ranks / priority orders /
        packing efficiencies all derive from domain-masked aggregates
        (ops/sorting.py, ops/efficiency.py), so partitions COMMUTE:
        solving them concurrently — each over a GATHERED sub-cluster of
        just its domain's rows, on its own pool slot — produces decisions
        byte-identical to the serialized window (pinned by
        tests/test_window_serving.py). Windows that do not partition
        (shared or unkeyed domains) run whole on the next slot, which
        still overlaps their d2h decision pull with the next window's
        h2d upload on another device.

        The committed base stays a single logical thread: each
        partition's `available_after` rows scatter back into the
        (donated) global base, and the next pipelined build resolves that
        combine before applying external deltas."""
        from spark_scheduler_tpu.tracing import tracer

        p = self._pipe
        n = tensors.available.shape[0]
        pool = self._pool
        tel = self.telemetry
        compiles_before = tel.compile_count() if tel is not None else None
        num_zones = self._num_zones_bucket()
        # Sized to the device pool (ISSUE 15 satellite): two workers per
        # slot keeps the upload/solve double-buffer engaged at pipeline
        # depth 2; a 1-slot mesh solver gets 2 workers, not 8.
        solve_pool = _shared_solve_pool(min(8, 2 * len(pool.slots)))
        now = self._clock()

        # Quarantine gate: probe any quarantined slot whose interval
        # elapsed; with NO healthy slot left, serve per the degraded
        # policy instead of dispatching into a dead pool.
        if pool.quarantined_slots():
            self.probe_quarantined()
        if not pool.healthy_slots():
            exc = AllSlotsQuarantinedError(
                f"all {len(pool.slots)} device slot(s) quarantined"
            )
            priors = tuple(p["unfetched"])
            self._pipe = None
            if tel is not None:
                tel.on_pipeline_event("device-failure")
            self._degraded_or_raise(exc)
            return self._make_fallback_handle(
                strategy, requests, host, n, priors
            )

        # ---- partition plan: ≥2 distinct domain keys, all keyed, masks
        # pairwise disjoint and non-empty. Plain-device slots only — a
        # sharded (mesh) slot solves the whole window over the node axis.
        plan = None
        if (
            len(pool.slots) > 1
            and not any(s.is_mesh for s in pool.slots)
            and all(k is not None for k in dom_keys)
        ):
            groups: dict[tuple, list[int]] = {}
            for r, key in enumerate(dom_keys):
                groups.setdefault(key, []).append(r)
            if len(groups) > 1:
                masks = [dom_per_req[ids[0]] for ids in groups.values()]
                overlap = np.zeros(n, np.int32)
                for m in masks:
                    overlap += m
                if int(overlap.max()) <= 1 and all(m.any() for m in masks):
                    plan = list(groups.items())

        base = p["avail"]
        base_device = next(iter(base.devices()))
        request_device: list = [None] * len(requests)
        parts: list[_WindowPart] = []
        # Dispatch-time host availability reference (int32, NOT a copy):
        # gathered parts capture their [k,3] base from it below, and the
        # rare dense paths reconstruct via the undo journal — the per
        # -window [N,3] int64 host_avail copy is gone (ISSUE 15).
        havail32 = np.asarray(host.available)

        # Candidate pruning on the pooled engine: each partition (or the
        # whole window when it does not partition, provided its requests
        # share one domain) prunes its own gather to the prefilter's top-K
        # rows — the sub-cluster solve machinery is identical, only the
        # index set shrinks and the committed rows scatter back as deltas.
        try_prune = self._prune_eligible(strategy)
        shared_dom, shared_key = (
            self._shared_prune_domain(requests, dom_keys, dom_per_req)
            if try_prune
            else (None, None)
        )

        def submit_part(slot, req_ids, idx_key, idx):
            row_sel = np.concatenate(
                [np.arange(*req_row_ranges[r]) for r in req_ids]
            )
            drv_g, exc_g = drv_arr[row_sel], exc_arr[row_sel]
            cnt_g, skip_g = counts[row_sel], skip_arr[row_sel]
            prune_plan = None
            if try_prune:
                part_dom = (
                    dom_per_req[req_ids[0]] if idx is not None
                    else shared_dom
                )
                part_key = (
                    dom_keys[req_ids[0]] if idx is not None
                    else shared_key
                )
                if part_dom is not None:
                    prune_plan = self._plan_prune(
                        host, part_dom,
                        [cand_per_req[r] for r in req_ids],
                        drv_g, exc_g, cnt_g,
                        dom_key=part_key,
                        dom_ref=requests[req_ids[0]].domain_node_names,
                    )
                if prune_plan is not None:
                    # The pruned gather REPLACES the domain gather: padded
                    # keep rows, no sub-replica caching (the keep set
                    # changes with every window's availability).
                    idx = prune_plan.keep
                    idx_key = None
                    self._note_prune_dispatch(prune_plan, len(row_sel))
            commit_g: list[bool] = []
            reset_g: list[bool] = []
            cand_g: list[np.ndarray] = []
            dom_g: list[np.ndarray] = []
            for r in req_ids:
                lo, hi = req_row_ranges[r]
                span = hi - lo
                commit_g += [False] * (span - 1) + [True]
                reset_g += [True] + [False] * (span - 1)
                c, d = cand_per_req[r], dom_per_req[r]
                if idx is not None:
                    c, d = c[idx], d[idx]
                cand_g += [c] * span
                dom_g += [d] * span
            b_g = len(row_sel)
            apps = make_app_batch(
                drv_g, exc_g, cnt_g, skippable=skip_g,
                pad_to=_bucket(b_g, 8),
                driver_cand=np.stack(cand_g), domain=np.stack(dom_g),
                commit=commit_g, reset=reset_g,
            )
            # Host-side copy kept on the part for slot-failure re-dispatch
            # (place_apps may shard `apps` onto the dying slot's mesh).
            apps_host = apps
            epoch = self._static_epoch
            # Simulated h2d boundary on the DISPATCHER thread: the pooled
            # engine still ships one window-batch upload per partition
            # submit over the single tunnel link.
            _shim("h2d")
            if idx is None:
                statics = slot.resident_statics(
                    host, epoch, self._clock, tel,
                    journal=self._static_journal,
                )
                # Whole-window base via the slot's delta-synced
                # availability mirror (ISSUE 15): a lagging slot catches
                # up by row-scatter when its missed epochs are journaled.
                sub_avail = self._pool_full_base(p, slot, base, base_device)
            elif prune_plan is not None:
                # Per-partition statics-gather reuse (ISSUE 15 tentpole
                # (b)): the planner's per-domain contexts re-serve the
                # SAME keep array across windows, so the gathered
                # sub-blob caches host-side per keep identity and
                # device-side per (keep, generation) on the slot — a
                # reused plan pays zero host gather and zero re-upload.
                t_gather = self._clock()
                ent = self._prune_gather_entry(host, prune_plan)
                skey = ("prune", id(prune_plan.keep))
                cached = slot.sub_statics.get(skey)
                if cached is not None and cached[0] == ent["gen"]:
                    statics = cached[1]
                    slot.uploads["reuse"] += 1
                    self.prune_stats["gather_reuse"] += 1
                    if tel is not None:
                        tel.on_device_upload(slot.label, "reuse", 0)
                        tel.on_prune_gather_reuse()
                else:
                    statics = tuple(slot._put(f) for f in ent["statics_np"])
                    if len(slot.sub_statics) >= 64:
                        slot.sub_statics.clear()
                    slot.sub_statics[skey] = (ent["gen"], statics)
                    slot.uploads["full"] += 1
                    if tel is not None:
                        tel.on_device_upload(
                            slot.label, "full",
                            sum(f.nbytes for f in ent["statics_np"]),
                        )
                sub_avail = slot.place_avail(_take_rows(base, jnp.asarray(idx)))
                self.prune_stats["gather_ms"] += (
                    self._clock() - t_gather
                ) * 1e3
            else:
                statics = slot.sub_replica(
                    host, idx_key, idx, epoch, self._clock, tel
                )
                sub_avail = slot.place_avail(_take_rows(base, jnp.asarray(idx)))
            apps = slot.place_apps(apps)
            # Donate the sub-base on plain devices: a gathered copy (or a
            # base the combine will replace) that nothing else reads.
            if prune_plan is not None:
                zone_base_dev = tuple(
                    slot._put(a) for a in prune_plan.zone_base
                )

                def fn(avail_, statics_, apps_, *, fill, emax, num_zones,
                       _zb=zone_base_dev):
                    return _window_blob_pruned(
                        avail_, statics_, apps_, _zb,
                        fill=fill, emax=emax, num_zones=num_zones,
                    )
            else:
                fn = (
                    _window_blob_statics if slot.is_mesh
                    else _window_blob_donated
                )
            slot.inflight += 1
            if tel is not None:
                tel.on_device_inflight(slot.label, slot.inflight)
                if slot.last_full_upload:
                    tel.on_device_age(
                        slot.label, max(0.0, now - slot.last_full_upload)
                    )

            from concurrent.futures import Future as _Future

            # The committed sub-base publishes on its OWN future the
            # moment the solve lands — the next window's base combine
            # must never wait out this part's decision-blob d2h (that
            # transfer overlaps the next window's work, exactly like the
            # single-device eager fetch).
            after_fut: _Future = _Future()

            def run():
                t0 = self._clock()
                try:
                    _shim("dispatch")
                    blob, after = fn(
                        sub_avail, statics, apps,
                        fill=strategy, emax=emax, num_zones=num_zones,
                    )
                    after = jax.block_until_ready(after)
                except BaseException as exc:
                    after_fut.set_exception(exc)
                    raise
                after_fut.set_result(after)
                t1 = self._clock()
                _shim("d2h")
                blob_np = np.asarray(jax.device_get(blob))
                t2 = self._clock()
                return {
                    "blob": blob_np,
                    "solve_ms": (t1 - t0) * 1e3,
                    "fetch_ms": (t2 - t1) * 1e3,
                }

            fut = solve_pool.submit(run)
            self._track(fut)

            def _propagate_cancel(f, af=after_fut):
                # A close()-cancelled part never runs; the base future
                # must fail too, not hang a later resolve.
                if f.cancelled() and not af.done():
                    af.cancel()

            fut.add_done_callback(_propagate_cancel)
            for r in req_ids:
                request_device[r] = slot.label
            return _WindowPart(
                future=fut, after_future=after_fut, req_ids=list(req_ids),
                requests=[requests[r] for r in req_ids],
                row_drv=drv_g.astype(np.int64),
                row_exc=exc_g.astype(np.int64),
                row_skip=skip_g, idx=idx, slot=slot, rows=b_g,
                idx_key=idx_key, apps=apps_host, prune=prune_plan,
                # Compact-fetch base: the part's rows' availability at
                # dispatch (the resident buffer mutates afterwards).
                base_kept=(
                    havail32[idx].astype(np.int64)
                    if idx is not None
                    else None
                ),
            )

        note_epoch = None
        try:
            with tracer().span(
                "solve-dispatch", strategy=strategy, nodes=n,
                window_requests=len(requests), window_rows=len(drv_arr),
                batched=True, path="pool",
                partitions=len(plan) if plan else 1,
            ):
                if plan is None:
                    parts.append(
                        submit_part(
                            pool.next_slot(), list(range(len(requests))),
                            None, None,
                        )
                    )
                    head = parts[0]
                    if head.prune is not None:
                        # Pruned whole-window solve: the part returns the
                        # kept rows' availability DELTA — fold it into the
                        # (donated) global base instead of replacing it.
                        p["avail"] = _PendingBase(
                            lambda: _add_rows_donated(
                                base,
                                jnp.asarray(head.idx),
                                jax.device_put(
                                    head.after_future.result(), base_device
                                ),
                            )
                        )
                        # Commits land on kept rows only: journal them so
                        # lagging slot mirrors catch up by scatter.
                        self._avail_journal_note(p, head.idx)
                    else:
                        p["avail"] = _PendingBase(
                            lambda: head.after_future.result()
                        )
                        # Unpruned whole window: the commit rows are
                        # unknowable at dispatch — mirrors crossing this
                        # epoch must full re-ship until the fetch patches
                        # the entry with the exact rows.
                        note_epoch = self._avail_journal_note(p, None)
                else:
                    for key, req_ids in plan:
                        idx = np.flatnonzero(
                            dom_per_req[req_ids[0]]
                        ).astype(np.int32)
                        parts.append(
                            submit_part(pool.next_slot(), req_ids, key, idx)
                        )

                    def combine(parts=parts, base=base):
                        # Scatter every partition's committed sub-base back
                        # into the global base (disjoint rows; the base is
                        # DONATED through the chain — in-place double-buffer).
                        # Waits only on the solves (after_future), never on
                        # the decision-blob transfers. Pruned partitions
                        # return DELTAS over padded keep rows — those fold
                        # in additively (padding adds zero).
                        out = base
                        for part in parts:
                            rows = jax.device_put(
                                part.after_future.result(), base_device
                            )
                            if part.prune is not None:
                                out = _add_rows_donated(
                                    out, jnp.asarray(part.idx), rows
                                )
                            else:
                                out = _scatter_rows_exact_donated(
                                    out, jnp.asarray(part.idx), rows
                                )
                        return out

                    p["avail"] = _PendingBase(combine)
                    # Partition scatters touch exactly the partitions'
                    # gathered rows (pruned parts: their kept rows).
                    self._avail_journal_note(
                        p, np.concatenate([pt.idx for pt in parts])
                    )
        except Exception as exc:
            if not classify_slot_failure(exc):
                raise
            # A device boundary failed ON THE DISPATCHER THREAD (window
            # upload): already-submitted partitions are cancelled, the
            # threaded base is suspect, and the window serves per the
            # degraded policy.
            for part in parts:
                part.future.cancel()
                part.slot.inflight = max(0, part.slot.inflight - 1)
                if tel is not None:
                    tel.on_device_inflight(part.slot.label, part.slot.inflight)
            priors = tuple(p["unfetched"])
            self._pipe = None
            if tel is not None:
                tel.on_pipeline_event("device-failure")
            self._degraded_or_raise(exc)
            return self._make_fallback_handle(
                strategy, requests, host, n, priors
            )

        self.window_path_counts["pool"] = (
            self.window_path_counts.get("pool", 0) + 1
        )
        b = len(drv_arr)
        info = {
            "path": "pool",
            "nodes": n,
            "rows": b,
            "row_bucket": _bucket(b, 8),
            "emax": emax,
            "partitions": len(parts),
            "devices": sorted({pt.slot.label for pt in parts}),
            "state_upload": self.last_state_upload,
            "compile_cache_hit": (
                tel.compile_count() == compiles_before
                if tel is not None
                else None
            ),
            "dispatch_id": next(self._dispatch_seq),
            "fused_k": 1,
        }
        self.last_solve_info = info
        if tel is not None:
            tel.on_window_dispatch(
                "pool", nodes=n, rows=b, row_bucket=_bucket(b, 8),
            )
            tel.on_transfer(
                "h2d",
                drv_arr.nbytes + exc_arr.nbytes + counts.nbytes
                + skip_arr.nbytes,
            )
        handle = WindowHandle(
            strategy=strategy,
            blob=None,
            requests=tuple(requests),
            flat_rows=[],
            # No dense dispatch-time copy (ISSUE 15): gathered parts
            # carry their [k,3] base; the rare dense paths reconstruct
            # via host_avail32 + the availability undo journal.
            host_avail=None,
            host_schedulable=np.asarray(host.schedulable),
            priors=tuple(p["unfetched"]),
            n=n,
        )
        handle.host_avail32 = havail32
        handle.avail_gen = self._avail_gen
        handle.avail_note_epoch = note_epoch
        self._avail_handles.add(handle)
        handle.parts = parts
        handle.request_device = request_device
        handle.host_tensors = host  # slot-failure re-dispatch inputs
        handle.info = info
        handle.dispatch_id = info["dispatch_id"]
        handle.dispatched_at = self._clock()
        p["unfetched"].append(handle)
        return handle

    def pack_window_fetch(self, handle) -> list[WindowDecision]:
        """Block on a dispatched window's decisions and reconstruct the
        per-request outcomes (the second half of pack_window). A
        FusedWindowView fetches its umbrella ONCE (memoized — including a
        failure, which every sub-window of the batch must surface
        identically) and slices its own requests' decisions out."""
        if isinstance(handle, FusedWindowView):
            owner = handle.owner
            res = owner.fused_decisions
            if res is None:
                try:
                    res = ("ok", self.pack_window_fetch(owner))
                except Exception as exc:
                    res = ("err", exc)
                owner.fused_decisions = res
            kind, val = res
            if kind == "err":
                raise val
            return val[handle.lo:handle.hi]
        if handle.released:
            # close()/discard_pipeline() dropped this dispatch's staging
            # buffers; its decisions are gone by design (the caller's
            # epoch machinery re-solves from host truth).
            raise RuntimeError("window dispatch was discarded")
        if not handle.requests:
            return []
        if handle.use_fallback:
            return self._fetch_fallback(handle)
        if handle.parts is not None:
            return self._fetch_pooled(handle)
        from spark_scheduler_tpu.tracing import tracer

        requests, n = handle.requests, handle.n
        with tracer().span(
            "solve", strategy=handle.strategy, nodes=n,
            window_requests=len(requests), batched=True,
        ):
            try:
                if handle.blob_future is not None:
                    blob = handle.blob_future.result()
                else:
                    blob = _shimmed_device_get(handle.blob)
            except Exception as exc:
                # The device base embodies this window's (now unknowable)
                # placements while no reservation was created for them.
                # Drop the whole pipeline: the next build does a full upload
                # from the host view — the durable truth — restoring the
                # lost gangs' capacity. Later in-flight handles still fetch
                # fine (their blobs are independent); they just skip the
                # mirror debit of a dead pipeline.
                self._pipe = None
                if self.telemetry is not None:
                    self.telemetry.on_pipeline_event("fetch-failure")
                if (
                    classify_slot_failure(exc)
                    and handle.host_tensors is not None
                    and self.degraded is not None
                ):
                    # Single device, no survivor: the degraded policy
                    # answers — host greedy re-solve of THIS window (its
                    # decisions are not yet applied anywhere, so the
                    # re-solve is exact), or shed.
                    self._degraded_or_raise(exc)
                    return self._fetch_fallback(handle)
                raise
        if self.telemetry is not None:
            self.telemetry.on_transfer("d2h", getattr(blob, "nbytes", 0))
        if handle.prune is not None:
            return self._fetch_pruned(handle, blob)
        if handle.seg_map is not None:
            # Pallas window path: the device blob is [S, R, 3+emax];
            # flatten the real rows back into flat-row order host-side.
            blob = np.asarray(blob)[handle.seg_map[0], handle.seg_map[1]]
        drivers = blob[:, 0]
        admitted = blob[:, 1].astype(bool)
        packed = blob[:, 2].astype(bool)
        execs = blob[:, 3:]

        base = self._dense_base(handle)
        placements = np.zeros_like(base)
        decisions = self._reconstruct_requests(
            requests, drivers, admitted, packed, execs,
            handle.row_driver_req, handle.row_exec_req,
            handle.row_skippable, base, placements,
            handle.host_schedulable,
        )
        handle.placements = placements
        # Pipeline accounting: the device base now permanently embodies this
        # window's committed gangs; debit them from the mirror so the next
        # build's host-vs-mirror delta ships only EXTERNAL changes. When the
        # host later fails to create one of these reservations, its usage
        # never reaches the host view and the next delta restores the gang's
        # capacity on device automatically (self-correcting drift).
        # The debit is SPARSE (ISSUE 15): the committed rows are read
        # straight off the decision blob — exactly the support of
        # `placements` — so the mirror subtracts O(placed) rows, the
        # pending ledger stays exact (no dense compare next build), and
        # the planner absorbs the rows instead of a snapshot diff.
        prows = self._commit_rows(handle.requests, drivers, admitted, execs)
        handle.placement_rows = prows
        handle.placement_vals = placements[prows]
        p = self._pipe
        if p is not None and handle in p["unfetched"]:
            p["unfetched"].remove(handle)
            if prows.size:
                p["mirror"][prows] -= placements[prows]
                if p.get("pending") is not None:
                    p["pending"].append(prows)
        self._prune_note_rows(prows)
        self._note_dispatch_complete(handle)
        self._device_recovered()
        return decisions

    def _note_dispatch_complete(self, handle) -> None:
        """Amortized round-trip telemetry: dispatch -> decisions-on-host
        wall time divided by the dispatch's fused window count — the
        per-window share of the device round trip a fused batch pays."""
        tel = self.telemetry
        if tel is None or not handle.dispatched_at:
            return
        k = max(1, (handle.info or {}).get("fused_k", 1))
        tel.on_dispatch_complete(
            (self._clock() - handle.dispatched_at) * 1e3 / k, k
        )

    def _fetch_pooled(self, handle: "WindowHandle") -> list[WindowDecision]:
        """Fetch + reconstruct a pooled (possibly partitioned) window.

        Partitions are row-disjoint, so any completion order yields the
        serialized window's exact base. GATHERED parts (domain partitions
        and pruned top-K parts) reconstruct in COMPACT part-local space
        against the [k,3] base captured at dispatch and accumulate their
        committed placements SPARSELY: the mirror debit then scatters
        exactly the union of partition debit rows, the pending ledger
        stays exact, and `mirror_dense_syncs` pins to 0 on the pooled
        path (ISSUE 15 — nothing here touches an [N]-wide array). Only
        an unpartitioned UNPRUNED window (idx None, a single part by
        construction) pays the dense reconstruction; escalations and
        greedy fallbacks materialize the dense base lazily.
        """
        from spark_scheduler_tpu.tracing import tracer

        requests, n = handle.requests, handle.n
        tel = self.telemetry
        results: list = [None] * len(requests)
        # Lenient prior union for the compact reconstructions (the
        # dense-base semantics: an unknown prior contributes nothing).
        lp_rows, lp_vals = self._collect_priors(handle, strict=False)
        sp_rows: list[np.ndarray] = []
        sp_vals: list[np.ndarray] = []
        dense: dict = {"base": None, "placements": None}
        # False once an escalation / greedy-fallback part contributed
        # placements the sparse lists do not cover (those flows kill the
        # pipeline, so the debit never runs — but later windows must then
        # read the DENSE placements, not an incomplete sparse support).
        support_complete = True

        def dense_base() -> np.ndarray:
            # Lazy dense view for the idx-None part / escalations /
            # greedy fallbacks: dispatch-time reconstruction minus the
            # placements already committed by earlier compact parts —
            # which must ALSO back-fill the dense placements tensor
            # (support_complete=False publishes it as the handle's
            # placements; later in-flight windows subtract it as a
            # prior, and missing the earlier partitions' commits would
            # let their re-solves double-book those rows).
            if dense["base"] is None:
                b = self._dense_base(handle)
                pl = np.zeros_like(b)
                for r_, v_ in zip(sp_rows, sp_vals):
                    if r_.size:
                        b[r_] -= v_
                        pl[r_] += v_
                dense["base"] = b
                dense["placements"] = pl
            return dense["base"]

        def commit_sparse(rows, vals) -> None:
            sp_rows.append(rows)
            sp_vals.append(vals)
            if dense["base"] is not None and rows.size:
                dense["base"][rows] -= vals
                dense["placements"][rows] += vals

        strict_ps = None
        strict_known = False
        with tracer().span(
            "solve", strategy=handle.strategy, nodes=n,
            window_requests=len(requests), batched=True,
            path="pool", partitions=len(handle.parts),
        ):
            for part_i, part in enumerate(handle.parts):
                redispatched = False
                try:
                    out = part.future.result()
                except Exception as exc:
                    part.slot.inflight = max(0, part.slot.inflight - 1)
                    if tel is not None:
                        tel.on_device_inflight(
                            part.slot.label, part.slot.inflight
                        )
                    recoverable = (
                        classify_slot_failure(exc)
                        and part.apps is not None
                        and handle.host_tensors is not None
                    )
                    if not recoverable:
                        # Same contract as a single-device fetch failure:
                        # the device base embodies unknowable placements,
                        # so the whole pipeline drops and the next build
                        # re-uploads host truth (the dead combine is
                        # skipped by _resolve_base the same way). Only
                        # the parts not yet processed release their
                        # in-flight slots here — earlier parts already
                        # did.
                        self._pipe = None
                        for pt in handle.parts[part_i + 1:]:
                            pt.slot.inflight = max(0, pt.slot.inflight - 1)
                            if tel is not None:
                                tel.on_device_inflight(
                                    pt.slot.label, pt.slot.inflight
                                )
                        if tel is not None:
                            tel.on_pipeline_event("fetch-failure")
                        raise
                    # SLOT FAILURE RECOVERY: quarantine the slot (its
                    # resident state is unreachable; the threaded base it
                    # fed is poisoned — pipeline rebuilds from host
                    # truth), then re-dispatch this partition on a
                    # surviving slot with byte-identical inputs. With no
                    # survivor, the degraded policy answers (greedy
                    # fallback decisions, or shed).
                    self._pipe = None
                    if tel is not None:
                        tel.on_pipeline_event("fetch-failure")
                    self._quarantine_slot(part.slot, exc)
                    try:
                        recovered = self._redispatch_part(
                            handle, part, dense_base()
                        )
                    except Exception:
                        for pt in handle.parts[part_i + 1:]:
                            pt.slot.inflight = max(0, pt.slot.inflight - 1)
                            if tel is not None:
                                tel.on_device_inflight(
                                    pt.slot.label, pt.slot.inflight
                                )
                        raise
                    if isinstance(recovered, tuple):
                        # Greedy-fallback decisions for this part: apply
                        # its placements to the dense base and move on.
                        decs, ppl = recovered
                        dense_base()
                        dense["base"] -= ppl
                        dense["placements"] += ppl
                        support_complete = False
                        for rid, d in zip(part.req_ids, decs):
                            results[rid] = d
                        continue
                    out = recovered
                    redispatched = True
                blob = out["blob"]
                if not redispatched:
                    part.slot.inflight = max(0, part.slot.inflight - 1)
                if tel is not None:
                    tel.on_transfer("d2h", blob.nbytes)
                    tel.on_device_window(
                        out.get("device", part.slot.label),
                        out["solve_ms"], out["fetch_ms"],
                        inflight=part.slot.inflight,
                    )
                drivers_l = blob[:, 0].astype(np.int64)
                admitted = blob[:, 1].astype(bool)
                packed = blob[:, 2].astype(bool)
                execs_l = blob[:, 3:].astype(np.int64)
                if part.idx is None:
                    # Dense whole-window path: indices are global, the
                    # reconstruction threads the dense base — exactly the
                    # single-device unpruned fetch, with the debit rows
                    # still read sparsely off the blob. (A whole window
                    # has exactly ONE part, so the pre-recon placements
                    # tensor holds no other part's commits and the prows
                    # capture below is this part's alone.)
                    base_d = dense_base()
                    decisions = self._reconstruct_requests(
                        part.requests, drivers_l, admitted, packed,
                        execs_l, part.row_drv, part.row_exc,
                        part.row_skip, base_d, dense["placements"],
                        handle.host_schedulable,
                    )
                    prows = self._commit_rows(
                        part.requests, drivers_l, admitted, execs_l
                    )
                    sp_rows.append(prows)
                    sp_vals.append(dense["placements"][prows].copy())
                    for rid, d in zip(part.req_ids, decisions):
                        results[rid] = d
                    continue
                gmap = part.idx.astype(np.int64)
                if part.prune is not None:
                    # Two-tier certificate, per partition, in compact
                    # space: the [k,3] base captured at dispatch minus
                    # the (strict) prior deltas on this part's kept rows.
                    from spark_scheduler_tpu.core.prune import (
                        certify_window,
                    )

                    if not strict_known:
                        strict_ps = self._prior_sparse(handle)
                        strict_known = True
                    k_real = part.prune.k_real
                    keep_real = part.prune.keep[:k_real]
                    if strict_ps is None:
                        cert_ok, reason, bk = False, "prior-unknown", None
                    else:
                        prior_rows, prior_deltas = strict_ps
                        bk = part.base_kept[:k_real].copy()
                        if prior_rows.size:
                            loc = np.searchsorted(keep_real, prior_rows)
                            locc = np.clip(loc, 0, keep_real.size - 1)
                            on_kept = keep_real[locc] == prior_rows
                            if on_kept.any():
                                bk[locc[on_kept]] -= prior_deltas[on_kept]
                        drv_g = np.where(
                            drivers_l >= 0,
                            gmap[np.clip(drivers_l, 0, None)], -1,
                        )
                        exc_g = np.where(
                            execs_l >= 0,
                            gmap[np.clip(execs_l, 0, None)], -1,
                        )
                        cert_ok, reason = certify_window(
                            part.prune,
                            strategy=handle.strategy,
                            requests=part.requests,
                            drivers=drv_g,
                            admitted=admitted,
                            packed=packed,
                            execs=exc_g,
                            drv64=part.row_drv,
                            exc64=part.row_exc,
                            base_kept=bk.copy(),  # certify threads commits
                            host=handle.host_tensors,
                            prior_rows=prior_rows,
                            prior_deltas=prior_deltas,
                        )
                    if not cert_ok:
                        # Escalate just this partition: re-solve it on the
                        # exact host reconstruction (other partitions are
                        # row-disjoint and stand), then invalidate the
                        # poisoned carry and the windows dispatched on it.
                        decs, ppl = self._escalation_decisions(
                            handle.strategy, handle.host_tensors,
                            dense_base(), part.requests,
                        )
                        dense["base"] -= ppl
                        dense["placements"] += ppl
                        support_complete = False
                        for rid, d in zip(part.req_ids, decs):
                            results[rid] = d
                        self._note_prune_escalation(handle, reason)
                        continue
                    kp = gmap.shape[0]
                    base_loc = np.zeros(
                        (kp, part.base_kept.shape[1]), np.int64
                    )
                    base_loc[:k_real] = bk
                    placements_loc = np.zeros_like(base_loc)
                    sched_loc = np.asarray(handle.host_schedulable)[
                        part.idx
                    ]
                    decisions = self._reconstruct_requests(
                        part.requests, drivers_l, admitted, packed,
                        execs_l, part.row_drv, part.row_exc,
                        part.row_skip, base_loc, placements_loc,
                        sched_loc, row_map=gmap,
                    )
                    loc = np.flatnonzero(placements_loc.any(axis=1))
                    commit_sparse(gmap[loc], placements_loc[loc])
                    for rid, d in zip(part.req_ids, decisions):
                        results[rid] = d
                    continue
                # Unpruned gathered partition: compact reconstruction in
                # the part's local row space (lenient priors — the
                # dense-base semantics).
                bk = part.base_kept.copy()
                if lp_rows.size:
                    loc = np.searchsorted(gmap, lp_rows)
                    locc = np.clip(loc, 0, gmap.size - 1)
                    on = gmap[locc] == lp_rows
                    if on.any():
                        bk[locc[on]] -= lp_vals[on]
                placements_loc = np.zeros_like(bk)
                sched_loc = np.asarray(handle.host_schedulable)[part.idx]
                decisions = self._reconstruct_requests(
                    part.requests, drivers_l, admitted, packed, execs_l,
                    part.row_drv, part.row_exc, part.row_skip,
                    bk, placements_loc, sched_loc, row_map=gmap,
                )
                loc = np.flatnonzero(placements_loc.any(axis=1))
                commit_sparse(gmap[loc], placements_loc[loc])
                for rid, d in zip(part.req_ids, decisions):
                    results[rid] = d
        # Combined sparse support of this window's committed placements.
        if sp_rows:
            allr = np.concatenate(sp_rows)
        else:
            allr = np.empty(0, np.int64)
        if allr.size:
            allv = np.concatenate(sp_vals)
            uniq, inv = np.unique(allr, return_inverse=True)
            vals = np.zeros((uniq.size, allv.shape[1]), np.int64)
            np.add.at(vals, inv, allv)
        else:
            uniq = np.empty(0, np.int64)
            vals = np.empty((0, NUM_DIMS), np.int64)
        if dense["placements"] is not None:
            handle.placements = dense["placements"]
        if support_complete:
            handle.placement_rows = uniq
            handle.placement_vals = vals
        p = self._pipe
        if p is not None and handle in p["unfetched"]:
            p["unfetched"].remove(handle)
            if uniq.size:
                # Sparse pooled debit (ISSUE 15 tentpole (a)): scatter
                # exactly the union of partition debit rows into the
                # mirror and the pending ledger — the next build compares
                # just these instead of a dense [N] sweep.
                p["mirror"][uniq] -= vals
                if p.get("pending") is not None:
                    p["pending"].append(uniq)
                self.build_stats["pooled_debit_rows"] += int(uniq.size)
            self._prune_note_rows(uniq)
            ne = handle.avail_note_epoch
            if (
                ne is not None
                and p.get("avail_journal", {}).get(ne, 0) is None
            ):
                # The dispatch journaled this epoch as unknowable; the
                # fetch just learned the exact commit rows — patch the
                # entry so slot mirrors can catch up across it.
                p["avail_journal"][ne] = uniq
        else:
            # Pipeline died mid-fetch (escalation / slot failure): the
            # next build full-uploads host truth; the planner resyncs.
            self._prune_mark_unknown()
        self._note_dispatch_complete(handle)
        self._device_recovered()
        return results

    def _redispatch_part(self, handle: "WindowHandle", part: "_WindowPart", base):
        """Re-run a failed partition's solve on a SURVIVING slot with
        byte-identical inputs: the availability rows come from the host
        reconstruction (`base` — host view at dispatch minus in-flight
        priors' placements, which is exactly what the dead slot's device
        base embodied; partitions are row-disjoint, so earlier parts'
        commits cannot touch this part's rows), the statics re-upload to
        the survivor, and the app batch is the part's stashed host copy.
        Slot choice never affects decisions (pool invariant), so the
        retried decisions equal what the dead slot would have returned —
        pinned by tests/test_slot_recovery.py.

        Returns a worker-style {"blob", "solve_ms", "fetch_ms", "device"}
        dict, or (decisions, placements) when NO slot survives and the
        degraded policy is greedy. Raises DegradedUnavailableError (shed)
        or AllSlotsQuarantinedError (no controller) otherwise."""
        pool = self._pool
        host = handle.host_tensors
        strategy = handle.strategy
        emax = (handle.info or {}).get("emax")
        # Safe to recompute: a zone-set change implies a node event, which
        # forces a pipeline drain BEFORE any new dispatch — no window can
        # be in flight across it.
        num_zones = self._num_zones_bucket()
        while True:
            self.probe_quarantined()
            healthy = pool.healthy_slots()
            if not healthy:
                exc = AllSlotsQuarantinedError(
                    "no surviving slot for re-dispatch"
                )
                self._degraded_or_raise(exc)
                decs, ppl = self.fallback.window_decisions(
                    strategy, host, base, part.requests
                )
                if self.degraded is not None:
                    self.degraded.on_fallback_decision(len(decs))
                return decs, ppl
            slot = min(healthy, key=lambda s: s.inflight)
            t0 = self._clock()
            try:
                _shim("h2d")
                epoch = self._static_epoch
                if part.idx is None:
                    statics = slot.resident_statics(
                        host, epoch, self._clock, self.telemetry,
                        journal=self._static_journal,
                    )
                    avail_rows = base
                elif part.prune is not None:
                    # Pruned partition: fresh gathered statics on the
                    # survivor (the keep set is per-window, never cached);
                    # the gathered base rows equal what the dead slot's
                    # device gather embodied.
                    statics = tuple(
                        slot._put(f)
                        for f in _gather_statics_host(
                            host, part.idx, part.prune.k_real
                        )
                    )
                    avail_rows = base[part.idx]
                else:
                    statics = slot.sub_replica(
                        host, part.idx_key, part.idx, epoch, self._clock,
                        self.telemetry,
                    )
                    avail_rows = base[part.idx]
                sub_avail = slot._put(
                    np.asarray(avail_rows, dtype=np.int32)
                )
                apps = slot.place_apps(part.apps)
                if part.prune is not None:
                    zone_base_dev = tuple(
                        slot._put(a) for a in part.prune.zone_base
                    )

                    def fn(avail_, statics_, apps_, *, fill, emax,
                           num_zones, _zb=zone_base_dev):
                        return _window_blob_pruned(
                            avail_, statics_, apps_, _zb,
                            fill=fill, emax=emax, num_zones=num_zones,
                        )
                else:
                    fn = (
                        _window_blob_statics if slot.is_mesh
                        else _window_blob_donated
                    )
                _shim("dispatch")
                blob, _after = fn(
                    sub_avail, statics, apps,
                    fill=strategy, emax=emax, num_zones=num_zones,
                )
                t1 = self._clock()
                _shim("d2h")
                blob_np = np.asarray(jax.device_get(blob))
                t2 = self._clock()
            except Exception as exc:
                if classify_slot_failure(exc):
                    # The survivor died too (e.g. the fault is the shared
                    # tunnel, not one device): quarantine it and keep
                    # walking the pool.
                    self._quarantine_slot(slot, exc)
                    continue
                raise
            self.redispatch_count += 1
            self._on_slot_event("redispatch", slot.label)
            if handle.info is not None:
                handle.info["redispatches"] = (
                    handle.info.get("redispatches", 0) + 1
                )
            if handle.request_device is not None:
                for r in part.req_ids:
                    handle.request_device[r] = slot.label
            return {
                "blob": blob_np,
                "solve_ms": (t1 - t0) * 1e3,
                "fetch_ms": (t2 - t1) * 1e3,
                "device": slot.label,
            }

    def _reconstruct_requests(
        self, requests, drivers, admitted, packed, execs,
        drv64, exc64, skip, base, placements, host_schedulable,
        row_map=None,
    ) -> list[WindowDecision]:
        """Host-side reconstruction for per-request packing efficiency: the
        availability each admitted request's final pack saw = the
        host view at dispatch, minus the committed placements of windows
        that were still in flight then (the device had them threaded),
        minus committed placements of earlier segments, minus in-segment
        admitted hypothetical placements. Vectorized over each segment's
        rows (a FIFO window carries O(requests x pending) hypothetical
        rows — per-row Python was the serving loop's hot spot). Mutates
        `base` and `placements` in place (the pooled fetch threads ONE
        base through every partition).

        `row_map` (pruned fetches): decision indices, `base` and
        `placements` live in a COMPACT kept-row space; row_map maps a
        local index to its global registry row for name resolution — the
        whole reconstruction then costs O(K) instead of O(N) (the
        per-request `base.copy()` below was a measured [N,3] cost per
        admitted request at the million-node tier)."""
        if row_map is not None:
            name_of = lambda i: self.registry.name_of(int(row_map[i]))  # noqa: E731
        else:
            name_of = lambda i: self.registry.name_of(int(i))  # noqa: E731
        decisions: list[WindowDecision] = []
        row = 0
        for r, req in enumerate(requests):
            nrows = len(req.rows)
            hyp = np.arange(row, row + nrows - 1)
            real = row + nrows - 1
            row += nrows
            req_admitted = bool(admitted[real])
            earlier_blocked = False
            eff = None
            if nrows > 1:
                adm_h = admitted[hyp]
                earlier_blocked = bool(
                    np.any(~adm_h & ~packed[hyp] & ~skip[hyp])
                )
            if req_admitted:
                seg_avail = base.copy()
                if nrows > 1:
                    dsel = adm_h & (drivers[hyp] >= 0)
                    if dsel.any():
                        np.subtract.at(
                            seg_avail, drivers[hyp][dsel], drv64[hyp][dsel]
                        )
                    e = execs[hyp]
                    esel = adm_h[:, None] & (e >= 0)
                    if esel.any():
                        ri, _si = np.nonzero(esel)
                        np.subtract.at(seg_avail, e[esel], exc64[hyp][ri])
                eff = avg_packing_efficiency_np(
                    host_schedulable,
                    seg_avail,
                    int(drivers[real]),
                    execs[real],
                    drv64[real],
                    exc64[real],
                )
                # Commit this request's placement into the base for the
                # segments after it (mirrors the device-side base thread).
                if drivers[real] >= 0:
                    base[drivers[real]] -= drv64[real]
                    placements[drivers[real]] += drv64[real]
                ev = execs[real]
                ev = ev[ev >= 0]
                if ev.size:
                    np.subtract.at(base, ev, exc64[real])
                    np.add.at(placements, ev, exc64[real])
            exec_idx = [int(x) for x in execs[real] if int(x) >= 0]
            decisions.append(
                WindowDecision(
                    packing=HostPacking(
                        driver_node=(
                            name_of(drivers[real])
                            if drivers[real] >= 0
                            else None
                        ),
                        executor_nodes=[
                            name_of(x) for x in exec_idx
                        ],
                        has_capacity=bool(packed[real]),
                        efficiency_max=float(eff.max) if eff else 0.0,
                        efficiency_cpu=float(eff.cpu) if eff else 0.0,
                        efficiency_memory=float(eff.memory) if eff else 0.0,
                        efficiency_gpu=float(eff.gpu) if eff else 0.0,
                    ),
                    admitted=req_admitted,
                    earlier_blocked=earlier_blocked,
                )
            )
        return decisions

    def subtract_usage(self, tensors, usage: dict[str, Resources]):
        """Subtract per-node usage from availability in-place-equivalent
        (NodeGroupSchedulingMetadata.SubtractUsageIfExists,
        resources.go:128-135); returns new tensors."""
        avail = np.array(tensors.available)
        for name, res in usage.items():
            idx = self.registry.index_of(name)
            if idx is not None and idx < avail.shape[0]:
                avail[idx] = avail[idx] - res.as_array()
        import dataclasses as _dc

        return _dc.replace(tensors, available=avail)
