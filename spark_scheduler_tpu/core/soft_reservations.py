"""In-memory soft reservations for dynamic-allocation extra executors.

Rebuilds internal/cache/softreservations.go:32-254, including the tombstone
`status` map that defeats the race between an executor's death event and a
late scheduling request for the same executor: once an executor name is
marked dead (status[name]=False), AddReservationForPod is a no-op for it.
"""

from __future__ import annotations

import dataclasses
import threading
from types import MappingProxyType
from typing import Mapping

from spark_scheduler_tpu.models.kube import Pod
from spark_scheduler_tpu.models.reservations import Reservation
from spark_scheduler_tpu.models.resources import FrozenResources, Resources
from spark_scheduler_tpu.core.sparkpods import (
    ROLE_DRIVER,
    ROLE_EXECUTOR,
    SPARK_APP_ID_LABEL,
    SPARK_ROLE_LABEL,
    is_spark_scheduler_pod,
)


@dataclasses.dataclass
class SoftReservation:
    reservations: dict[str, Reservation] = dataclasses.field(default_factory=dict)
    status: dict[str, bool] = dataclasses.field(default_factory=dict)

    def copy(self) -> "SoftReservation":
        return SoftReservation(
            reservations={k: v.copy() for k, v in self.reservations.items()},
            status=dict(self.status),
        )


class SoftReservationStore:
    def __init__(self, backend=None):
        self._store: dict[str, SoftReservation] = {}
        self._lock = threading.RLock()
        # Both listener families fire AFTER the store lock is released, so a
        # listener may re-enter store queries without lock-order inversion
        # (listeners take their own locks, then call back into this store).
        # Consequence: deltas can be observed reordered relative to store
        # state; consumers must treat them as commutative increments.
        # Delta listeners: fn(node, resources, sign) on every soft-usage
        # change (+1 reservation added, -1 removed) — the incremental feed
        # for ReservedUsageTracker.
        self._delta_listeners: list = []
        # Membership listeners: fn(app_id, pod_name) fired when an executor
        # gains/loses a soft reservation — the overhead computer's signal
        # that the pod flipped between overhead and reserved.
        self._membership_listeners: list = []
        # Incrementally-maintained per-node usage aggregate (the dense
        # mirror behind used_soft_reservation_resources): mutable running
        # sums + reservation refcounts per node, updated under the lock by
        # the same mutations that feed the delta listeners. The walk over
        # every app x reservation is gone from the query path.
        self._usage_sum: dict[str, Resources] = {}
        self._usage_refs: dict[str, int] = {}
        self._usage_version = 0
        self._usage_view: tuple[int, Mapping[str, FrozenResources]] | None = None
        if backend is not None:
            backend.subscribe("pods", on_delete=self._on_pod_deletion)

    def add_delta_listener(self, fn) -> None:
        self._delta_listeners.append(fn)

    def add_membership_listener(self, fn) -> None:
        self._membership_listeners.append(fn)

    def _notify_delta(self, node: str, resources: Resources, sign: int) -> None:
        for fn in self._delta_listeners:
            fn(node, resources, sign)

    def _notify_membership(self, app_id: str, pod_name: str) -> None:
        for fn in self._membership_listeners:
            fn(app_id, pod_name)

    # -- queries ------------------------------------------------------------

    def get_soft_reservation(self, app_id: str) -> tuple[SoftReservation, bool]:
        with self._lock:
            sr = self._store.get(app_id)
            if sr is None:
                return SoftReservation(), False
            return sr.copy(), True

    def get_all_copy(self) -> dict[str, SoftReservation]:
        with self._lock:
            return {k: v.copy() for k, v in self._store.items()}

    def executor_has_soft_reservation(self, executor: Pod) -> bool:
        return self.get_executor_soft_reservation(executor) is not None

    def get_executor_soft_reservation(self, executor: Pod) -> Reservation | None:
        app_id = executor.labels.get(SPARK_APP_ID_LABEL)
        if app_id is None:
            return None
        with self._lock:
            sr = self._store.get(app_id)
            if sr is not None and executor.name in sr.reservations:
                return sr.reservations[executor.name].copy()
        return None

    def used_soft_reservation_resources(self) -> Mapping[str, Resources]:
        """Per-node usage of all live soft reservations
        (softreservations.go:155-172).

        Returns a MEMOIZED IMMUTABLE view (MappingProxyType of
        FrozenResources) over the incrementally-maintained aggregate —
        the same shape as the reference's fresh dict, but O(1) when
        nothing changed since the last call and never a per-app walk.
        Mutating the view (or a value in it) raises; call `.copy()` on a
        value for a mutable one."""
        with self._lock:
            view = self._usage_view
            if view is not None and view[0] == self._usage_version:
                return view[1]
            frozen = MappingProxyType(
                {
                    node: FrozenResources(
                        res.cpu_milli, res.mem_kib, res.gpu_milli
                    )
                    for node, res in self._usage_sum.items()
                }
            )
            self._usage_view = (self._usage_version, frozen)
            return frozen

    def _usage_apply(self, node: str, resources: Resources, sign: int) -> None:
        """Apply one reservation delta to the dense mirror (caller holds
        the lock). Refcounted so a node whose reservations all vanish
        drops out of the view exactly as the reference's walk would omit
        it — including zero-resource reservations."""
        refs = self._usage_refs.get(node, 0) + sign
        if refs <= 0:
            self._usage_refs.pop(node, None)
            self._usage_sum.pop(node, None)
        else:
            self._usage_refs[node] = refs
            cur = self._usage_sum.get(node)
            if cur is None:
                cur = self._usage_sum[node] = Resources.zero()
            if sign > 0:
                cur.add(resources)
            else:
                cur.sub(resources)
        self._usage_version += 1

    # -- mutations ----------------------------------------------------------

    def create_soft_reservation_if_not_exists(self, app_id: str) -> None:
        with self._lock:
            self._store.setdefault(app_id, SoftReservation())

    def add_reservation_for_pod(
        self, app_id: str, pod_name: str, reservation: Reservation
    ) -> None:
        with self._lock:
            sr = self._store.get(app_id)
            if sr is None:
                raise KeyError(
                    f"cannot add soft reservation: app {app_id} not in store"
                )
            if pod_name in sr.status:
                # tombstoned (dead) or already reserved: no-op
                # (softreservations.go:119-127)
                return
            sr.reservations[pod_name] = reservation
            sr.status[pod_name] = True
            self._usage_apply(reservation.node, reservation.resources, +1)
        self._notify_delta(reservation.node, reservation.resources, +1)
        self._notify_membership(app_id, pod_name)

    def remove_executor_reservation(self, app_id: str, executor_name: str) -> None:
        with self._lock:
            sr = self._store.get(app_id)
            if sr is None:
                return
            removed = sr.reservations.pop(executor_name, None)
            # Always tombstone: remember the death to beat the
            # death-event/schedule-request race (softreservations.go:197-210).
            sr.status[executor_name] = False
            if removed is not None:
                self._usage_apply(removed.node, removed.resources, -1)
        if removed is not None:
            self._notify_delta(removed.node, removed.resources, -1)
            self._notify_membership(app_id, executor_name)

    def remove_driver_reservation(self, app_id: str) -> None:
        with self._lock:
            sr = self._store.pop(app_id, None)
            if sr is not None:
                for r in sr.reservations.values():
                    self._usage_apply(r.node, r.resources, -1)
        if sr is not None:
            for name, r in sr.reservations.items():
                self._notify_delta(r.node, r.resources, -1)
                self._notify_membership(app_id, name)

    def _on_pod_deletion(self, pod: Pod) -> None:
        if not is_spark_scheduler_pod(pod):
            return
        app_id = pod.labels.get(SPARK_APP_ID_LABEL, "")
        role = pod.labels.get(SPARK_ROLE_LABEL)
        if role == ROLE_DRIVER:
            self.remove_driver_reservation(app_id)
        elif role == ROLE_EXECUTOR:
            self.remove_executor_reservation(app_id, pod.name)

    # -- metrics ------------------------------------------------------------

    def application_count(self) -> int:
        with self._lock:
            return len(self._store)

    def active_extra_executor_count(self) -> int:
        with self._lock:
            return sum(len(sr.reservations) for sr in self._store.values())
