"""In-process fake Kubernetes apiserver (list/watch REST subset).

Implements the part of the k8s API machinery the scheduler's ingestion
needs — the same protocol the reference consumes through client-go
informers (cmd/server.go:111-147) and fakes with in-memory clientsets in
tests (extendertest harness):

  - typed collections with a single monotonically increasing
    resourceVersion domain (etcd revision model);
  - `GET <collection>` list responses carrying the collection
    resourceVersion to resume watching from;
  - `GET <collection>?watch=true&resourceVersion=N` chunked streams of
    `{"type": ADDED|MODIFIED|DELETED|ERROR, "object": ...}` JSON lines;
  - bounded event history: a watch from an expired resourceVersion gets a
    `410 Gone` ERROR event, forcing the client to relist (the reflector
    relist path);
  - optimistic-concurrency writes (409 on resourceVersion conflict,
    404/409 on missing/duplicate objects) for tests that drive cluster
    state through the API.

Collections served:

  /api/v1/nodes                                       (cluster-scoped)
  /api/v1/pods                                        (all-namespace list+watch)
  /api/v1/namespaces/{ns}/pods[/{name}]               (namespaced CRUD)
  /apis/sparkscheduler.palantir.com/v1beta2/resourcereservations
  /apis/scaler.palantir.com/v1alpha2/demands          (+ namespaced forms)

Objects are stored as raw k8s-shaped JSON dicts — this *is* the wire
format; decoding to framework models happens client-side (kube_io).
"""

from __future__ import annotations

import collections
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse


class _Collection:
    def __init__(self, resource: str, namespaced: bool, list_kind: str, api_prefix: str):
        self.resource = resource
        self.namespaced = namespaced
        self.list_kind = list_kind
        self.api_prefix = api_prefix  # e.g. "/api/v1" or "/apis/<group>/<version>"
        self.objects: dict[tuple[str, str], dict] = {}

    @property
    def collection_path(self) -> str:
        return f"{self.api_prefix}/{self.resource}"


COLLECTIONS = (
    ("nodes", False, "NodeList", "/api/v1"),
    ("pods", True, "PodList", "/api/v1"),
    (
        "resourcereservations",
        True,
        "ResourceReservationList",
        "/apis/sparkscheduler.palantir.com/v1beta2",
    ),
    ("demands", True, "DemandList", "/apis/scaler.palantir.com/v1alpha2"),
    (
        "customresourcedefinitions",
        False,
        "CustomResourceDefinitionList",
        "/apis/apiextensions.k8s.io/v1",
    ),
)


class ValidationError(Exception):
    """Object rejected by its CRD's openAPI schema (HTTP 422 Invalid)."""


def _meta(obj: dict) -> dict:
    return obj.setdefault("metadata", {})


def _obj_key(obj: dict) -> tuple[str, str]:
    m = _meta(obj)
    return (m.get("namespace", ""), m.get("name", ""))


class FakeKubeAPIServer:
    """Thread-safe fake apiserver. `history_limit` bounds the watch-event
    replay window; a small limit forces 410-Gone relists (the etcd
    compaction analog), which tests use to exercise the reflector's
    resync path."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        history_limit: int = 4096,
        cert_file: str | None = None,
        key_file: str | None = None,
        required_token: str | None = None,
    ):
        """`cert_file`/`key_file` serve HTTPS; `required_token` enforces
        `Authorization: Bearer <token>` on every request (401 otherwise) —
        together they emulate a real apiserver's serviceaccount auth for
        testing the in-cluster reflector path."""
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rv = 0
        self._closed = False
        self._required_token = required_token
        # Fault injection (chaos soak, VERDICT r2 #8). All default off:
        #   chaos_conflict_rate      spurious 409 Conflict on create/update
        #   chaos_drop_rate          connection closed mid-request
        #   terminating_namespaces   creates rejected 403 NamespaceTerminating
        import random as _random

        self.chaos_conflict_rate = 0.0
        self.chaos_drop_rate = 0.0
        self.terminating_namespaces: set[str] = set()
        self._chaos_rng = _random.Random(0)
        self.chaos_injected = {"conflicts": 0, "drops": 0, "ns_terminating": 0}
        self.collections: dict[str, _Collection] = {
            res: _Collection(res, namespaced, kind, prefix)
            for res, namespaced, kind, prefix in COLLECTIONS
        }
        # CRD manifests by plural resource name; writes to a collection with
        # a registered CRD are validated against its openAPI schema the way
        # the real apiserver's structural validation would reject them.
        self._crds: dict[str, dict] = {}
        # (rv, resource, event_type, object-snapshot); single global window,
        # mirroring etcd's single revision domain.
        self._history: collections.deque = collections.deque(maxlen=history_limit)

        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _authorized(self) -> bool:
                if outer._required_token is None:
                    return True
                header = self.headers.get("Authorization", "")
                if header == f"Bearer {outer._required_token}":
                    return True
                FakeKubeAPIServer._write_json(
                    self, 401, outer._status(401, "Unauthorized", "bad bearer token")
                )
                return False

            def do_GET(self):
                if self._authorized():
                    outer._handle_get(self)

            def do_POST(self):
                if self._authorized():
                    outer._handle_write(self, "create")

            def do_PUT(self):
                if self._authorized():
                    outer._handle_write(self, "update")

            def do_DELETE(self):
                if self._authorized():
                    outer._handle_write(self, "delete")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        # Same per-connection TLS machinery as the real servers — one
        # implementation to maintain (server/http.py _maybe_wrap_tls).
        from spark_scheduler_tpu.server.http import _maybe_wrap_tls

        self.tls = _maybe_wrap_tls(self._server, cert_file, key_file)
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def base_url(self) -> str:
        host, port = self._server.server_address[:2]
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="fake-apiserver"
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- CRD registration ----------------------------------------------------

    def register_crd(self, crd: dict) -> None:
        plural = crd["spec"]["names"]["plural"]
        with self._lock:
            self._crds[plural] = crd

    def _validate(self, resource: str, obj: dict) -> None:
        with self._lock:
            crd = self._crds.get(resource)
        # Validation only applies to CRDs whose manifest carries schemas
        # (a minimally-registered CRD behaves like preserveUnknownFields).
        if crd is None or not (crd.get("spec") or {}).get("versions"):
            return
        from spark_scheduler_tpu.models.crds import validate_custom_resource

        errors = validate_custom_resource(crd, obj)
        if errors:
            raise ValidationError("; ".join(errors))

    def _maybe_track_crd(self, resource: str, obj: dict, deleted: bool = False) -> None:
        """CRDs created/updated THROUGH the API register their schemas for
        validation, like the real apiserver establishing a CRD."""
        if resource != "customresourcedefinitions":
            return
        plural = ((obj.get("spec") or {}).get("names") or {}).get("plural")
        if not plural:
            return
        with self._lock:
            if deleted:
                self._crds.pop(plural, None)
            else:
                self._crds[plural] = obj

    # -- state mutation (also the test-driver API) --------------------------

    def create(self, resource: str, obj: dict) -> dict:
        col = self.collections[resource]
        self._validate(resource, obj)
        with self._cond:
            key = _obj_key(obj)
            if key in col.objects:
                raise KeyError(f"{resource} {key} exists")
            self._rv += 1
            _meta(obj)["resourceVersion"] = str(self._rv)
            # Store a snapshot, not the caller's dict: later caller-side
            # mutation must not change apiserver state without a watch event.
            snapshot = json.loads(json.dumps(obj))
            col.objects[key] = snapshot
            self._history.append((self._rv, resource, "ADDED", snapshot))
            self._cond.notify_all()
        self._maybe_track_crd(resource, snapshot)
        return obj

    def create_many(self, resource: str, objs: list[dict]) -> None:
        """Create a batch under ONE lock acquisition — no watcher can
        interleave, so a batch larger than the history window deterministically
        forces the mid-stream 410 path (tests) and bulk seeding is fast."""
        col = self.collections[resource]
        for obj in objs:
            self._validate(resource, obj)
        snapshots = []
        with self._cond:
            for obj in objs:
                key = _obj_key(obj)
                if key in col.objects:
                    raise KeyError(f"{resource} {key} exists")
                self._rv += 1
                _meta(obj)["resourceVersion"] = str(self._rv)
                snapshot = json.loads(json.dumps(obj))
                col.objects[key] = snapshot
                self._history.append((self._rv, resource, "ADDED", snapshot))
                snapshots.append(snapshot)
            self._cond.notify_all()
        for snapshot in snapshots:
            self._maybe_track_crd(resource, snapshot)

    def update(self, resource: str, obj: dict, check_rv: bool = False) -> dict:
        col = self.collections[resource]
        self._validate(resource, obj)
        with self._cond:
            key = _obj_key(obj)
            cur = col.objects.get(key)
            if cur is None:
                raise LookupError(f"{resource} {key} not found")
            if check_rv:
                sent = _meta(obj).get("resourceVersion")
                if sent and sent != _meta(cur).get("resourceVersion"):
                    raise ValueError(
                        f"conflict: rv {sent} != {_meta(cur).get('resourceVersion')}"
                    )
            self._rv += 1
            _meta(obj)["resourceVersion"] = str(self._rv)
            snapshot = json.loads(json.dumps(obj))
            col.objects[key] = snapshot
            self._history.append((self._rv, resource, "MODIFIED", snapshot))
            self._cond.notify_all()
        self._maybe_track_crd(resource, snapshot)
        return obj

    def delete(self, resource: str, namespace: str, name: str) -> None:
        col = self.collections[resource]
        with self._cond:
            cur = col.objects.pop((namespace, name), None)
            if cur is None:
                raise LookupError(f"{resource} {(namespace, name)} not found")
            self._rv += 1
            # DELETED events carry the final object state at the deletion
            # revision (k8s watch semantics).
            final = json.loads(json.dumps(cur))
            _meta(final)["resourceVersion"] = str(self._rv)
            self._history.append((self._rv, resource, "DELETED", final))
            self._cond.notify_all()
        self._maybe_track_crd(resource, cur, deleted=True)

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    # -- request handling ---------------------------------------------------

    def _resolve(self, path: str) -> Optional[tuple[_Collection, Optional[str], Optional[str]]]:
        """path -> (collection, namespace|None, name|None). namespace None
        means the cluster/all-namespace collection path."""
        for col in self.collections.values():
            base = col.collection_path
            if path == base:
                return (col, None, None)
            if path.startswith(base + "/") and not col.namespaced:
                return (col, None, path[len(base) + 1 :])
            if col.namespaced:
                ns_prefix = f"{col.api_prefix}/namespaces/"
                if path.startswith(ns_prefix):
                    rest = path[len(ns_prefix) :].split("/")
                    if len(rest) >= 2 and rest[1] == col.resource:
                        ns = rest[0]
                        name = rest[2] if len(rest) > 2 else None
                        return (col, ns, name)
        return None

    @staticmethod
    def _write_json(handler, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    @staticmethod
    def _status(code: int, reason: str, message: str) -> dict:
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "reason": reason,
            "message": message,
            "code": code,
        }

    def _handle_get(self, handler) -> None:
        parsed = urlparse(handler.path)
        resolved = self._resolve(parsed.path)
        if resolved is None:
            self._write_json(handler, 404, self._status(404, "NotFound", parsed.path))
            return
        col, ns, name = resolved
        if self._drop_connection(handler):
            return
        query = parse_qs(parsed.query)
        if name:
            with self._lock:
                obj = col.objects.get((ns or "", name))
            if obj is None:
                self._write_json(handler, 404, self._status(404, "NotFound", name))
            else:
                self._write_json(handler, 200, obj)
            return
        if query.get("watch", ["false"])[0] in ("true", "1"):
            self._serve_watch(handler, col, ns, query)
            return
        with self._lock:
            items = [
                obj
                for key, obj in sorted(col.objects.items())
                if ns is None or key[0] == ns
            ]
            rv = self._rv
        self._write_json(
            handler,
            200,
            {
                "kind": col.list_kind,
                "apiVersion": "v1",
                "metadata": {"resourceVersion": str(rv)},
                "items": items,
            },
        )

    def _serve_watch(self, handler, col: _Collection, ns: Optional[str], query) -> None:
        """Chunked watch stream. Replays history after `resourceVersion`,
        then blocks for new events until timeoutSeconds / client
        disconnect / server shutdown."""
        try:
            since = int(query.get("resourceVersion", ["0"])[0] or 0)
        except ValueError:
            since = 0
        try:
            timeout_s = float(query.get("timeoutSeconds", ["300"])[0])
        except ValueError:
            timeout_s = 300.0

        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def send_event(event: dict) -> bool:
            data = (json.dumps(event) + "\n").encode()
            try:
                handler.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                handler.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False

        import time as _time

        deadline = _time.monotonic() + timeout_s
        last_sent = since
        with self._cond:
            # Expired-history check: if the requested rv predates the replay
            # window (events pruned past it), the client must relist — the
            # etcd-compaction 410 path reflectors recover from by relisting.
            expired = bool(self._history) and since + 1 < self._history[0][0]
        if expired:
            send_event(
                {
                    "type": "ERROR",
                    "object": self._status(
                        410, "Expired", f"too old resource version: {since}"
                    ),
                }
            )
            self._finish_chunks(handler)
            return

        while True:
            batch: list[tuple[str, dict]] = []
            expired_mid_stream = False
            with self._cond:
                # Events the client hasn't consumed yet can be pruned while
                # the stream is blocked on a slow writer; silently skipping
                # them would let the client diverge forever. Error the watch
                # (410) so it relists — real apiserver behavior.
                if self._history and self._history[0][0] > last_sent + 1:
                    expired_mid_stream = True
                else:
                    for rv, resource, etype, obj in self._history:
                        if rv <= last_sent:
                            continue
                        if resource != col.resource or (
                            ns is not None and _obj_key(obj)[0] != ns
                        ):
                            # Filtered/foreign events still advance the
                            # cursor — otherwise a watcher of a QUIET
                            # collection trips the pruning check as soon as
                            # a busy collection slides the shared history
                            # window past it.
                            last_sent = rv
                            continue
                        batch.append((etype, obj))
                        last_sent = rv
                if not batch and not expired_mid_stream:
                    if self._closed:
                        break
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=min(remaining, 1.0))
                    if self._closed:
                        break
                    continue
            if expired_mid_stream:
                send_event(
                    {
                        "type": "ERROR",
                        "object": self._status(
                            410,
                            "Expired",
                            f"events pruned past resource version {last_sent}",
                        ),
                    }
                )
                break
            ok = True
            for etype, obj in batch:
                if not send_event({"type": etype, "object": obj}):
                    ok = False
                    break
            if not ok:
                return  # client went away; no terminating chunk possible
        self._finish_chunks(handler)

    @staticmethod
    def _finish_chunks(handler) -> None:
        try:
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _drop_connection(self, handler) -> bool:
        """Chaos: abruptly close the socket (client sees a reset/short read)."""
        if self.chaos_drop_rate and self._chaos_rng.random() < self.chaos_drop_rate:
            self.chaos_injected["drops"] += 1
            try:
                handler.connection.close()
            except OSError:
                pass
            handler.close_connection = True
            return True
        return False

    def _handle_write(self, handler, verb: str) -> None:
        parsed = urlparse(handler.path)
        resolved = self._resolve(parsed.path)
        if resolved is None:
            self._write_json(handler, 404, self._status(404, "NotFound", parsed.path))
            return
        col, ns, name = resolved
        if self._drop_connection(handler):
            return
        if (
            verb == "create"
            and ns in self.terminating_namespaces
        ):
            self.chaos_injected["ns_terminating"] += 1
            self._write_json(
                handler,
                403,
                self._status(
                    403,
                    "NamespaceTerminating",
                    f"namespace {ns} is being terminated",
                ),
            )
            return
        if (
            verb in ("create", "update")
            and self.chaos_conflict_rate
            and self._chaos_rng.random() < self.chaos_conflict_rate
        ):
            self.chaos_injected["conflicts"] += 1
            self._write_json(
                handler,
                409,
                self._status(409, "Conflict", "chaos: injected write conflict"),
            )
            return
        body: dict[str, Any] = {}
        length = int(handler.headers.get("Content-Length") or 0)
        if length:
            try:
                body = json.loads(handler.rfile.read(length))
            except json.JSONDecodeError as exc:
                self._write_json(handler, 400, self._status(400, "BadRequest", str(exc)))
                return
        try:
            if verb == "create":
                if ns is not None:
                    _meta(body).setdefault("namespace", ns)
                created = self.create(col.resource, body)
                self._write_json(handler, 201, created)
            elif verb == "update":
                if name and not _meta(body).get("name"):
                    _meta(body)["name"] = name
                if ns is not None:
                    _meta(body).setdefault("namespace", ns)
                updated = self.update(col.resource, body, check_rv=True)
                self._write_json(handler, 200, updated)
            else:  # delete
                if not name:
                    self._write_json(
                        handler, 400, self._status(400, "BadRequest", "delete needs a name")
                    )
                    return
                self.delete(col.resource, ns or "", name)
                self._write_json(handler, 200, self._status(200, "Success", name))
        except ValidationError as exc:
            self._write_json(handler, 422, self._status(422, "Invalid", str(exc)))
        except KeyError as exc:
            self._write_json(handler, 409, self._status(409, "AlreadyExists", str(exc)))
        except LookupError as exc:
            self._write_json(handler, 404, self._status(404, "NotFound", str(exc)))
        except ValueError as exc:
            self._write_json(handler, 409, self._status(409, "Conflict", str(exc)))
