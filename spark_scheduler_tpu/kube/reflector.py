"""List+watch reflectors feeding the ClusterBackend.

The client-go informer slot (SURVEY.md L3): the reference builds a
SharedInformerFactory per API group, lists then watches each resource,
and hands add/update/delete events to components (cmd/server.go:111-147).
`Reflector` reproduces the reflector/informer contract natively:

  1. LIST the collection, remember the collection resourceVersion,
     replace the local state wholesale (firing synthetic deletes for
     objects that vanished during a watch gap);
  2. WATCH from that resourceVersion, applying ADDED/MODIFIED/DELETED
     incrementally and advancing the resume point with every event;
  3. on stream end / network error: re-watch from the last seen
     resourceVersion (resume, no relist);
  4. on `410 Gone` (history expired): relist, then watch again — the
     informer resync path;
  5. `wait_synced` = WaitForCacheSync (cmd/server.go:140-147).

`KubeIngestion` wires node + pod reflectors into a ClusterBackend and
measures the creation→ingestion delay histogram the reference records per
informer add (internal/metrics/informer.go:28-51).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Callable, Optional
from urllib.parse import urlparse

from spark_scheduler_tpu.faults.retry import RetryPolicy
from spark_scheduler_tpu.server.kube_io import node_from_k8s, pod_from_k8s

LIST_TIMEOUT_S = 10.0
WATCH_TIMEOUT_S = 30.0  # per-request watch window; the loop re-arms
RELIST_BACKOFF_S = 0.2
RELIST_BACKOFF_CAP_S = 30.0  # a down apiserver is probed, not hammered
INFORMER_DELAY_METRIC = "foundry.spark.scheduler.informer.delay"


class GoneError(Exception):
    """Watch history expired (HTTP 410 / ERROR event) — relist required."""


class CollectionAbsentError(Exception):
    """404 on a tolerate_absent collection (CRD not installed yet) — sync
    as empty, poll slowly until the CRD appears (demand_informer.go:75-97
    semantics: the Demand CRD belongs to the external autoscaler)."""


class BackendSyncTarget:
    """Applies decoded watch events to a ClusterBackend kind, diffing
    wholesale relists into the add/update/delete stream subscribers expect
    (the informer cache replace semantics)."""

    def __init__(
        self,
        backend,
        kind: str,
        on_add: Optional[Callable[[Any], None]] = None,
    ):
        self._backend = backend
        self._kind = kind
        self._on_add = on_add

    @staticmethod
    def _key(obj) -> tuple[str, str]:
        return (getattr(obj, "namespace", ""), obj.name)

    def replace(self, objects: list) -> None:
        new = {self._key(o): o for o in objects}
        current = {self._key(o): o for o in self._backend.list(self._kind)}
        for key, obj in current.items():
            if key not in new:
                self._backend.delete(self._kind, key[0], key[1])
        for key, obj in new.items():
            if key in current:
                if current[key] != obj:  # dataclass field equality
                    self._backend.update(self._kind, obj)
            else:
                self._backend.create(self._kind, obj)
                if self._on_add:
                    self._on_add(obj)

    def add(self, obj) -> None:
        if self._backend.get(self._kind, *self._key(obj)) is None:
            self._backend.create(self._kind, obj)
            if self._on_add:
                self._on_add(obj)
        else:
            self._backend.update(self._kind, obj)

    def update(self, obj) -> None:
        if self._backend.get(self._kind, *self._key(obj)) is None:
            self.add(obj)
        else:
            self._backend.update(self._kind, obj)

    def delete(self, obj) -> None:
        key = self._key(obj)
        if self._backend.get(self._kind, *key) is not None:
            self._backend.delete(self._kind, key[0], key[1])


class Reflector:
    """One resource's list+watch loop against a k8s-API base URL."""

    def __init__(
        self,
        base_url: str,
        collection_path: str,
        decode: Callable[[dict], Any],
        target: BackendSyncTarget,
        name: str = "",
        watch_timeout_s: float = WATCH_TIMEOUT_S,
        relist_backoff_s: float = RELIST_BACKOFF_S,
        retry_policy: Optional[RetryPolicy] = None,
        ca_file: Optional[str] = None,
        token_file: Optional[str] = None,
        insecure_skip_tls_verify: bool = False,
        tolerate_absent: bool = False,
        absent_poll_s: float = 60.0,
    ):
        """`ca_file`/`token_file` enable in-cluster operation against a real
        apiserver (https://kubernetes.default.svc with the serviceaccount CA
        bundle + bearer token, the client-go rest.InClusterConfig slot).
        The token file is re-read per connection: serviceaccount tokens are
        rotated by the kubelet. https endpoints are ALWAYS verified
        (against `ca_file` or the system CAs) unless
        `insecure_skip_tls_verify` is explicitly set."""
        parsed = urlparse(base_url)
        self._host = parsed.hostname or "127.0.0.1"
        self._tls = parsed.scheme == "https"
        self._port = parsed.port or (443 if self._tls else 80)
        self._ca_file = ca_file
        self._token_file = token_file
        self._insecure = insecure_skip_tls_verify
        self._token_error_logged = False
        self._tolerate_absent = tolerate_absent
        self._absent_poll_s = absent_poll_s
        self._path = collection_path
        self._decode = decode
        self._target = target
        self.name = name or collection_path
        self._watch_timeout_s = watch_timeout_s
        self._relist_backoff_s = relist_backoff_s
        # Relist/rewatch backoff (ISSUE 9 satellite): the old fixed
        # `relist_backoff_s` sleep hammered a down apiserver at 5 Hz
        # forever; now it is only the policy's BASE — consecutive
        # failures back off exponentially (full jitter, capped), and any
        # successful list or watch window resets the ladder.
        # max_attempts=None: a reflector retries forever by contract.
        self._retry_policy = retry_policy or RetryPolicy(
            max_attempts=None,
            base_delay_s=relist_backoff_s,
            multiplier=2.0,
            max_delay_s=RELIST_BACKOFF_CAP_S,
        )
        self._consecutive_failures = 0
        self.backoff_total_s = 0.0  # observable: cumulative backoff slept
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conn_lock = threading.Lock()
        self._watch_conn: Optional[http.client.HTTPConnection] = None
        self.last_resource_version = 0
        self.relist_count = 0  # observable: how many LISTs happened

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"reflector-{self.name}"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._conn_lock:
            if self._watch_conn is not None:
                try:
                    # shutdown() (not just close()) so a reader blocked in
                    # recv() on another thread wakes immediately.
                    sock = self._watch_conn.sock
                    if sock is not None:
                        import socket as _socket

                        sock.shutdown(_socket.SHUT_RDWR)
                    self._watch_conn.close()
                except OSError:
                    pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- backoff ------------------------------------------------------------

    def _note_success(self) -> None:
        self._consecutive_failures = 0

    def _failure_backoff(self) -> float:
        """Delay before the next attempt: exponential in the consecutive-
        failure count, full-jittered, capped. Split from the wait so
        tests pin the ladder without a live socket."""
        delay = self._retry_policy.delay(self._consecutive_failures)
        self._consecutive_failures += 1
        return delay

    def _backoff_wait(self) -> None:
        delay = self._failure_backoff()
        self.backoff_total_s += delay
        self._stop.wait(delay)

    def wait_synced(self, timeout: Optional[float] = None) -> bool:
        return self._synced.wait(timeout)

    # -- the loop -----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._list_and_watch()
            except GoneError:
                continue  # relist immediately
            except CollectionAbsentError:
                # Synced-as-empty; poll slowly for the CRD to appear —
                # never hammer the apiserver over a missing collection.
                self._synced.set()
                self._stop.wait(self._absent_poll_s)
            except Exception:
                if self._stop.is_set():
                    return
                self._backoff_wait()

    def _list_and_watch(self) -> None:
        rv = self._list()
        self.last_resource_version = rv
        self._synced.set()
        self._note_success()
        while not self._stop.is_set():
            try:
                self._watch_once()
                # A watch window that ended cleanly (server closed it, or
                # events flowed) means the apiserver is healthy again.
                self._note_success()
            except (GoneError, CollectionAbsentError):
                raise
            except (OSError, http.client.HTTPException):
                if self._stop.is_set():
                    return
                # Transient stream loss: resume from the last seen rv
                # without relisting (reflector resume semantics), backing
                # off on consecutive failures.
                self._backoff_wait()

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        if not self._tls:
            return http.client.HTTPConnection(self._host, self._port, timeout=timeout)
        import ssl

        # Secure by default: ca_file if given, else the system trust store.
        # Verification is only disabled on an EXPLICIT insecure opt-in — a
        # missing CA must fail loudly, not silently accept any peer (the
        # watch stream is the scheduler's entire world view).
        ctx = ssl.create_default_context(cafile=self._ca_file)
        if self._insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return http.client.HTTPSConnection(
            self._host, self._port, timeout=timeout, context=ctx
        )

    def _headers(self) -> dict[str, str]:
        if not self._token_file:
            return {}
        try:
            with open(self._token_file, "r", encoding="utf-8") as f:
                return {"Authorization": f"Bearer {f.read().strip()}"}
        except OSError as exc:
            # A configured-but-unreadable token means every request will be
            # rejected 401 — say so once instead of silently retrying
            # unauthenticated forever.
            if not self._token_error_logged:
                self._token_error_logged = True
                from spark_scheduler_tpu.tracing import svc1log

                svc1log().warn(
                    "serviceaccount token unreadable; requests go out "
                    "unauthenticated",
                    tokenFile=self._token_file,
                    error=repr(exc),
                    reflector=self.name,
                )
            return {}

    def _list(self) -> int:
        conn = self._connect(LIST_TIMEOUT_S)
        try:
            conn.request("GET", self._path, headers=self._headers())
            resp = conn.getresponse()
            if resp.status == 404 and self._tolerate_absent:
                resp.read()
                self.relist_count += 1
                self._target.replace([])
                raise CollectionAbsentError(self._path)
            if resp.status != 200:
                raise http.client.HTTPException(f"list {self._path}: {resp.status}")
            body = json.loads(resp.read())
        finally:
            conn.close()
        self.relist_count += 1
        items = [self._decode(raw) for raw in body.get("items", [])]
        self._target.replace(items)
        try:
            return int((body.get("metadata") or {}).get("resourceVersion") or 0)
        except ValueError:
            return 0

    def _watch_once(self) -> None:
        conn = self._connect(self._watch_timeout_s + LIST_TIMEOUT_S)
        with self._conn_lock:
            self._watch_conn = conn
        try:
            conn.request(
                "GET",
                f"{self._path}?watch=true"
                f"&resourceVersion={self.last_resource_version}"
                f"&timeoutSeconds={self._watch_timeout_s:g}",
                headers=self._headers(),
            )
            resp = conn.getresponse()
            if resp.status == 410:
                raise GoneError()
            if resp.status == 404 and self._tolerate_absent:
                raise CollectionAbsentError(self._path)
            if resp.status != 200:
                raise http.client.HTTPException(f"watch {self._path}: {resp.status}")
            while not self._stop.is_set():
                line = resp.readline()
                if not line:
                    return  # server closed the window; re-arm
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                self._apply(event)
        finally:
            with self._conn_lock:
                self._watch_conn = None
            conn.close()

    def _apply(self, event: dict) -> None:
        etype = event.get("type")
        raw = event.get("object") or {}
        if etype == "ERROR":
            if raw.get("code") == 410:
                raise GoneError()
            raise http.client.HTTPException(f"watch error: {raw}")
        if etype == "BOOKMARK":
            rv = (raw.get("metadata") or {}).get("resourceVersion")
            if rv:
                self.last_resource_version = int(rv)
            return
        obj = self._decode(raw)
        if etype == "ADDED":
            self._target.add(obj)
        elif etype == "MODIFIED":
            self._target.update(obj)
        elif etype == "DELETED":
            self._target.delete(obj)
        rv = (raw.get("metadata") or {}).get("resourceVersion")
        if rv:
            try:
                self.last_resource_version = int(rv)
            except ValueError:
                pass


class KubeIngestion:
    """Node + pod reflectors for a scheduler app — the informer-factory
    slot of initServer (cmd/server.go:111-147). Also records the
    pod-creation→ingestion delay histogram (internal/metrics/informer.go:
    28-51: time from pod creationTimestamp to the informer add callback)."""

    def __init__(
        self,
        backend,
        base_url: str,
        metrics=None,
        clock: Callable[[], float] = time.time,
        watch_timeout_s: float = WATCH_TIMEOUT_S,
        ca_file: Optional[str] = None,
        token_file: Optional[str] = None,
        insecure_skip_tls_verify: bool = False,
    ):
        def on_pod_add(pod) -> None:
            if metrics is not None and pod.creation_timestamp:
                delay = max(0.0, clock() - pod.creation_timestamp)
                metrics.histogram(INFORMER_DELAY_METRIC, kind="pods").update(delay)

        self.node_reflector = Reflector(
            base_url,
            "/api/v1/nodes",
            node_from_k8s,
            BackendSyncTarget(backend, "nodes"),
            name="nodes",
            watch_timeout_s=watch_timeout_s,
            ca_file=ca_file,
            token_file=token_file,
            insecure_skip_tls_verify=insecure_skip_tls_verify,
        )
        self.pod_reflector = Reflector(
            base_url,
            "/api/v1/pods",
            pod_from_k8s,
            BackendSyncTarget(backend, "pods", on_add=on_pod_add),
            name="pods",
            watch_timeout_s=watch_timeout_s,
            ca_file=ca_file,
            token_file=token_file,
            insecure_skip_tls_verify=insecure_skip_tls_verify,
        )
        self.reflectors = [self.node_reflector, self.pod_reflector]

    def start(self) -> None:
        for r in self.reflectors:
            r.start()

    def stop(self) -> None:
        for r in self.reflectors:
            r.stop()

    def wait_synced(self, timeout: Optional[float] = None) -> bool:
        """WaitForCacheSync: all reflectors listed at least once
        (cmd/server.go:140-147)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in self.reflectors:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not r.wait_synced(remaining):
                return False
        return True


SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_config() -> tuple[str, str, str]:
    """(base_url, ca_file, token_file) from the pod's serviceaccount — the
    rest.InClusterConfig slot (cmd/server.go:57-75 "in-cluster")."""
    import os

    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"  # IPv6 literal needs brackets in a URL
    return (
        f"https://{host}:{port}",
        f"{SERVICEACCOUNT_DIR}/ca.crt",
        f"{SERVICEACCOUNT_DIR}/token",
    )


def in_cluster_ingestion(backend, metrics=None, **kw) -> KubeIngestion:
    """KubeIngestion configured from the pod's serviceaccount."""
    base_url, ca_file, token_file = in_cluster_config()
    return KubeIngestion(
        backend,
        base_url,
        metrics=metrics,
        ca_file=ca_file,
        token_file=token_file,
        **kw,
    )
