"""KubeBackend — the ClusterBackend over a real Kubernetes apiserver.

The last process boundary of the reference (SURVEY.md §3.5): reservation
and demand writes go to the apiserver as CRs through rate-limited typed
clients (cmd/server.go:57-96 builds clientsets with config QPS/Burst;
internal/cache/async.go drives them), while the local store remains the
read path and watch streams carry external changes back.

This backend extends InMemoryBackend so every component (caches, managers,
reconciler) works unchanged:

  - pods / nodes: read-only, fed by KubeIngestion reflectors (the app
    wires those when kube-api-url is set);
  - resourcereservations / demands: create/update/delete are forwarded to
    the apiserver REST API FIRST (409 -> ConflictError/AlreadyExistsError,
    404 -> NotFoundError — the AsyncClient's retry ladder maps 1:1), then
    applied locally with the apiserver-assigned resourceVersion;
  - their watch streams echo back: external ADDs/DELETEs apply fully
    (failover: a new leader sees the previous leader's reservations),
    while MODIFIEDs of locally-owned objects only fast-forward the
    resourceVersion — the cache owner is the sole writer
    (internal/cache/cache.go:106-133 tryOverrideResourceVersion);
  - the CRD registry reads/writes apiextensions
    customresourcedefinitions through the same API;
  - every REST call passes a token-bucket rate limiter (config QPS/Burst,
    config/config.go:30-31).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Optional
from urllib.parse import urlparse

from spark_scheduler_tpu.store.backend import (
    AlreadyExistsError,
    BackendError,
    ConflictError,
    InMemoryBackend,
    NamespaceTerminatingError,
    NotFoundError,
)

RR_PATH = "/apis/sparkscheduler.palantir.com/v1beta2"
DEMAND_PATH = "/apis/scaler.palantir.com/v1alpha2"
CRD_PATH = "/apis/apiextensions.k8s.io/v1/customresourcedefinitions"


class TokenBucket:
    """Client-side rate limiter (client-go flowcontrol slot; config
    qps/burst, config/config.go:30-31). acquire() blocks until a token is
    available."""

    def __init__(self, qps: float, burst: int, clock=time.monotonic, sleep=time.sleep):
        self.qps = float(qps)
        self.burst = max(1, int(burst))
        self._tokens = float(self.burst)
        self._last = clock()
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()

    def acquire(self) -> None:
        while True:
            with self._lock:
                now = self._clock()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            self._sleep(wait)


class RestClient:
    """Minimal JSON REST client with TLS/bearer auth + rate limiting."""

    def __init__(
        self,
        base_url: str,
        rate_limiter: Optional[TokenBucket] = None,
        ca_file: Optional[str] = None,
        token_file: Optional[str] = None,
        insecure_skip_tls_verify: bool = False,
        timeout_s: float = 10.0,
        metrics=None,
    ):
        # `metrics`: a MetricRegistry; each request records a latency
        # histogram tagged by verb + outcome family (the reference's
        # client-latency adapters, internal/metrics/metrics.go:253-297).
        self._metrics = metrics
        parsed = urlparse(base_url)
        self._host = parsed.hostname or "127.0.0.1"
        self._tls = parsed.scheme == "https"
        self._port = parsed.port or (443 if self._tls else 80)
        self._ca_file = ca_file
        self._token_file = token_file
        self._insecure = insecure_skip_tls_verify
        self._timeout_s = timeout_s
        self._limiter = rate_limiter

    def _connect(self) -> http.client.HTTPConnection:
        if not self._tls:
            return http.client.HTTPConnection(
                self._host, self._port, timeout=self._timeout_s
            )
        import ssl

        ctx = ssl.create_default_context(cafile=self._ca_file)
        if self._insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return http.client.HTTPSConnection(
            self._host, self._port, timeout=self._timeout_s, context=ctx
        )

    def _headers(self) -> dict[str, str]:
        headers = {"Content-Type": "application/json"}
        if self._token_file:
            try:
                with open(self._token_file, "r", encoding="utf-8") as f:
                    headers["Authorization"] = f"Bearer {f.read().strip()}"
            except OSError:
                pass
        return headers

    def request(self, method: str, path: str, payload: Optional[dict] = None):
        if self._limiter is not None:
            self._limiter.acquire()
        start = time.perf_counter()
        status = 0
        conn = self._connect()
        try:
            conn.request(
                method,
                path,
                body=json.dumps(payload).encode() if payload is not None else None,
                headers=self._headers(),
            )
            resp = conn.getresponse()
            raw = resp.read()
            body = json.loads(raw) if raw else {}
            status = resp.status
            return status, body
        finally:
            conn.close()
            if self._metrics is not None:
                self._metrics.histogram(
                    "foundry.spark.scheduler.kubeclient.request",
                    verb=method,
                    family=f"{status // 100}xx" if status else "error",
                ).update(time.perf_counter() - start)


def _raise_for_status(status: int, body: dict, context: str) -> None:
    reason = body.get("reason", "")
    message = body.get("message", "")
    if status == 409 and reason == "AlreadyExists":
        raise AlreadyExistsError(f"{context}: {message}")
    if status == 409:
        raise ConflictError(f"{context}: {message}")
    if status == 403 and reason == "NamespaceTerminating":
        # Not retryable: the async write-back drops the create outright
        # (async.go:88-96).
        raise NamespaceTerminatingError(f"{context}: {message}")
    if status == 404:
        raise NotFoundError(f"{context}: {message}")
    if status == 422:
        raise BackendError(f"{context}: invalid: {message}")
    if status >= 400:
        raise BackendError(f"{context}: HTTP {status}: {message}")


class KubeBackend(InMemoryBackend):
    def __init__(
        self,
        base_url: str,
        qps: float = 5.0,
        burst: int = 10,
        ca_file: Optional[str] = None,
        token_file: Optional[str] = None,
        insecure_skip_tls_verify: bool = False,
        watch: bool = True,
        watch_timeout_s: float = 30.0,
        metrics=None,
    ):
        super().__init__()
        self._crds.clear()  # the apiserver's CRD registry is authoritative
        self.rate_limiter = TokenBucket(qps, burst)
        self._rest = RestClient(
            base_url,
            rate_limiter=self.rate_limiter,
            ca_file=ca_file,
            token_file=token_file,
            insecure_skip_tls_verify=insecure_skip_tls_verify,
            metrics=metrics,
        )
        self._base_url = base_url
        self._watch = watch
        self._watch_timeout_s = watch_timeout_s
        self._ca_file = ca_file
        self._token_file = token_file
        self._insecure = insecure_skip_tls_verify
        self._reflectors: list = []

    # -- codecs / paths ------------------------------------------------------

    @staticmethod
    def _codec(kind: str):
        from spark_scheduler_tpu.server import conversion as C

        if kind == "resourcereservations":
            return C.rr_v1beta2_to_wire, C.rr_v1beta2_from_wire
        if kind == "demands":
            return C.demand_v1alpha2_to_wire, C.demand_v1alpha2_from_wire
        raise KeyError(kind)

    @staticmethod
    def _collection(kind: str, namespace: Optional[str] = None) -> str:
        base = RR_PATH if kind == "resourcereservations" else DEMAND_PATH
        if namespace:
            return f"{base}/namespaces/{namespace}/{kind}"
        return f"{base}/{kind}"

    def _is_remote(self, kind: str) -> bool:
        return kind in ("resourcereservations", "demands")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Initial REST list of reservations + demands into the local store
        (cache fill, cache/resourcereservations.go:53-60), then watch from
        the listed resourceVersion."""
        from spark_scheduler_tpu.kube.reflector import BackendSyncTarget, Reflector

        for kind in ("resourcereservations", "demands"):
            _, from_wire = self._codec(kind)
            target = _ExternalTarget(self, kind)
            reflector = Reflector(
                self._base_url,
                self._collection(kind),
                from_wire,
                target,
                name=kind,
                watch_timeout_s=self._watch_timeout_s,
                ca_file=self._ca_file,
                token_file=self._token_file,
                insecure_skip_tls_verify=self._insecure,
                # A 404'd collection means its CRD isn't installed yet:
                # sync as empty and poll slowly. The reservation CRD is
                # created by the scheduler itself moments later
                # (ensure_resource_reservations_crd), so it re-polls fast;
                # the Demand CRD belongs to the external autoscaler and
                # may never appear (demand_informer.go:75-97).
                tolerate_absent=True,
                absent_poll_s=5.0 if kind == "resourcereservations" else 60.0,
            )
            if self._watch:
                reflector.start()
                self._reflectors.append(reflector)
            else:
                reflector._list()  # one synchronous fill

    def wait_synced(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in self._reflectors:
            remaining = (
                None if deadline is None else max(0.0, deadline - time.monotonic())
            )
            if not r.wait_synced(remaining):
                return False
        return True

    def stop(self) -> None:
        for r in self._reflectors:
            r.stop()
        self._reflectors.clear()

    # -- remote-kind CRUD ----------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        if not self._is_remote(kind):
            return super().create(kind, obj)
        to_wire, from_wire = self._codec(kind)
        ns = getattr(obj, "namespace", "")
        status, body = self._rest.request(
            "POST", self._collection(kind, ns), to_wire(obj)
        )
        _raise_for_status(status, body, f"create {kind} {ns}/{obj.name}")
        created = from_wire(body)
        self._apply_external(kind, created, replace=True)
        return created

    def update(self, kind: str, obj: Any) -> Any:
        if not self._is_remote(kind):
            return super().update(kind, obj)
        to_wire, from_wire = self._codec(kind)
        ns = getattr(obj, "namespace", "")
        status, body = self._rest.request(
            "PUT", f"{self._collection(kind, ns)}/{obj.name}", to_wire(obj)
        )
        _raise_for_status(status, body, f"update {kind} {ns}/{obj.name}")
        updated = from_wire(body)
        self._apply_external(kind, updated, replace=True)
        return updated

    def delete(self, kind: str, namespace: str, name: str) -> None:
        if not self._is_remote(kind):
            return super().delete(kind, namespace, name)
        status, body = self._rest.request(
            "DELETE", f"{self._collection(kind, namespace)}/{name}"
        )
        _raise_for_status(status, body, f"delete {kind} {namespace}/{name}")
        self._remove_local(kind, namespace, name)

    def get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        """Remote kinds re-read through the API — the AsyncClient's conflict
        fast-forward (async.go:111-120) needs the apiserver's CURRENT
        resourceVersion, not the possibly-stale local echo."""
        if not self._is_remote(kind):
            return super().get(kind, namespace, name)
        _, from_wire = self._codec(kind)
        try:
            status, body = self._rest.request(
                "GET", f"{self._collection(kind, namespace)}/{name}"
            )
        except OSError:
            return super().get(kind, namespace, name)
        if status == 404:
            return None
        if status != 200:
            return super().get(kind, namespace, name)
        obj = from_wire(body)
        self._apply_external(kind, obj)  # rv fast-forward if already known
        return obj

    # -- external application (watch echoes, failover fills) -----------------
    # Objects carry APISERVER resourceVersions; the base class's local rv
    # counter never touches remote kinds (it would clobber the apiserver rv
    # and wedge every subsequent PUT in 409s), so application manipulates
    # the store directly and fires handlers itself.

    def _apply_external(self, kind: str, obj: Any, replace: bool = False) -> None:
        """Unknown keys apply fully (fires add handlers — failover
        discovery); known keys fast-forward the resourceVersion, replacing
        the object (firing update) only for our own write's response
        (`replace=True`) — the cache owner is the sole writer, external
        MODIFIEDs only bump the rv (cache.go:106-133)."""
        key = (getattr(obj, "namespace", ""), obj.name)
        event = None
        with self._lock:
            cur = self._objects[kind].get(key)
            if cur is None:
                self._objects[kind][key] = obj
                event = ("add", (obj,))
            elif replace:
                self._objects[kind][key] = obj
                event = ("update", (cur, obj))
            else:
                obj_rv = getattr(obj, "resource_version", 0)
                if getattr(cur, "resource_version", 0) < obj_rv:
                    cur.resource_version = obj_rv
        if event is not None:
            self._fire(kind, event[0], *event[1])

    def _remove_local(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            cur = self._objects[kind].pop((namespace, name), None)
        if cur is not None:
            self._fire(kind, "delete", cur)

    # -- CRD registry over apiextensions ------------------------------------

    def register_crd(self, name: str, definition: Optional[dict] = None) -> None:
        if definition is None:
            from spark_scheduler_tpu.models.crds import (
                DEMAND_CRD_NAME,
                RESERVATION_CRD_NAME,
                demand_crd,
                resource_reservation_crd,
            )

            if name == DEMAND_CRD_NAME:
                definition = demand_crd()
            elif name == RESERVATION_CRD_NAME:
                definition = resource_reservation_crd()
            else:
                definition = {
                    "apiVersion": "apiextensions.k8s.io/v1",
                    "kind": "CustomResourceDefinition",
                    "metadata": {"name": name},
                    "spec": {"names": {"plural": name.split(".")[0]}},
                }
        status, body = self._rest.request("POST", CRD_PATH, definition)
        if status == 409:
            # create-or-upgrade (crd/utils.go:98-133): fetch current rv, PUT
            get_status, current = self._rest.request("GET", f"{CRD_PATH}/{name}")
            if get_status == 200:
                definition = dict(definition)
                definition.setdefault("metadata", {})
                definition["metadata"] = {
                    **definition["metadata"],
                    "resourceVersion": current.get("metadata", {}).get(
                        "resourceVersion", ""
                    ),
                }
                status, body = self._rest.request(
                    "PUT", f"{CRD_PATH}/{name}", definition
                )
        if status not in (200, 201):
            raise BackendError(f"register CRD {name}: HTTP {status}")
        super().register_crd(name, definition)

    def crd_exists(self, name: str) -> bool:
        # Positive results are cached locally: SafeDemandCache gates every
        # demand operation on this, and a REST GET per gate would burn the
        # rate budget (established CRDs effectively never disappear; the
        # reference also only checks until first establishment).
        if super().crd_exists(name):
            return True
        try:
            status, _ = self._rest.request("GET", f"{CRD_PATH}/{name}")
        except OSError:
            return False
        if status == 200:
            with self._lock:
                self._crds.add(name)
            return True
        return False

    def unregister_crd(self, name: str) -> None:
        self._rest.request("DELETE", f"{CRD_PATH}/{name}")
        super().unregister_crd(name)


class _ExternalTarget:
    """Reflector sync target for apiserver-owned reservation/demand echoes
    (the informer hookup of the write-through cache, cache.go:95-133)."""

    def __init__(self, backend: KubeBackend, kind: str):
        self._backend = backend
        self._kind = kind

    def replace(self, objects: list) -> None:
        known = {
            (getattr(o, "namespace", ""), o.name): o
            for o in self._backend.list(self._kind)
        }
        fresh = {(getattr(o, "namespace", ""), o.name): o for o in objects}
        for key, obj in fresh.items():
            self._backend._apply_external(self._kind, obj)
        for key, obj in known.items():
            if key not in fresh:
                self._backend._remove_local(self._kind, key[0], key[1])

    def add(self, obj) -> None:
        self._backend._apply_external(self._kind, obj)

    def update(self, obj) -> None:
        self._backend._apply_external(self._kind, obj)

    def delete(self, obj) -> None:
        self._backend._remove_local(
            self._kind, getattr(obj, "namespace", ""), obj.name
        )
