"""Kubernetes list+watch ingestion (the reference's informer slot, L3).

The reference learns cluster state from apiserver watch streams through
client-go SharedInformerFactory (cmd/server.go:111-147) and ships fake
clientsets for tests. This package provides the same boundary natively:

  - `FakeKubeAPIServer` — an in-process HTTP server speaking the k8s REST
    list/watch subset (resourceVersions, chunked watch streams, 410 Gone),
    the stand-in for both the real apiserver and client-go's fakes;
  - `Reflector` — list-then-watch with resourceVersion resume, relist on
    410/expiry, per-kind decode;
  - `KubeIngestion` — reflectors for nodes + pods applying into a
    `ClusterBackend`, with informer-delay measurement
    (internal/metrics/informer.go:28-51).
"""

from spark_scheduler_tpu.kube.apiserver import FakeKubeAPIServer
from spark_scheduler_tpu.kube.backend import KubeBackend, RestClient, TokenBucket
from spark_scheduler_tpu.kube.reflector import (
    BackendSyncTarget,
    KubeIngestion,
    Reflector,
    in_cluster_ingestion,
)

__all__ = [
    "FakeKubeAPIServer",
    "KubeBackend",
    "RestClient",
    "TokenBucket",
    "Reflector",
    "BackendSyncTarget",
    "KubeIngestion",
    "in_cluster_ingestion",
]
