"""Tagged metric registry.

The reference leans on palantir/pkg/metrics — a tagged registry of
counters/gauges/histograms flushed every 30s (metrics/metrics.go:79,
SURVEY.md §2c). This is the dependency-free equivalent: thread-safe
counters, gauges, and reservoir histograms keyed by (name, sorted tags),
with a `snapshot()` the reporters/tests consume and `emit()` for JSON-line
output.
"""

from __future__ import annotations

import json
import threading
import time


def _key(name: str, tags: dict[str, str] | None) -> tuple:
    return (name, tuple(sorted((tags or {}).items())))


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, delta: int = 1) -> None:
        with self._lock:
            self.value += delta


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value


class Histogram:
    """Bounded-reservoir histogram with exact small-sample percentiles."""

    __slots__ = ("_values", "_count", "_max", "_min", "_sum", "_lock", "_cap")

    def __init__(self, cap: int = 1024):
        self._values: list[float] = []
        self._count = 0
        # None sentinels: min AND max are exact over ALL samples (a 0.0
        # max initializer would fabricate a never-observed 0.0 for
        # all-negative series).
        self._max: float | None = None
        self._min: float | None = None
        self._sum = 0.0
        self._lock = threading.Lock()
        self._cap = cap

    def update(self, value: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += value
            self._max = value if self._max is None else max(self._max, value)
            self._min = value if self._min is None else min(self._min, value)
            if len(self._values) < self._cap:
                self._values.append(value)
            else:  # reservoir replacement, deterministic stride
                self._values[self._count % self._cap] = value

    def stats(self) -> dict:
        with self._lock:
            vs = sorted(self._values)
            n = len(vs)
            return {
                "count": self._count,
                "max": self._max if self._max is not None else 0.0,
                "min": self._min if self._min is not None else 0.0,
                # Exact running sum — exposition must emit THIS, not
                # mean*count: the reconstruction can shrink by an ulp
                # between scrapes and Prometheus reads any _sum decrease
                # as a counter reset (spurious rate() spikes).
                "sum": self._sum,
                "mean": self._sum / self._count if self._count else 0.0,
                "p50": vs[min(int(0.5 * n), n - 1)] if n else 0.0,
                "p95": vs[min(int(0.95 * n), n - 1)] if n else 0.0,
                "p99": vs[min(int(0.99 * n), n - 1)] if n else 0.0,
            }


class MetricRegistry:
    def __init__(self):
        self._metrics: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def _get(self, kind, name: str, tags: dict[str, str] | None):
        k = _key(name, tags)
        with self._lock:
            m = self._metrics.get(k)
            if m is None:
                m = kind()
                self._metrics[k] = m
            elif not isinstance(m, kind):
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def counter(self, name: str, **tags: str) -> Counter:
        return self._get(Counter, name, tags)

    def gauge(self, name: str, **tags: str) -> Gauge:
        return self._get(Gauge, name, tags)

    def histogram(self, name: str, **tags: str) -> Histogram:
        return self._get(Histogram, name, tags)

    def unregister(self, name: str, **tags: str) -> None:
        """Drop a metric series (stale-tag cleanup, usage.go:96-113)."""
        with self._lock:
            self._metrics.pop(_key(name, tags), None)

    def snapshot(self) -> dict:
        """{name: [{tags, kind, value|stats}]} — test/reporting view."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict[str, list] = {}
        for (name, tags), m in items:
            if isinstance(m, Counter):
                entry = {"tags": dict(tags), "kind": "counter", "value": m.value}
            elif isinstance(m, Gauge):
                entry = {"tags": dict(tags), "kind": "gauge", "value": m.value}
            else:
                entry = {"tags": dict(tags), "kind": "histogram", **m.stats()}
            out.setdefault(name, []).append(entry)
        return out

    def emit(self, stream, now: float | None = None) -> None:
        """One JSON line per metric series (the 30s metric flush analog).
        Every line of a flush carries the same `time` so readers can group
        lines into ticks and plot the values as a time series."""
        if now is None:
            now = time.time()
        for name, entries in self.snapshot().items():
            for e in entries:
                stream.write(json.dumps({"time": now, "metric": name, **e}) + "\n")
