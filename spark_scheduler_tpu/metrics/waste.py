"""Scheduling-waste reporter (metrics/waste.go:36-298).

Tracks, per pending pod, its failed scheduling attempts and the create /
fulfill times of its Demand, and on successful scheduling attributes the
elapsed "waste" to a phase:

  before-demand-creation                 first failure -> demand created
  after-demand-fulfilled                 demand fulfilled -> scheduled
  after-demand-fulfilled-no-failures     fulfilled -> scheduled, no failures after
  after-demand-fulfilled-since-last-failure  last failure after fulfillment -> scheduled
  total-time-no-demand                   first failure -> scheduled (no demand)

Histograms are tagged by waste type + instance group; entries for pods that
terminated are dropped after the 6h cleanup tick (waste.go:279-298).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from spark_scheduler_tpu.core.sparkpods import find_instance_group
from spark_scheduler_tpu.metrics.registry import MetricRegistry

SCHEDULING_WASTE = "foundry.spark.scheduler.scheduling.waste"
SCHEDULING_WASTE_PER_GROUP = "foundry.spark.scheduler.scheduling.wasteperinstancegroup"

CLEANUP_AFTER_S = 6 * 3600.0  # waste.go cleanup cadence


@dataclasses.dataclass
class _PodInfo:
    first_failure: float | None = None
    last_failure: float | None = None
    demand_created: float | None = None
    demand_fulfilled: float | None = None
    done: float | None = None  # scheduled or deleted


class WasteReporter:
    def __init__(
        self,
        registry: MetricRegistry | None = None,
        instance_group_label: str = "instance-group",
        clock=time.time,
    ):
        self.registry = registry or MetricRegistry()
        self._label = instance_group_label
        self._clock = clock
        self._pods: dict[tuple[str, str], _PodInfo] = {}
        # Request threads, informer callbacks, and the reporter tick all
        # touch _pods.
        self._lock = threading.Lock()

    # --------------------------------------------------------------- inputs

    def mark_failed_scheduling_attempt(self, pod, outcome: str) -> None:
        now = self._clock()
        with self._lock:
            info = self._pods.setdefault(pod.key, _PodInfo())
            if info.first_failure is None:
                info.first_failure = now
            info.last_failure = now

    def on_demand_created(self, pod_key) -> None:
        now = self._clock()
        with self._lock:
            self._pods.setdefault(pod_key, _PodInfo()).demand_created = now

    def on_demand_fulfilled(self, pod_key) -> None:
        now = self._clock()
        with self._lock:
            self._pods.setdefault(pod_key, _PodInfo()).demand_fulfilled = now

    def on_pod_scheduled(self, pod) -> None:
        now = self._clock()
        with self._lock:
            info = self._pods.get(pod.key)
            if info is None or info.done is not None:
                return
            # Claim the transition under the lock so a concurrently
            # delivered duplicate update can't double-count the histograms.
            info = dataclasses.replace(info, done=now)
            self._pods[pod.key] = info
        group = find_instance_group(pod, self._label) or ""

        def mark(waste_type: str, duration: float) -> None:
            if duration <= 0:
                return
            self.registry.histogram(SCHEDULING_WASTE, wastetype=waste_type).update(
                duration
            )
            self.registry.histogram(
                SCHEDULING_WASTE_PER_GROUP,
                wastetype=waste_type,
                **{"instance-group": group},
            ).update(duration)

        if info.demand_created is None:
            if info.first_failure is not None:
                mark("total-time-no-demand", now - info.first_failure)
            return
        if info.first_failure is not None:
            mark("before-demand-creation", info.demand_created - info.first_failure)
        if info.demand_fulfilled is not None:
            mark("after-demand-fulfilled", now - info.demand_fulfilled)
            if info.last_failure is None or info.last_failure <= info.demand_fulfilled:
                mark("after-demand-fulfilled-no-failures", now - info.demand_fulfilled)
            else:
                mark(
                    "after-demand-fulfilled-since-last-failure",
                    now - info.last_failure,
                )

    def on_pod_deleted(self, pod) -> None:
        now = self._clock()
        with self._lock:
            info = self._pods.get(pod.key)
            if info is not None and info.done is None:
                info.done = now

    # -------------------------------------------------------------- cleanup

    def cleanup(self) -> None:
        """Drop entries finished more than 6h ago (waste.go:279-298)."""
        now = self._clock()
        with self._lock:
            stale = [
                k
                for k, v in self._pods.items()
                if v.done is not None and now - v.done >= CLEANUP_AFTER_S
            ]
            for k in stale:
                del self._pods[k]
