"""Observability: tagged metric registry, scheduler metric families, periodic
reporters, and the scheduling-waste tracker (SURVEY.md §2a metrics rows;
internal/metrics/* in the reference).
"""

from spark_scheduler_tpu.metrics.registry import MetricRegistry
from spark_scheduler_tpu.metrics.scheduler_metrics import SchedulerMetrics
from spark_scheduler_tpu.metrics.reporters import (
    CacheReporter,
    QueueReporter,
    ReporterRunner,
    SoftReservationReporter,
    UsageReporter,
)
from spark_scheduler_tpu.metrics.waste import WasteReporter

__all__ = [
    "MetricRegistry",
    "SchedulerMetrics",
    "UsageReporter",
    "CacheReporter",
    "QueueReporter",
    "SoftReservationReporter",
    "WasteReporter",
    "ReporterRunner",
]
