"""Periodic metric reporters (cmd/server.go:239-247 starts five of these in
the reference; each ticks every 30s, metrics.go:79).

Every reporter exposes `report_once()` so tests and the serving layer can
drive it synchronously; `ReporterRunner` threads them on a cadence.
"""

from __future__ import annotations

import threading
import time

from spark_scheduler_tpu.core.sparkpods import (
    ROLE_DRIVER,
    SPARK_ROLE_LABEL,
    find_instance_group,
)
from spark_scheduler_tpu.metrics.registry import MetricRegistry

USAGE_CPU = "foundry.spark.scheduler.resource.usage.cpu"
USAGE_MEMORY = "foundry.spark.scheduler.resource.usage.memory"
USAGE_GPU = "foundry.spark.scheduler.resource.usage.nvidia.com/gpu"
LIFECYCLE_MAX = "foundry.spark.scheduler.pod.lifecycle.max"
LIFECYCLE_MIN = "foundry.spark.scheduler.pod.lifecycle.min"
LIFECYCLE_P99 = "foundry.spark.scheduler.pod.lifecycle.p99"
LIFECYCLE_P95 = "foundry.spark.scheduler.pod.lifecycle.p95"
LIFECYCLE_P50 = "foundry.spark.scheduler.pod.lifecycle.p50"
LIFECYCLE_COUNT = "foundry.spark.scheduler.pod.lifecycle.count"
CACHED_OBJECTS = "foundry.spark.scheduler.cache.objects.count"
INFLIGHT_REQUESTS = "foundry.spark.scheduler.cache.inflight.count"
UNEXPLAINED_DIFFERENCE = "foundry.spark.scheduler.cache.unexplained.difference"
# Size skew explained by informer propagation delay (cache.go:33-34).
INFORMER_DELAY_BUFFER = 5
SOFT_RESERVATION_COUNT = "foundry.spark.scheduler.softreservation.count"
SOFT_RESERVATION_EXECUTORS = "foundry.spark.scheduler.softreservation.executorcount"

TICK_INTERVAL_S = 30.0  # metrics.go:79
STUCK_POD_THRESHOLD_S = 12 * 3600.0  # queue.go:32


class UsageReporter:
    """Reserved CPU/mem/GPU gauges per node, with stale-series cleanup
    (metrics/usage.go:33-114)."""

    def __init__(self, registry: MetricRegistry, reservation_manager):
        self._registry = registry
        self._rrm = reservation_manager
        self._seen_nodes: set[str] = set()

    def report_once(self) -> None:
        usage = self._rrm.get_reserved_resources()  # {node: Resources}
        live = set(usage)
        for node in self._seen_nodes - live:  # stale tag cleanup
            for name in (USAGE_CPU, USAGE_MEMORY, USAGE_GPU):
                self._registry.unregister(name, nodename=node)
        self._seen_nodes = live
        for node, res in usage.items():
            self._registry.gauge(USAGE_CPU, nodename=node).set(res.cpu_milli)
            self._registry.gauge(USAGE_MEMORY, nodename=node).set(res.mem_kib)
            self._registry.gauge(USAGE_GPU, nodename=node).set(res.gpu_milli)


class CacheReporter:
    """Cache depth vs backend truth + inflight write-queue lengths + drift
    detection (metrics/cache.go:32-141).

    With a `backend`, each tick also lists the backend's truth for every
    cached type and compares: a size skew larger than the inflight write
    queue plus the informer-delay buffer is UNEXPLAINED — exactly the
    failure mode the async fire-and-forget write path can produce — and is
    surfaced as a warning (with per-object only-in-cache / only-in-backend
    lines, cache.go:115-127) plus the `cache.unexplained.difference`
    gauge."""

    def __init__(
        self,
        registry: MetricRegistry,
        caches: dict[str, object],
        backend=None,
    ):
        self._registry = registry
        self._caches = caches  # {object_type: WriteThroughCache}
        self._backend = backend

    def report_once(self) -> None:
        from spark_scheduler_tpu.tracing import svc1log

        for obj_type, cache in self._caches.items():
            crd_gate = getattr(cache, "crd_exists", None)
            if crd_gate is not None and not crd_gate():
                continue  # SafeDemandCache before the CRD appears
            cached = cache.list()
            self._registry.gauge(
                CACHED_OBJECTS, objectType=obj_type, source="cache"
            ).set(len(cached))
            total_queue = 0
            for i, depth in enumerate(cache.queue_lengths()):
                total_queue += depth
                self._registry.gauge(
                    INFLIGHT_REQUESTS, objectType=obj_type, queueIndex=str(i)
                ).set(depth)
            if self._backend is None:
                continue
            try:
                actual = self._backend.list(obj_type)
            except Exception as exc:
                svc1log().error(
                    "failed to list backend objects for cache drift check",
                    objectType=obj_type, error=repr(exc),
                )
                continue
            self._registry.gauge(
                CACHED_OBJECTS, objectType=obj_type, source="lister"
            ).set(len(actual))
            skew = abs(len(actual) - len(cached))
            unexplained = skew > total_queue + INFORMER_DELAY_BUFFER
            self._registry.gauge(
                UNEXPLAINED_DIFFERENCE, objectType=obj_type
            ).set(skew if unexplained else 0)
            if unexplained:
                svc1log().warn(
                    "found unexplained cache size difference",
                    objectType=obj_type,
                    cached=len(cached), actual=len(actual),
                    inflight=total_queue,
                )
                def _key(obj):
                    return getattr(obj, "uid", None) or (
                        getattr(obj, "namespace", ""), getattr(obj, "name", "")
                    )

                cached_keys = {_key(o) for o in cached}
                actual_keys = {_key(o) for o in actual}
                for obj in actual:
                    if _key(obj) not in cached_keys:
                        svc1log().warn(
                            "object only exists in backend",
                            objectType=obj_type,
                            name=getattr(obj, "name", ""),
                            namespace=getattr(obj, "namespace", ""),
                        )
                for obj in cached:
                    if _key(obj) not in actual_keys:
                        svc1log().warn(
                            "object only exists in cache",
                            objectType=obj_type,
                            name=getattr(obj, "name", ""),
                            namespace=getattr(obj, "namespace", ""),
                        )


class SoftReservationReporter:
    """Soft-reservation app/executor counts (metrics/softreservations.go:31-104)."""

    def __init__(self, registry: MetricRegistry, soft_store):
        self._registry = registry
        self._store = soft_store

    def report_once(self) -> None:
        self._registry.gauge(SOFT_RESERVATION_COUNT).set(
            self._store.application_count()
        )
        self._registry.gauge(SOFT_RESERVATION_EXECUTORS).set(
            self._store.active_extra_executor_count()
        )


class QueueReporter:
    """Pod lifecycle age histograms per (instance group, role, lifecycle)
    with stuck-pod detection (metrics/queue.go:31-192). Lifecycle of a pod:
    queued (not scheduled), initializing (scheduled, not ready), ready."""

    def __init__(
        self,
        registry: MetricRegistry,
        backend,
        instance_group_label: str,
        clock=time.time,
        on_stuck_pod=None,
    ):
        self._registry = registry
        self._backend = backend
        self._label = instance_group_label
        self._clock = clock
        self._on_stuck_pod = on_stuck_pod
        self._seen_tags: set[tuple[str, str, str]] = set()

    @staticmethod
    def lifecycle_of(pod) -> str:
        if not pod.is_scheduled():
            return "queued"
        ready = pod.get_condition("Ready")
        if ready is None or not ready.status:
            return "initializing"
        return "ready"

    def report_once(self) -> None:
        now = self._clock()
        buckets: dict[tuple[str, str, str], list[float]] = {}
        for pod in self._backend.list_pods():
            role = pod.labels.get(SPARK_ROLE_LABEL)
            if role is None or pod.is_terminated():
                continue
            lifecycle = self.lifecycle_of(pod)
            if lifecycle == "ready":
                continue  # only pending/initializing ages are interesting
            group = find_instance_group(pod, self._label) or ""
            age = max(now - pod.creation_timestamp, 0.0)
            buckets.setdefault((group, role, lifecycle), []).append(age)
            if age > STUCK_POD_THRESHOLD_S and self._on_stuck_pod is not None:
                self._on_stuck_pod(pod, lifecycle, age)
        # Stale-series cleanup: a bucket that emptied must not keep reporting
        # its last values (same pattern as UsageReporter).
        for group, role, lifecycle in self._seen_tags - set(buckets):
            tags = {
                "instance-group": group,
                "sparkrole": role,
                "lifecycle": lifecycle,
            }
            for name in (
                LIFECYCLE_COUNT, LIFECYCLE_MAX, LIFECYCLE_MIN,
                LIFECYCLE_P99, LIFECYCLE_P95, LIFECYCLE_P50,
            ):
                self._registry.unregister(name, **tags)
        self._seen_tags = set(buckets)
        for (group, role, lifecycle), ages in buckets.items():
            ages.sort()
            tags = {
                "instance-group": group,
                "sparkrole": role,
                "lifecycle": lifecycle,
            }
            n = len(ages)
            self._registry.gauge(LIFECYCLE_COUNT, **tags).set(n)
            self._registry.gauge(LIFECYCLE_MAX, **tags).set(ages[-1])
            self._registry.gauge(LIFECYCLE_MIN, **tags).set(ages[0])
            self._registry.gauge(LIFECYCLE_P99, **tags).set(
                ages[min(int(0.99 * n), n - 1)]
            )
            self._registry.gauge(LIFECYCLE_P95, **tags).set(
                ages[min(int(0.95 * n), n - 1)]
            )
            self._registry.gauge(LIFECYCLE_P50, **tags).set(
                ages[min(int(0.5 * n), n - 1)]
            )


class ReporterRunner:
    """Threads a set of reporters on the 30s tick (cmd/server.go:243-247)."""

    def __init__(self, reporters, interval_s: float = TICK_INTERVAL_S, on_error=None):
        self._reporters = list(reporters)
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._on_error = on_error

    def report_once(self) -> None:
        # Per-reporter isolation: one failing reporter must not starve the
        # others (and must not silently kill the tick loop).
        for r in self._reporters:
            try:
                r.report_once()
            except Exception as exc:
                if self._on_error is not None:
                    self._on_error(r, exc)
                else:
                    import sys
                    import traceback

                    print(
                        f"metric reporter {type(r).__name__} failed: {exc}",
                        file=sys.stderr,
                    )
                    traceback.print_exc()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._interval):
                self.report_once()

        self._thread = threading.Thread(target=loop, daemon=True, name="metric-reporter")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
