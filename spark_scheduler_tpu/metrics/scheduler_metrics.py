"""Scheduler metric families.

The hook surface the extender and demand manager call, backed by the tagged
registry. Metric names mirror the reference's `foundry.spark.scheduler.*`
series (internal/metrics/metrics.go:29-59) so existing dashboards carry
over; tag names likewise (metrics.go:61-76).
"""

from __future__ import annotations

import threading
import time

from spark_scheduler_tpu.core.sparkpods import find_instance_group
from spark_scheduler_tpu.metrics.registry import MetricRegistry

REQUEST_COUNTER = "foundry.spark.scheduler.requests"
SCHEDULE_TIME = "foundry.spark.scheduler.schedule.time"
RECONCILIATION_TIME = "foundry.spark.scheduler.reconciliation.time"
WAIT_TIME = "foundry.spark.scheduler.wait.time"
RETRY_TIME = "foundry.spark.scheduler.retry.time"
CROSS_AZ_TRAFFIC = "foundry.spark.scheduler.az.cross.traffic"
TOTAL_TRAFFIC = "foundry.spark.scheduler.total.traffic"
APP_ZONES_COUNT = "foundry.spark.scheduler.application.zones.count"
PACKING_EFFICIENCY = "foundry.spark.scheduler.packing.efficiency"
SINGLE_AZ_PACK_FAILURE = (
    "foundry.spark.scheduler.singleazdynamicallocationpackfailure.count"
)
COMPACTION_TIME = "foundry.spark.scheduler.softreservation.compaction.time"

TAG_ROLE = "sparkrole"
TAG_OUTCOME = "outcome"
TAG_INSTANCE_GROUP = "instance-group"
TAG_DIMENSION = "dimension"
TAG_FUNCTION = "function"


class SchedulerMetrics:
    """Request-path metrics (ScheduleTimer, metrics.go:149-204 + cross-AZ
    reporter metrics.go:206-254 + packing efficiency, binpack.go:25-64)."""

    def __init__(
        self,
        registry: MetricRegistry | None = None,
        instance_group_label: str = "instance-group",
        clock=time.time,
    ):
        self.registry = registry or MetricRegistry()
        self._label = instance_group_label
        self._clock = clock
        # First-failure timestamps per pod, for wait/retry times
        # (metrics.go:184-204): wait = now - pod creation; retry = now -
        # first failed attempt. Entries are dropped on success or by
        # `cleanup()` (pods deleted without ever succeeding would otherwise
        # accumulate forever).
        self._first_failure: dict[tuple[str, str], float] = {}
        self._first_failure_max_age_s = 6 * 3600.0
        # Request threads and the reporter tick both touch _first_failure.
        self._ff_lock = threading.Lock()

    def _group(self, pod) -> str:
        return find_instance_group(pod, self._label) or ""

    # ------------------------------------------------------------- extender

    def mark_schedule_outcome(self, pod, role: str, outcome: str, elapsed_s: float):
        tags = {
            TAG_ROLE: role,
            TAG_OUTCOME: outcome,
            TAG_INSTANCE_GROUP: self._group(pod),
        }
        self.registry.counter(REQUEST_COUNTER, **tags).inc()
        self.registry.histogram(SCHEDULE_TIME, **tags).update(elapsed_s)
        now = self._clock()
        self.registry.histogram(WAIT_TIME, **tags).update(
            max(now - pod.creation_timestamp, 0.0)
        )
        with self._ff_lock:
            first = self._first_failure.get(pod.key)
            if outcome.startswith("success"):
                self._first_failure.pop(pod.key, None)
        if first is not None:
            self.registry.histogram(RETRY_TIME, **tags).update(max(now - first, 0.0))

    def mark_failed_scheduling_attempt(self, pod, outcome: str):
        with self._ff_lock:
            self._first_failure.setdefault(pod.key, self._clock())

    def forget_pod(self, pod) -> None:
        """Pod deleted without ever scheduling — drop its retry state."""
        with self._ff_lock:
            self._first_failure.pop(pod.key, None)

    def report_once(self) -> None:
        """Periodic eviction of abandoned retry state (ReporterRunner tick)."""
        cutoff = self._clock() - self._first_failure_max_age_s
        with self._ff_lock:
            stale = [k for k, t in self._first_failure.items() if t <= cutoff]
            for k in stale:
                del self._first_failure[k]

    def mark_reconciliation_finished(self, elapsed_s: float, instance_group: str = ""):
        self.registry.histogram(
            RECONCILIATION_TIME, **{TAG_INSTANCE_GROUP: instance_group}
        ).update(elapsed_s)

    def mark_compaction(self, elapsed_s: float):
        self.registry.histogram(COMPACTION_TIME).update(elapsed_s)

    def mark_single_az_dynamic_allocation_pack_failure(self, zone: str):
        self.registry.counter(SINGLE_AZ_PACK_FAILURE, zone=zone).inc()

    # -------------------------------------------------------------- packing

    def report_packing_efficiency(self, binpacker_name: str, packing):
        """Avg packing efficiency per dimension (metrics/binpack.go:37-64)."""
        for dim, value in (
            ("CPU", packing.efficiency_cpu),
            ("Memory", packing.efficiency_memory),
            ("GPU", packing.efficiency_gpu),
            ("Max", packing.efficiency_max),
        ):
            self.registry.histogram(
                PACKING_EFFICIENCY,
                **{TAG_FUNCTION: binpacker_name, TAG_DIMENSION: dim},
            ).update(value)

    def report_cross_zone(self, driver_node: str, executor_nodes, nodes):
        """Cross-AZ pod pairs for one app (metrics.go:206-254): pods paired
        across different zones / total pairs, plus distinct-zone count."""
        zone_of = {n.name: n.zone for n in nodes}
        placements = [driver_node] + list(executor_nodes)
        per_zone: dict[str, int] = {}
        for name in placements:
            z = zone_of.get(name)
            if z is None:
                return  # node vanished; skip like the reference's error path
            per_zone[z] = per_zone.get(z, 0) + 1
        total = len(placements)
        total_pairs = total * (total - 1) // 2
        same_pairs = sum(c * (c - 1) // 2 for c in per_zone.values())
        self.registry.counter(CROSS_AZ_TRAFFIC).inc(total_pairs - same_pairs)
        self.registry.counter(TOTAL_TRAFFIC).inc(total_pairs)
        self.registry.histogram(APP_ZONES_COUNT).update(len(per_zone))
