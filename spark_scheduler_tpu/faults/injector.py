"""FaultPlan / FaultInjector — deterministic fault schedules over named
surfaces.

Before this module the repo had exactly one injection point (the
backend's `fault_injector` lambda, store/backend.py) and one latency shim
(testing/rtt_shim.py), each hand-wired per test. The injector unifies
them: a PLAN is a seed plus a list of SPECS, each spec a (surface
pattern, trigger, action) triple; the injector instantiates one seeded
RNG stream PER SPEC, so the schedule of fired faults is a pure function
of (plan, sequence of fire() calls) — the same seed against the same
workload fires the same faults in the same places, which is what makes
chaos soaks replayable (same seed => same schedule => same verdicts).

Named surfaces (dot-paths; specs match with fnmatch patterns):

  backend.<kind>.<verb>   every ClusterBackend mutation (create/update/
                          delete per kind) — via backend_hook(), the same
                          seam the ad-hoc lambda used
  kube.write.<verb>       the async write-back client draining a request
  device.h2d|dispatch|d2h the solver's device boundaries — via
                          device_shim(), composing with SimulatedRTT
  lease.read|write        the HA lease store — via FaultyLeaseStore
  wal.<op>.<kind>         the durable backend's log (op: append|fsync;
                          kind: the record's, `crd` for the registry) —
                          via wal_hook()

Actions: "error" (raise; DeviceFaultError on device.* so the solver's
slot classifier quarantines), "latency" (sleep latency_ms), "partition"
(a contiguous window of matching events all error — a dead apiserver /
dropped tunnel, not a blip).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import random
import threading
import time
from typing import Callable, Optional, Sequence

from spark_scheduler_tpu.faults.errors import DeviceFaultError, InjectedFault


@dataclasses.dataclass
class FaultSpec:
    """One (surface, trigger, action). Triggers compose as: `limit` caps
    total fires; then `partition` (start/length window over this spec's
    MATCHING-event index), `at` (explicit indices), `every` (every Nth),
    or `p` (per-event coin from the spec's seeded stream) — first
    configured wins, checked in that order."""

    surface: str  # fnmatch pattern, e.g. "backend.resourcereservations.*"
    mode: str = "error"  # error | latency | partition
    p: Optional[float] = None
    at: Optional[Sequence[int]] = None
    every: Optional[int] = None
    start: int = 0  # partition window start (matching-event index)
    length: int = 0  # partition window length (0 = open-ended)
    limit: Optional[int] = None
    latency_ms: float = 0.0
    error: Optional[Callable[[], Exception]] = None
    name: str = ""

    def label(self, idx: int) -> str:
        return self.name or f"{self.surface}#{idx}"


@dataclasses.dataclass
class FaultPlan:
    """A seed + specs. Loadable from plain dicts (the chaos-matrix CI leg
    and bench arms define plans as literals)."""

    seed: int
    specs: list[FaultSpec] = dataclasses.field(default_factory=list)
    name: str = ""

    @classmethod
    def from_dict(cls, raw: dict) -> "FaultPlan":
        return cls(
            seed=int(raw.get("seed", 0)),
            name=str(raw.get("name", "")),
            specs=[
                FaultSpec(**{k.replace("-", "_"): v for k, v in s.items()})
                for s in raw.get("specs", [])
            ],
        )


class FaultInjector:
    """Instantiated from a plan; `fire(surface)` is the single hot-path
    entry every adapter funnels into. Thread-safe (device shims fire from
    pool workers while backend hooks fire from request threads)."""

    def __init__(
        self,
        plan: FaultPlan,
        sleep: Callable[[float], None] = time.sleep,
        on_fire: Optional[Callable[[str, str], None]] = None,
    ):
        self.plan = plan
        self._sleep = sleep
        # Telemetry seam: fn(surface, action) per fired fault (see
        # RetryTelemetry.fault_hook — foundry.spark.scheduler.faults.
        # injected). Called outside the injector lock.
        self.on_fire = on_fire
        self._lock = threading.Lock()
        self._rngs = [
            random.Random(f"{plan.seed}:{i}") for i in range(len(plan.specs))
        ]
        self._match_counts = [0] * len(plan.specs)
        self._fired_counts = [0] * len(plan.specs)
        self._seq = 0
        self.counts: dict[str, int] = {}  # events seen per surface
        self.fired: dict[str, int] = {}  # faults fired per surface
        # (seq, surface, spec label, action) — the deterministic schedule
        # replay tests compare.
        self.log: list[tuple[int, str, str, str]] = []
        # Installed-seam bookkeeping for uninstall().
        self._installed_backends: list = []
        self._installed_clients: list = []
        self._installed_wals: list = []
        self._device_prior = None
        self._device_installed = False

    # -- core ---------------------------------------------------------------

    def _decide(self, i: int, spec: FaultSpec) -> bool:
        idx = self._match_counts[i]
        self._match_counts[i] += 1
        if spec.limit is not None and self._fired_counts[i] >= spec.limit:
            return False
        if spec.mode == "partition":
            if idx < spec.start:
                return False
            return spec.length <= 0 or idx < spec.start + spec.length
        if spec.at is not None:
            return idx in spec.at
        if spec.every is not None:
            return spec.every > 0 and idx % spec.every == 0
        if spec.p is not None:
            return self._rngs[i].random() < spec.p
        return True  # unconditional (one-shot specs pair this with limit=1)

    def fire(self, surface: str) -> None:
        """Count one event on `surface`; sleep and/or raise per the plan.
        Latency faults sleep OUTSIDE the lock (a slow apiserver must not
        serialize unrelated surfaces through the injector)."""
        sleep_ms = 0.0
        raise_exc: Exception | None = None
        fired: list[str] = []
        with self._lock:
            self.counts[surface] = self.counts.get(surface, 0) + 1
            for i, spec in enumerate(self.plan.specs):
                if not fnmatch.fnmatch(surface, spec.surface):
                    continue
                if not self._decide(i, spec):
                    continue
                self._fired_counts[i] += 1
                self.fired[surface] = self.fired.get(surface, 0) + 1
                self._seq += 1
                action = "latency" if spec.mode == "latency" else "error"
                self.log.append((self._seq, surface, spec.label(i), action))
                fired.append(action)
                if spec.mode == "latency":
                    sleep_ms += spec.latency_ms
                    continue
                if spec.error is not None:
                    raise_exc = spec.error()
                elif surface.startswith("device."):
                    raise_exc = DeviceFaultError(surface)
                else:
                    raise_exc = InjectedFault(surface)
                break  # first erroring spec wins
        if self.on_fire is not None:
            for action in fired:
                self.on_fire(surface, action)
        if sleep_ms > 0:
            self._sleep(sleep_ms / 1e3)
        if raise_exc is not None:
            raise raise_exc

    def schedule(self) -> tuple:
        """The fired-fault schedule as a hashable value (replay tests pin
        same seed => same schedule)."""
        with self._lock:
            return tuple(self.log)

    def stats(self) -> dict:
        with self._lock:
            return {
                "plan": self.plan.name,
                "seed": self.plan.seed,
                "events": dict(self.counts),
                "fired": dict(self.fired),
            }

    # -- adapters -----------------------------------------------------------

    def backend_hook(self):
        """A `backend.fault_injector`-compatible fn(kind, verb, obj):
        latency faults sleep inline and return None; error faults RETURN
        the exception (the backend raises it inside its mutation lock) —
        the exact contract of the ad-hoc hook this subsumes."""

        def hook(kind, verb, obj):
            try:
                self.fire(f"backend.{kind}.{verb}")
            except Exception as exc:
                return exc
            return None

        return hook

    def install_backend(self, backend) -> None:
        # Remember the hook we displaced so nested injectors compose:
        # e.g. the soak's one-shot write-fault op installs its own
        # injector INSIDE a chaos-matrix run and must hand the seam back.
        self._installed_backends.append(
            (backend, getattr(backend, "fault_injector", None))
        )
        backend.fault_injector = self.backend_hook()

    def async_client_hook(self):
        """fn(request) for AsyncClient.fault_hook: fires on every drained
        write-back request BEFORE it reaches the backend (the kube client
        failing, not the apiserver) — raising routes into the client's
        RetryPolicy ladder."""

        def hook(req) -> None:
            self.fire(f"kube.write.{req.type.value}")

        return hook

    def install_async_client(self, client) -> None:
        self._installed_clients.append(
            (client, getattr(client, "fault_hook", None))
        )
        client.fault_hook = self.async_client_hook()

    def device_shim(self, inner=None):
        """A core.solver.set_device_shim-compatible callable: fires
        device.<kind> then delegates to `inner` (e.g. a SimulatedRTT) —
        fault injection and RTT simulation compose at one seam."""

        def shim(kind: str) -> None:
            self.fire(f"device.{kind}")
            if inner is not None:
                inner(kind)

        return shim

    def install_device(self, inner=None) -> None:
        from spark_scheduler_tpu.core import solver as _solver

        if not self._device_installed:
            self._device_prior = _solver._DEVICE_SHIM
            self._device_installed = True
        _solver.set_device_shim(
            self.device_shim(inner if inner is not None else self._device_prior)
        )

    def lease_store(self, store) -> "FaultyLeaseStore":
        return FaultyLeaseStore(store, self)

    def wal_hook(self):
        """fn(op, record=None) for DurableBackend.wal_fault_hook: op is
        "append" or "fsync"; raising makes the commit fail exactly where
        a full disk or torn fsync would. The surface is kind-granular —
        `wal.<op>.<kind>` (`crd` for registry records) — so a plan can
        fault reservation appends without also failing every pod/node
        bookkeeping write (match broadly with `wal.append.*`)."""

        def hook(op: str, record=None) -> None:
            kind = (record or {}).get("kind", "crd")
            self.fire(f"wal.{op}.{kind}")

        return hook

    def install_wal(self, durable_backend) -> None:
        self._installed_wals.append(
            (durable_backend, getattr(durable_backend, "wal_fault_hook", None))
        )
        durable_backend.wal_fault_hook = self.wal_hook()

    def uninstall(self) -> None:
        for b, prior in self._installed_backends:
            b.fault_injector = prior
        self._installed_backends.clear()
        for c, prior in self._installed_clients:
            c.fault_hook = prior
        self._installed_clients.clear()
        for w, prior in self._installed_wals:
            w.wal_fault_hook = prior
        self._installed_wals.clear()
        if self._device_installed:
            from spark_scheduler_tpu.core import solver as _solver

            _solver.set_device_shim(self._device_prior)
            self._device_installed = False
            self._device_prior = None

    def __enter__(self) -> "FaultInjector":
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()


class FaultyLeaseStore:
    """Lease-store wrapper firing lease.read / lease.write around the
    delegate — the lease surface of the chaos matrix. Duck-typed to the
    BackendLeaseStore/FileLeaseStore surface (read / compare_and_swap)."""

    def __init__(self, delegate, injector: FaultInjector):
        self._delegate = delegate
        self._injector = injector

    def read(self):
        self._injector.fire("lease.read")
        return self._delegate.read()

    def compare_and_swap(self, expect, record) -> bool:
        self._injector.fire("lease.write")
        return self._delegate.compare_and_swap(expect, record)
