"""Exception taxonomy of the fault-tolerance subsystem.

The split that matters operationally is SLOT-FATAL vs PROGRAMMING ERROR:
a device slot whose solve died of a tunnel drop / XlaRuntimeError should
be quarantined and its window re-dispatched on a survivor, while a
TypeError in the packing code must propagate loudly — retrying it on
another slot would fail identically and hide the bug.
`classify_slot_failure` draws that line in one place.
"""

from __future__ import annotations


class InjectedFault(RuntimeError):
    """An error the FaultInjector raised on purpose. Carries the surface
    it fired on so assertions can tell injected failures from real ones."""

    def __init__(self, surface: str, message: str = ""):
        super().__init__(message or f"injected fault on {surface}")
        self.surface = surface


class DeviceFaultError(InjectedFault):
    """Injected DEVICE-surface fault (h2d / dispatch / d2h): classified
    slot-fatal, exactly like a real tunnel drop or XlaRuntimeError."""


class AllSlotsQuarantinedError(RuntimeError):
    """Every device slot of the pool is quarantined: no device can serve.
    The extender answers per the `server.degraded-mode` policy (greedy
    host fallback or 503+Retry-After)."""


class DegradedUnavailableError(RuntimeError):
    """No device can serve and the degraded-mode policy is "shed": the
    request must be answered 503 with Retry-After instead of a decision."""

    def __init__(self, reason: str, retry_after_s: float = 5.0):
        super().__init__(reason)
        self.retry_after_s = retry_after_s


class RetryDeadlineExceeded(RuntimeError):
    """RetryPolicy.call gave up: the overall deadline elapsed (or the
    attempt budget ran out with a deadline configured). `__cause__` is the
    last attempt's real exception."""


class AttemptTimeoutError(TimeoutError):
    """One attempt exceeded the policy's per-attempt timeout. The attempt
    thread is abandoned (there is no portable way to cancel it); the
    caller retries or gives up per the policy."""


class BreakerOpenError(RuntimeError):
    """A call was refused because the circuit breaker is open (the
    downstream is failing; probing is rationed to the half-open window)."""


# Exception type names that mean "the DEVICE (or its transport) died", as
# opposed to "the program is wrong". Matched by name so the classifier
# needs no jaxlib import (the concrete class moved modules across jax
# releases).
_SLOT_FATAL_TYPE_NAMES = frozenset(
    {"XlaRuntimeError", "ChannelError", "RpcError"}
)


def classify_slot_failure(exc: BaseException) -> bool:
    """True when `exc` indicates the device slot (hardware, runtime, or
    tunnel) failed and the work should be retried on a surviving slot;
    False for programming errors that must propagate."""
    if isinstance(exc, DeviceFaultError):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in _SLOT_FATAL_TYPE_NAMES:
            return True
    return False
