"""Fault-tolerance subsystem (ISSUE 9).

Three pieces, deliberately small and dependency-free so every layer of the
scheduler can import them:

  errors     the exception taxonomy: InjectedFault / DeviceFaultError
             (slot-fatal), AllSlotsQuarantinedError, DegradedUnavailable,
             RetryDeadlineExceeded / AttemptTimeoutError / BreakerOpenError.
  retry      RetryPolicy (exponential backoff + full jitter + per-attempt
             timeout + overall deadline) and CircuitBreaker
             (closed -> open -> half-open -> closed) — the one retry ladder
             the kube async client, backend write-back, lease renewals,
             reflector relists, and the autoscaler loop all ride.
  injector   FaultPlan / FaultSpec / FaultInjector — seeded, deterministic
             schedules of latency/error/partition faults over NAMED
             surfaces (backend verbs, kube async-client writes, device
             h2d/dispatch/d2h, lease store, WAL append/fsync). Subsumes
             the ad-hoc `backend.fault_injector` lambda and composes with
             the rtt_shim at the device seam.
  degraded   DegradedModeController — the `server.degraded-mode` policy
             (host-side greedy fallback vs 503+Retry-After shedding) the
             extender consults when every device slot is quarantined.
"""

from spark_scheduler_tpu.faults.errors import (
    AllSlotsQuarantinedError,
    AttemptTimeoutError,
    BreakerOpenError,
    DegradedUnavailableError,
    DeviceFaultError,
    InjectedFault,
    RetryDeadlineExceeded,
    classify_slot_failure,
)
from spark_scheduler_tpu.faults.retry import CircuitBreaker, RetryPolicy
from spark_scheduler_tpu.faults.injector import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
    FaultyLeaseStore,
)
from spark_scheduler_tpu.faults.degraded import (
    DEGRADED_GREEDY,
    DEGRADED_SHED,
    DegradedModeController,
)

__all__ = [
    "AllSlotsQuarantinedError",
    "AttemptTimeoutError",
    "BreakerOpenError",
    "CircuitBreaker",
    "DEGRADED_GREEDY",
    "DEGRADED_SHED",
    "DegradedModeController",
    "DegradedUnavailableError",
    "DeviceFaultError",
    "FaultInjector",
    "FaultPlan",
    "FaultSpec",
    "FaultyLeaseStore",
    "InjectedFault",
    "RetryDeadlineExceeded",
    "RetryPolicy",
    "classify_slot_failure",
]
