"""DegradedModeController — what the scheduler does when no device can
serve.

The reference has no analogue (its "device" is a Go for-loop); here a
tunneled-TPU deployment can lose every pool slot at once (tunnel cut,
driver OOM) and a millions-of-users front-end needs a defined answer:

  greedy  keep serving: the extender solves on the HOST via the promoted
          greedy oracle (core/greedy.py — slot-for-slot the kernels'
          semantics, just O(nodes) Python instead of one device program).
          Readiness stays 200 but reports degraded; throughput drops,
          correctness doesn't.
  shed    answer /predicates 503 with Retry-After (the kube-scheduler
          extender client retries); readiness flips 503 so load balancers
          drain the replica while probes keep watching it.

Either way /debug/state and the telemetry gauge reflect the state, and
the controller auto-clears as soon as a quarantined slot's probe
reinstates it.
"""

from __future__ import annotations

import threading
import time

DEGRADED_GREEDY = "greedy"
DEGRADED_SHED = "shed"

DEGRADED_POLICIES = (DEGRADED_GREEDY, DEGRADED_SHED)


class DegradedModeController:
    def __init__(
        self,
        policy: str = DEGRADED_GREEDY,
        retry_after_s: float = 5.0,
        clock=time.time,
        on_change=None,
    ):
        if policy not in DEGRADED_POLICIES:
            raise ValueError(
                f"degraded-mode policy {policy!r}: expected one of "
                f"{DEGRADED_POLICIES}"
            )
        self.policy = policy
        self.retry_after_s = retry_after_s
        self._clock = clock
        self._on_change = on_change  # fn(active: bool) — telemetry hook
        self._lock = threading.Lock()
        self.active = False
        self.reason = ""
        self.since = 0.0
        self.engagements = 0
        self.fallback_decisions = 0
        self.shed_requests = 0

    def engage(self, reason: str) -> None:
        with self._lock:
            if not self.active:
                self.active = True
                self.since = self._clock()
                self.engagements += 1
                changed = True
            else:
                changed = False
            self.reason = reason
        if changed and self._on_change is not None:
            self._on_change(True)

    def clear(self) -> None:
        with self._lock:
            changed = self.active
            self.active = False
            self.reason = ""
        if changed and self._on_change is not None:
            self._on_change(False)

    def on_fallback_decision(self, n: int = 1) -> None:
        with self._lock:
            self.fallback_decisions += n

    def on_shed(self, n: int = 1) -> None:
        with self._lock:
            self.shed_requests += n

    @property
    def sheds(self) -> bool:
        return self.policy == DEGRADED_SHED

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "policy": self.policy,
                "active": self.active,
                "reason": self.reason,
                "since": self.since if self.active else None,
                "engagements": self.engagements,
                "fallback_decisions": self.fallback_decisions,
                "shed_requests": self.shed_requests,
                "retry_after_s": self.retry_after_s,
            }
