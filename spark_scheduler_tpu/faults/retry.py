"""RetryPolicy + CircuitBreaker — the one retry ladder of the scheduler.

The reference's only retry knob is a bare count (`async-client-retry-count`,
config.go:72-77); every consumer here used either that count with zero
delay or a fixed sleep. RetryPolicy replaces both with the standard shape:
exponential backoff, FULL jitter (delay ~ U[0, min(cap, base*mult^n)] — the
AWS-architecture result that full jitter minimizes contention on a
recovering dependency), an optional per-attempt timeout, and an optional
overall deadline. CircuitBreaker adds the closed/open/half-open discipline
so a down dependency is probed, not hammered.

Both are clock-injectable and rng-injectable: the chaos-matrix soak runs
them deterministically, and the unit tests pin the exact backoff sequence
and jitter bounds.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Optional

from spark_scheduler_tpu.faults.errors import (
    AttemptTimeoutError,
    BreakerOpenError,
    RetryDeadlineExceeded,
)


@dataclasses.dataclass
class RetryPolicy:
    """`max_attempts` counts TOTAL tries (1 = no retry); None = unbounded
    (loop-style consumers like the reflector, which retry forever with
    capped backoff). `jitter="full"` draws each delay uniformly from
    [0, backoff(attempt)]; "none" sleeps the deterministic backoff."""

    max_attempts: Optional[int] = 5
    base_delay_s: float = 0.1
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: str = "full"
    attempt_timeout_s: Optional[float] = None
    deadline_s: Optional[float] = None

    def backoff(self, attempt: int) -> float:
        """Deterministic (pre-jitter) delay after the `attempt`-th failure
        (0-based): base * multiplier^attempt, capped at max_delay_s."""
        return min(
            self.max_delay_s,
            self.base_delay_s * (self.multiplier ** max(0, attempt)),
        )

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        cap = self.backoff(attempt)
        if self.jitter == "full":
            return (rng or random).uniform(0.0, cap)
        return cap

    def replace(self, **kw) -> "RetryPolicy":
        return dataclasses.replace(self, **kw)

    # -- execution ----------------------------------------------------------

    def call(
        self,
        fn: Callable,
        *,
        retry_on: tuple = (Exception,),
        breaker: "CircuitBreaker | None" = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: random.Random | None = None,
        on_retry: Callable[[int, BaseException, float], None] | None = None,
    ):
        """Run `fn()` under this policy. Per-attempt timeout (when set)
        runs the attempt on a daemon thread and abandons it on timeout;
        the overall deadline aborts BETWEEN attempts (it never interrupts
        one) with RetryDeadlineExceeded chaining the last real error.
        `breaker`, when given, gates every attempt (BreakerOpenError when
        refused without a half-open probe slot) and is fed the outcome."""
        start = clock()
        attempt = 0
        while True:
            if breaker is not None and not breaker.allow():
                raise BreakerOpenError(breaker.name or "circuit open")
            try:
                if self.attempt_timeout_s is not None:
                    result = _run_with_timeout(fn, self.attempt_timeout_s)
                else:
                    result = fn()
            except retry_on as exc:
                if breaker is not None:
                    breaker.on_failure()
                attempt += 1
                out_of_attempts = (
                    self.max_attempts is not None
                    and attempt >= self.max_attempts
                )
                if out_of_attempts:
                    raise
                pause = self.delay(attempt - 1, rng)
                if self.deadline_s is not None and (
                    clock() - start + pause > self.deadline_s
                ):
                    raise RetryDeadlineExceeded(
                        f"retry deadline {self.deadline_s}s exceeded after "
                        f"{attempt} attempt(s)"
                    ) from exc
                if on_retry is not None:
                    on_retry(attempt, exc, pause)
                if pause > 0:
                    sleep(pause)
                continue
            if breaker is not None:
                breaker.on_success()
            return result


def _run_with_timeout(fn: Callable, timeout_s: float):
    """Run fn on a daemon thread, abandon it on timeout. The abandoned
    thread keeps running to completion (documented caveat — Python offers
    no safe cross-thread cancel); its result is discarded."""
    from concurrent.futures import Future, TimeoutError as _FutTimeout

    fut: Future = Future()

    def run():
        if not fut.set_running_or_notify_cancel():
            return
        try:
            fut.set_result(fn())
        except BaseException as exc:
            fut.set_exception(exc)

    t = threading.Thread(target=run, daemon=True, name="retry-attempt")
    t.start()
    try:
        return fut.result(timeout=timeout_s)
    except _FutTimeout:
        raise AttemptTimeoutError(
            f"attempt exceeded {timeout_s}s (thread abandoned)"
        ) from None


CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Classic three-state breaker. CLOSED counts consecutive failures;
    at `failure_threshold` it OPENS and refuses calls for
    `reset_timeout_s`; the first allow() after the window flips to
    HALF_OPEN and admits exactly one probe — success closes, failure
    re-opens (re-arming the window). Thread-safe; `on_transition(old,
    new)` is the telemetry hook."""

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[str, str], None] | None = None,
        name: str = "",
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self.name = name
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_out = False
        self.opens = 0  # lifetime open transitions (telemetry)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, new: str) -> None:
        old, self._state = self._state, new
        if new == OPEN:
            self.opens += 1
            self._opened_at = self._clock()
        if old != new and self._on_transition is not None:
            self._on_transition(old, new)

    def allow(self) -> bool:
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    self._transition(HALF_OPEN)
                    self._probe_out = True
                    return True
                return False
            # HALF_OPEN: one probe at a time.
            if not self._probe_out:
                self._probe_out = True
                return True
            return False

    def on_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_out = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def on_failure(self) -> None:
        with self._lock:
            self._probe_out = False
            if self._state == HALF_OPEN:
                self._transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._transition(OPEN)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "state": self._state,
                "failures": self._failures,
                "opens": self.opens,
            }
