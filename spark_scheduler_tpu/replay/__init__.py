"""Durable decision traces, deterministic replay, what-if simulation
(ISSUE 17); batched multi-arm sweeps (ISSUE 18).

  trace       versioned JSONL codec: TraceWriter (the FlightRecorder's
              journaling sink) + TraceReader (torn-tail tolerant).
  engine      backend-free deterministic replay (`replay_trace`) and the
              config what-if differ (`what_if`), factored into per-arm
              `ReplayLane`s a multi-lane driver can interleave.
  sweep       the grid driver: one trace, M config arms, lockstep lanes
              over one shared host build with stacked cross-arm window
              solves (`run_sweep` / `SweepReport` / `grid_arms`).
  generators  seed-deterministic synthetic workloads (diurnal / bursty /
              churn) emitting the same trace format.

CLI: `python -m spark_scheduler_tpu.replay --help`.
"""

from spark_scheduler_tpu.replay.engine import (
    ReplayMismatchError,
    ReplayReport,
    replay_trace,
    what_if,
)
from spark_scheduler_tpu.replay.generators import GENERATORS, generate
from spark_scheduler_tpu.replay.sweep import (
    SweepReport,
    grid_arms,
    last_sweep_telemetry,
    run_sweep,
)
from spark_scheduler_tpu.replay.trace import (
    TRACE_VERSION,
    TraceReader,
    TraceWriter,
    config_fingerprint,
    config_from_fingerprint,
    config_hash,
)

__all__ = [
    "GENERATORS",
    "ReplayMismatchError",
    "ReplayReport",
    "SweepReport",
    "TRACE_VERSION",
    "TraceReader",
    "TraceWriter",
    "config_fingerprint",
    "config_from_fingerprint",
    "config_hash",
    "generate",
    "grid_arms",
    "last_sweep_telemetry",
    "replay_trace",
    "run_sweep",
    "what_if",
]
