"""Durable decision traces, deterministic replay, what-if simulation
(ISSUE 17).

  trace       versioned JSONL codec: TraceWriter (the FlightRecorder's
              journaling sink) + TraceReader (torn-tail tolerant).
  engine      backend-free deterministic replay (`replay_trace`) and the
              config what-if differ (`what_if`).
  generators  seed-deterministic synthetic workloads (diurnal / bursty /
              churn) emitting the same trace format.

CLI: `python -m spark_scheduler_tpu.replay --help`.
"""

from spark_scheduler_tpu.replay.engine import (
    ReplayMismatchError,
    ReplayReport,
    replay_trace,
    what_if,
)
from spark_scheduler_tpu.replay.generators import GENERATORS, generate
from spark_scheduler_tpu.replay.trace import (
    TRACE_VERSION,
    TraceReader,
    TraceWriter,
    config_fingerprint,
    config_from_fingerprint,
    config_hash,
)

__all__ = [
    "GENERATORS",
    "ReplayMismatchError",
    "ReplayReport",
    "TRACE_VERSION",
    "TraceReader",
    "TraceWriter",
    "config_fingerprint",
    "config_from_fingerprint",
    "config_hash",
    "generate",
    "replay_trace",
    "what_if",
]
