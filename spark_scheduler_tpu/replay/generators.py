"""Synthetic workload generators emitting replayable traces (ISSUE 17 d).

Each generator writes the SAME versioned trace format the live TraceWriter
captures, from nothing but a seed — so `python -m spark_scheduler_tpu.replay
generate diurnal --seed 7` followed by `run` (replay with binding) yields a
fully captured trace, and the whole pipeline is exercisable without a
cluster, a server, or even the soak harness.

Determinism contract: same (kind, seed, sizing) → byte-identical output.
Everything varying is drawn from one `np.random.default_rng(seed)`; the
trace clock is a simulated epoch clock starting at T0 (no wall time
anywhere, header included); pod UIDs are explicit (`uid-<app>-<pod>`), so
no uuid4 sneaks in via `Pod.__post_init__`.

Scenarios
---------
  diurnal   sinusoidal arrival rate over a simulated day — static-allocation
            apps pile up at peak, drain at trough (teardown watermark).
  bursty    multi-tenant: per-tenant instance groups, long quiet gaps
            punctuated by back-to-back submission bursts from one tenant.
  churn     dynamic-allocation apps under heavy executor churn: kills
            (pod deletes), replacement executor requests against the freed
            reservations, app teardowns, periodic reconciles.

Generated traces are *input-only*: predicate windows carry `bind: true`
and no `result` events — the replay engine completes each window
immediately and binds placements itself (run mode).
"""

from __future__ import annotations

import math

import numpy as np

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.core.sparkpods import (
    DA_MAX_EXECUTOR_COUNT,
    DA_MIN_EXECUTOR_COUNT,
    DRIVER_CPU,
    DRIVER_MEMORY,
    DYNAMIC_ALLOCATION_ENABLED,
    EXECUTOR_COUNT,
    EXECUTOR_CPU,
    EXECUTOR_MEMORY,
    ROLE_DRIVER,
    ROLE_EXECUTOR,
    SPARK_APP_ID_LABEL,
    SPARK_ROLE_LABEL,
    SPARK_SCHEDULER_NAME,
)
from spark_scheduler_tpu.models.kube import Container, Node, Pod, ZONE_LABEL
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.replay.trace import TraceWriter
from spark_scheduler_tpu.server.config import InstallConfig

INSTANCE_GROUP_LABEL = "resource_channel"
DEFAULT_GROUP = "batch-medium-priority"
T0 = 1_700_000_000.0  # simulated epoch origin — never wall time
NAMESPACE = "namespace"


def _pod(app_id, name, role, ts, group, annotations=None):
    return Pod(
        name=name,
        namespace=NAMESPACE,
        uid=f"uid-{name}",
        labels={SPARK_ROLE_LABEL: role, SPARK_APP_ID_LABEL: app_id},
        annotations=dict(annotations or {}),
        creation_timestamp=ts,
        scheduler_name=SPARK_SCHEDULER_NAME,
        node_selector={INSTANCE_GROUP_LABEL: group},
        containers=[Container(requests=Resources.from_quantities("1", "1Gi"))],
    )


class _App:
    __slots__ = ("app_id", "group", "pods", "next_exec", "annotations", "ts")

    def __init__(self, app_id, group, ts, annotations):
        self.app_id = app_id
        self.group = group
        self.ts = ts
        self.annotations = annotations
        self.pods: list[Pod] = []
        self.next_exec = 1


class _Sim:
    """Shared scenario plumbing: sim clock, node roster, app lifecycle."""

    def __init__(self, path, kind, seed, n_nodes, groups, binpack_algo):
        self.rng = np.random.default_rng(seed)
        self.t = T0
        self.writer = TraceWriter(
            path,
            clock=lambda: self.t,
            decisions=False,
            source=f"generator:{kind}",
        )
        config = InstallConfig(
            fifo=True,
            binpack_algo=binpack_algo,
            instance_group_label=INSTANCE_GROUP_LABEL,
            sync_writes=True,
        )
        self.writer.write_header(
            config,
            meta={
                "generator": kind,
                "seed": int(seed),
                "n_nodes": int(n_nodes),
                # replay is purely event-driven; don't let the simulated
                # multi-hour gaps trip the clock-based resync heuristic
                "resync_suppressed": True,
            },
        )
        self.nodes: list[str] = []
        zones = ("zone1", "zone2")
        for i in range(n_nodes):
            name = f"node-{i:04d}"
            self.writer.on_node_add(
                Node(
                    name=name,
                    allocatable=Resources.from_quantities(
                        "8", "8Gi", "1", round_up=False
                    ),
                    labels={
                        ZONE_LABEL: zones[i % len(zones)],
                        INSTANCE_GROUP_LABEL: groups[i % len(groups)],
                    },
                )
            )
            self.nodes.append(name)
        self.live: dict[str, _App] = {}

    def advance(self, dt) -> None:
        self.t += max(0.0, float(dt))

    def _window(self, pods) -> None:
        for p in pods:
            self.writer.on_pod_add(p)
        self.writer.on_predicate(
            [ExtenderArgs(pod=p, node_names=list(self.nodes)) for p in pods],
            mode="window",
            bind=True,
        )

    def submit(self, app_id, n_exec, group=DEFAULT_GROUP, dynamic=False,
               max_exec=None) -> _App:
        if dynamic:
            ann = {
                DRIVER_CPU: "1",
                DRIVER_MEMORY: "1Gi",
                EXECUTOR_CPU: "1",
                EXECUTOR_MEMORY: "1Gi",
                DYNAMIC_ALLOCATION_ENABLED: "true",
                DA_MIN_EXECUTOR_COUNT: str(n_exec),
                DA_MAX_EXECUTOR_COUNT: str(max_exec or n_exec),
            }
        else:
            ann = {
                DRIVER_CPU: "1",
                DRIVER_MEMORY: "1Gi",
                EXECUTOR_CPU: "1",
                EXECUTOR_MEMORY: "1Gi",
                EXECUTOR_COUNT: str(n_exec),
            }
        app = _App(app_id, group, self.t, ann)
        driver = _pod(app_id, f"{app_id}-driver", ROLE_DRIVER, app.ts, group, ann)
        app.pods.append(driver)
        self._window([driver])
        count = n_exec if not dynamic else (max_exec or n_exec)
        batch: list[Pod] = []
        for _ in range(count):
            e = self.new_executor(app)
            batch.append(e)
            if len(batch) == 6:
                self._window(batch)
                batch = []
        if batch:
            self._window(batch)
        self.live[app_id] = app
        return app

    def new_executor(self, app: _App) -> Pod:
        e = _pod(
            app.app_id,
            f"{app.app_id}-exec-{app.next_exec}",
            ROLE_EXECUTOR,
            app.ts,
            app.group,
        )
        app.next_exec += 1
        app.pods.append(e)
        return e

    def kill_executor(self, app: _App) -> None:
        execs = [
            p for p in app.pods
            if p.labels.get(SPARK_ROLE_LABEL) == ROLE_EXECUTOR
        ]
        if not execs:
            return
        victim = execs[int(self.rng.integers(0, len(execs)))]
        app.pods.remove(victim)
        self.writer.on_pod_delete(victim)

    def teardown(self, app_id) -> None:
        app = self.live.pop(app_id, None)
        if app is None:
            return
        for p in app.pods:
            self.writer.on_pod_delete(p)
        self.writer.emit_rr_delete(NAMESPACE, app_id)

    def finish(self) -> dict:
        self.writer.emit_reconcile()
        stats = self.writer.stats()
        self.writer.close()
        return stats


def gen_diurnal(path, seed, n_nodes=24, apps=48,
                binpack_algo="single-az-tightly-pack") -> dict:
    sim = _Sim(path, "diurnal", seed, n_nodes, (DEFAULT_GROUP,), binpack_algo)
    order: list[str] = []
    for i in range(apps):
        day_frac = ((sim.t - T0) % 86400.0) / 86400.0
        # peak (midday) ~9x trough arrival rate
        rate = 0.1 + 0.9 * (0.5 - 0.5 * math.cos(2 * math.pi * day_frac))
        sim.advance(sim.rng.exponential(400.0 / rate))
        app_id = f"diurnal-{i:04d}"
        sim.submit(app_id, int(sim.rng.integers(2, 7)))
        order.append(app_id)
        # drain the backlog: completed apps leave as new ones arrive
        while len(sim.live) > 10:
            sim.advance(sim.rng.exponential(30.0))
            sim.teardown(order.pop(0))
    for app_id in order[: len(order) // 2]:
        sim.advance(sim.rng.exponential(60.0))
        sim.teardown(app_id)
    return sim.finish()


def gen_bursty(path, seed, n_nodes=24, bursts=10,
               binpack_algo="single-az-tightly-pack") -> dict:
    tenants = ("tenant-a", "tenant-b", "tenant-c")
    sim = _Sim(path, "bursty", seed, n_nodes, tenants, binpack_algo)
    n = 0
    order: list[str] = []
    for b in range(bursts):
        sim.advance(sim.rng.exponential(1800.0))  # quiet gap
        tenant = tenants[int(sim.rng.integers(0, len(tenants)))]
        for _ in range(int(sim.rng.integers(3, 8))):
            sim.advance(sim.rng.exponential(2.0))  # back-to-back
            app_id = f"{tenant}-{n:04d}"
            n += 1
            sim.submit(app_id, int(sim.rng.integers(1, 5)), group=tenant)
            order.append(app_id)
        while len(sim.live) > 12:
            sim.teardown(order.pop(0))
    return sim.finish()


def gen_churn(path, seed, n_nodes=16, steps=120,
              binpack_algo="single-az-tightly-pack") -> dict:
    sim = _Sim(path, "churn", seed, n_nodes, (DEFAULT_GROUP,), binpack_algo)
    n = 0
    for _ in range(steps):
        sim.advance(sim.rng.exponential(45.0))
        ids = sorted(sim.live)
        op = sim.rng.random()
        if op < 0.35 or not ids:
            app_id = f"churn-{n:04d}"
            n += 1
            lo = int(sim.rng.integers(1, 4))
            sim.submit(app_id, lo, dynamic=True,
                       max_exec=lo + int(sim.rng.integers(0, 4)))
        elif op < 0.70:
            app = sim.live[ids[int(sim.rng.integers(0, len(ids)))]]
            sim.kill_executor(app)
            if sim.rng.random() < 0.6:
                # dynamic allocation asks for a replacement executor
                sim.advance(sim.rng.exponential(5.0))
                sim._window([sim.new_executor(app)])
        elif op < 0.90:
            sim.teardown(ids[int(sim.rng.integers(0, len(ids)))])
        else:
            sim.writer.emit_reconcile()
    return sim.finish()


GENERATORS = {
    "diurnal": gen_diurnal,
    "bursty": gen_bursty,
    "churn": gen_churn,
}


def generate(kind: str, path: str, seed: int, **sizing) -> dict:
    try:
        fn = GENERATORS[kind]
    except KeyError:
        raise ValueError(
            f"unknown generator {kind!r}; have {sorted(GENERATORS)}"
        ) from None
    return fn(path, seed, **sizing)
